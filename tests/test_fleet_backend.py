"""The device-routed fleet backend (automerge_tpu.fleet.backend): drop-in
Backend-contract conformance, differential equivalence against the host
backend, promotion/fallback, device materialization, and sync interop.

Modeled on the reference's alternative-backend harness (test/wasm.js:27-36):
the same change streams go through the host backend and the fleet backend,
asserting identical patches, state, and serialization."""

import copy

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu import backend as host_backend
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend, FleetDoc

ACTORS = ['aa' * 16, 'bb' * 16, 'cc' * 16, '11' * 16]


def change_buf(actor, seq, start_op, ops, deps=(), time=0, message=''):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': time,
        'message': message, 'deps': sorted(deps), 'ops': ops,
    })


def fresh_pair():
    """A host backend handle and a fleet backend handle on a private fleet."""
    fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
    return host_backend.init(), fb.init(), fb


def apply_both(hb, gb, changes):
    hb2, hp = host_backend.apply_changes(hb, changes)
    gb2, gp = fleet_backend.apply_changes(gb, changes)
    assert hp == gp
    return hb2, gb2


class TestDifferential:
    def test_simple_sets_and_patches(self):
        hb, gb, _ = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'bird', 'value': 'magpie',
             'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'n', 'value': 7,
             'datatype': 'int', 'pred': []},
        ])
        hb, gb = apply_both(hb, gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'bird', 'value': 'wren',
             'pred': [f'1@{ACTORS[0]}']},
        ], deps=host_backend.get_heads(hb))
        hb, gb = apply_both(hb, gb, [c2])
        assert host_backend.get_patch(hb) == fleet_backend.get_patch(gb)
        assert gb['state'].materialize() == {'bird': 'wren', 'n': 7}

    def test_concurrent_conflict_sets(self):
        hb, gb, _ = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        c2 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 2,
             'datatype': 'int', 'pred': []}])
        hb, gb = apply_both(hb, gb, [c1, c2])
        hp = host_backend.get_patch(hb)
        assert set(hp['diffs']['props']['x'].keys()) == \
            {f'1@{ACTORS[0]}', f'1@{ACTORS[1]}'}
        assert hp == fleet_backend.get_patch(gb)
        # Lamport winner: equal counters, higher actor id wins
        assert gb['state'].materialize() == {'x': 2}

    def test_counter_accumulation(self):
        hb, gb, _ = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 10,
             'datatype': 'counter', 'pred': []}])
        hb, gb = apply_both(hb, gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': 4,
             'pred': [f'1@{ACTORS[0]}']}],
            deps=host_backend.get_heads(hb))
        c3 = change_buf(ACTORS[1], 1, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': -2,
             'pred': [f'1@{ACTORS[0]}']}])
        hb, gb = apply_both(hb, gb, [c2, c3])
        hp = host_backend.get_patch(hb)
        assert hp['diffs']['props']['c'][f'1@{ACTORS[0]}'] == \
            {'type': 'value', 'value': 12, 'datatype': 'counter'}
        assert hp == fleet_backend.get_patch(gb)
        assert gb['state'].materialize() == {'c': 12}

    def test_delete_and_empty_props(self):
        hb, gb, _ = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        hb, gb = apply_both(hb, gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{ACTORS[0]}']}], deps=host_backend.get_heads(hb))
        hb2, hp = host_backend.apply_changes(hb, [c2])
        gb2, gp = fleet_backend.apply_changes(gb, [c2])
        assert hp == gp
        assert hp['diffs']['props']['k'] == {}
        assert host_backend.get_patch(hb2) == fleet_backend.get_patch(gb2)
        assert gb2['state'].materialize() == {}

    def test_save_load_round_trip(self):
        hb, gb, fb = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 'x',
             'pred': []}])
        c2 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': True,
             'pred': []}])
        hb, gb = apply_both(hb, gb, [c1, c2])
        assert bytes(host_backend.save(hb)) == bytes(fleet_backend.save(gb))
        # Load the saved doc back through the fleet backend
        gb2 = fb.load(host_backend.save(hb))
        assert fleet_backend.get_patch(gb2) == host_backend.get_patch(hb)
        assert gb2['state'].is_fleet

    def test_queueing_missing_deps(self):
        hb, gb, _ = fresh_pair()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}], deps=[h1])
        hb, gb = apply_both(hb, gb, [c2])   # queued: dep missing
        assert fleet_backend.get_missing_deps(gb) == [h1]
        hb, gb = apply_both(hb, gb, [c1])   # both drain
        assert host_backend.get_patch(hb) == fleet_backend.get_patch(gb)
        assert gb['state'].materialize() == {'k': 2}

    def test_error_parity_and_rollback(self):
        for bad_ops, msg in [
            ([{'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
               'datatype': 'int', 'pred': [f'9@{ACTORS[1]}']}],
             'no matching operation for pred'),
            ([{'action': 'inc', 'obj': '_root', 'key': 'z', 'value': 1,
               'pred': []}], 'unknown counter'),
        ]:
            hb, gb, _ = fresh_pair()
            setup = change_buf(ACTORS[0], 1, 1, [
                {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
                 'datatype': 'int', 'pred': []}])
            hb, gb = apply_both(hb, gb, [setup])
            bad = change_buf(ACTORS[0], 2, 2, bad_ops,
                             deps=host_backend.get_heads(hb))
            with pytest.raises(ValueError, match=msg):
                host_backend.apply_changes(hb, [bad])
            hb2, gb2, _ = fresh_pair()
            hb2, gb2 = apply_both(hb2, gb2, [setup])
            with pytest.raises(ValueError, match=msg):
                fleet_backend.apply_changes(gb2, [bad])
            # Fleet state must be unchanged after the failed call
            assert fleet_backend.get_patch(gb2) == host_backend.get_patch(hb2)
            assert gb2['state'].materialize() == {'k': 1}

    def test_seq_gate_errors(self):
        _, gb, _ = fresh_pair()
        c = change_buf(ACTORS[0], 3, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        with pytest.raises(ValueError, match='Skipped sequence number'):
            fleet_backend.apply_changes(gb, [c])

    def test_randomized_differential(self):
        rng = np.random.default_rng(7)
        for trial in range(6):
            hb, gb, fb = fresh_pair()
            seqs = {a: 0 for a in ACTORS[:3]}
            ctrs = {a: 0 for a in ACTORS[:3]}
            visible = {}    # key -> set of opIds (tracked for pred choice)
            values = ['x', -5, 3.25, None, True, 1 << 40, 'yy']
            for step in range(30):
                actor = ACTORS[int(rng.integers(0, 3))]
                key = f'k{int(rng.integers(0, 5))}'
                seqs[actor] += 1
                ctr = max(ctrs.values()) + 1
                kind = rng.random()
                vis = sorted(visible.get(key, set()))
                if kind < 0.55 or not vis:
                    value = values[int(rng.integers(0, len(values)))] \
                        if rng.random() < 0.5 else int(rng.integers(0, 100))
                    pred = vis if rng.random() < 0.7 else []
                    op = {'action': 'set', 'obj': '_root', 'key': key,
                          'value': value, 'pred': pred}
                    if isinstance(value, int) and not isinstance(value, bool):
                        op['datatype'] = 'int'
                    visible.setdefault(key, set()).difference_update(pred)
                    visible[key].add(f'{ctr}@{actor}')
                elif kind < 0.8:
                    pred = vis
                    op = {'action': 'del', 'obj': '_root', 'key': key,
                          'pred': pred}
                    visible[key].difference_update(pred)
                else:
                    value = int(rng.integers(0, 50))
                    pred = vis
                    op = {'action': 'set', 'obj': '_root', 'key': key,
                          'value': value, 'datatype': 'counter', 'pred': pred}
                    visible[key].difference_update(pred)
                    visible[key].add(f'{ctr}@{actor}')
                deps = host_backend.get_heads(hb) if rng.random() < 0.8 else []
                buf = change_buf(actor, seqs[actor], ctr, [op], deps=deps)
                ctrs[actor] = ctr
                hb, gb = apply_both(hb, gb, [buf])
            assert host_backend.get_patch(hb) == fleet_backend.get_patch(gb)
            assert bytes(host_backend.save(hb)) == bytes(fleet_backend.save(gb))


class TestDeviceMaterialization:
    def test_device_matches_mirror(self):
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        rng = np.random.default_rng(3)
        handles = fleet_backend.init_docs(6, fb.fleet)
        seqs = [0] * 6
        per_doc = [[] for _ in range(6)]
        for d in range(6):
            ctr = 0
            vis = {}
            actor = ACTORS[d % 2]
            for _ in range(12):
                key = f'k{int(rng.integers(0, 6))}'
                ctr += 1
                if rng.random() < 0.3 and vis.get(key):
                    op = {'action': 'del', 'obj': '_root', 'key': key,
                          'pred': sorted(vis[key])}
                    vis[key] = set()
                else:
                    op = {'action': 'set', 'obj': '_root', 'key': key,
                          'value': int(rng.integers(0, 1000)),
                          'datatype': 'int', 'pred': sorted(vis.get(key, set()))}
                    vis[key] = {f'{ctr}@{actor}'}
                seqs[d] += 1
                deps = host_backend.get_heads(handles[d]) if seqs[d] > 1 else []
                per_doc[d].append(change_buf(actor, seqs[d], ctr,
                                             [op], deps=deps))
            handles[d], _ = fleet_backend.apply_changes(handles[d], per_doc[d])
        mirror = [h['state'].materialize() for h in handles]
        device = fleet_backend.materialize_docs(handles)
        assert device == mirror

    def test_conflicted_counter_increment_matches_reference(self):
        """An inc on a conflicted counter preds EVERY conflicting set; the
        reference attributes it to the Lamport-MAX pred'd set
        (counterStates[succOp] overwrites earlier registrations,
        new.js:942-945) and the other conflicting sets never complete
        their counter state — they stay invisible. The register engine
        must do the same: add to the max live pred'd lane, hide the rest
        (round-4 50x-chaos find, seed 18)."""
        import automerge_tpu as am
        a, b, c = ACTORS[0], ACTORS[1], ACTORS[2]
        c1 = change_buf(a, 1, 1, [
            {'action': 'makeMap', 'obj': '_root', 'key': 'm', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        # concurrent counter creations under the same key -> conflict
        c2 = change_buf(a, 2, 2, [
            {'action': 'set', 'obj': f'1@{a}', 'key': 'y', 'value': 0,
             'datatype': 'counter', 'pred': []}], deps=[h1])
        c3 = change_buf(b, 1, 2, [
            {'action': 'set', 'obj': f'1@{a}', 'key': 'y', 'value': 3,
             'datatype': 'counter', 'pred': []}], deps=[h1])
        h2 = am.decode_change(c2)['hash']
        h3 = am.decode_change(c3)['hash']
        # an actor that has seen BOTH increments the conflicted counter:
        # pred lists every conflicting set op
        c4 = change_buf(c, 1, 3, [
            {'action': 'inc', 'obj': f'1@{a}', 'key': 'y', 'value': 1,
             'datatype': 'counter', 'pred': [f'2@{a}', f'2@{b}']}],
            deps=sorted([h2, h3]))
        hb = host_backend.init()
        for ch in (c1, c2, c3, c4):
            hb, _ = host_backend.apply_changes(hb, [ch])
        want = host_backend.get_patch(hb)
        for turbo in (False, True):
            fleet = DocFleet(doc_capacity=2, key_capacity=8,
                             exact_device=True)
            gb = fleet_backend.init(fleet)
            if turbo:
                [gb], _ = fleet_backend.apply_changes_docs(
                    [gb], [[c1, c2, c3, c4]], mirror=False)
            else:
                for ch in (c1, c2, c3, c4):
                    gb, _ = fleet_backend.apply_changes(gb, [ch])
            got = fleet_backend.get_patch(gb)
            assert got == want, turbo
            assert fleet.metrics.mirror_rebuilds == 0
            # winner (higher actor) shows base 3 + the shared inc
            assert fleet_backend.materialize_docs([gb]) == [{'m': {'y': 4}}]

    def test_conflicted_counter_inc_with_dead_max_pred(self):
        """The attribution target is the Lamport-max pred even when that
        set was already overwritten: the inc is consumed silently by the
        dead set, and the LIVE lower branch still hides (its succ never
        completes). The reference shows only the overwriting value."""
        import automerge_tpu as am
        a, b, c = ACTORS[0], ACTORS[1], ACTORS[2]
        c1 = change_buf(a, 1, 1, [
            {'action': 'makeMap', 'obj': '_root', 'key': 'm', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(a, 2, 2, [
            {'action': 'set', 'obj': f'1@{a}', 'key': 'y', 'value': 0,
             'datatype': 'counter', 'pred': []}], deps=[h1])
        c3 = change_buf(b, 1, 2, [
            {'action': 'set', 'obj': f'1@{a}', 'key': 'y', 'value': 3,
             'datatype': 'counter', 'pred': []}], deps=[h1])
        h2 = am.decode_change(c2)['hash']
        h3 = am.decode_change(c3)['hash']
        # b overwrites its own counter with a plain value...
        c4 = change_buf(b, 2, 3, [
            {'action': 'set', 'obj': f'1@{a}', 'key': 'y', 'value': 9,
             'datatype': 'int', 'pred': [f'2@{b}']}], deps=[h3])
        h4 = am.decode_change(c4)['hash']
        # ...while c, who saw only the two counters, incs the conflict
        c5 = change_buf(c, 1, 3, [
            {'action': 'inc', 'obj': f'1@{a}', 'key': 'y', 'value': 1,
             'datatype': 'counter', 'pred': [f'2@{a}', f'2@{b}']}],
            deps=sorted([h2, h3]))
        hb = host_backend.init()
        for ch in (c1, c2, c3, c4, c5):
            hb, _ = host_backend.apply_changes(hb, [ch])
        want = host_backend.get_patch(hb)
        for turbo in (False, True):
            fleet = DocFleet(doc_capacity=2, key_capacity=8,
                             exact_device=True)
            gb = fleet_backend.init(fleet)
            if turbo:
                [gb], _ = fleet_backend.apply_changes_docs(
                    [gb], [[c1, c2, c3, c4, c5]], mirror=False)
            else:
                for ch in (c1, c2, c3, c4, c5):
                    gb, _ = fleet_backend.apply_changes(gb, [ch])
            got = fleet_backend.get_patch(gb)
            assert got == want, turbo
            assert fleet_backend.materialize_docs([gb]) == \
                [{'m': {'y': 9}}], turbo

    def test_counter_inc_of_overwritten_set_not_served_wrong(self):
        """Round-4 chaos find: the grid's counter cell cannot attribute an
        inc to its pred, so an inc whose counter set lost (or was
        overwritten in the same batch) was credited to the winning counter
        and materialize_docs served base+1. The host winner mirror now
        flags such slots into grid_overflow and reads fall back to the
        exact mirror (ref new.js:937-965 counter succ semantics)."""
        import automerge_tpu as am
        a, b = ACTORS[0], ACTORS[1]
        c1 = change_buf(a, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 5,
             'datatype': 'counter', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(a, 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'counter', 'pred': [f'1@{a}']}], deps=[h1])
        c3 = change_buf(b, 1, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 6,
             'datatype': 'counter', 'pred': [f'1@{a}']}], deps=[h1])
        for split in (False, True):
            for mirror in (True, False):
                fleet = DocFleet(doc_capacity=2, key_capacity=4)
                h = fleet_backend.init(fleet)
                groups = [[c1, c2], [c3]] if split else [[c1, c2, c3]]
                for g in groups:
                    if mirror:
                        h, _ = fleet_backend.apply_changes(h, g)
                    else:
                        [h], _ = fleet_backend.apply_changes_docs(
                            [h], [g], mirror=False)
                assert fleet_backend.materialize_docs([h]) == [{'x': 6}], \
                    (split, mirror)
        # The happy path — incs of the standing winner — must NOT flag
        fleet = DocFleet(doc_capacity=2, key_capacity=4)
        h = fleet_backend.init(fleet)
        [h], _ = fleet_backend.apply_changes_docs([h], [[c1, c2]],
                                                  mirror=False)
        assert fleet_backend.materialize_docs([h]) == [{'x': 6}]
        assert 0 not in fleet.grid_overflow

    def test_negative_inc_delta_device_parity(self):
        """Negative inc deltas must land inline in the value column, not as
        value-table references (regression: device counters were corrupted
        by the table index)."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 10,
             'datatype': 'counter', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': -5,
             'pred': [f'1@{ACTORS[0]}']}], deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert gb['state'].materialize() == {'c': 5}
        assert fleet_backend.materialize_docs([gb]) == [{'c': 5}]

    def test_counter_overwrite_resets_device_accumulator(self):
        """A causally-later plain set over a counter must not inherit the
        counter's accumulated increments on the device read path
        (regression: the counters column was never reset)."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 10,
             'datatype': 'counter', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': 3,
             'pred': [f'1@{ACTORS[0]}']}], deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        # Flush so the overwrite arrives in a separate device batch
        assert fleet_backend.materialize_docs([gb]) == [{'c': 13}]
        c3 = change_buf(ACTORS[0], 3, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 100,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c3])
        assert gb['state'].materialize() == {'c': 100}
        assert fleet_backend.materialize_docs([gb]) == [{'c': 100}]

    def test_actor_renumbering_tie_break(self):
        """Equal op counters, actors arriving in non-sorted order: the device
        scatter-max must still pick the reference's Lamport winner."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        # 'bb…' arrives first (gets number 0), then 'aa…' must renumber
        c1 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        fb.fleet.flush()
        c2 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 2,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'x': 1}]
        assert gb['state'].materialize() == {'x': 1}

    def test_batched_apply_one_dispatch(self):
        fb = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        handles = fleet_backend.init_docs(5, fb.fleet)
        per_doc = []
        for d in range(5):
            per_doc.append([change_buf(ACTORS[0], 1, 1, [
                {'action': 'set', 'obj': '_root', 'key': f'k{d}', 'value': d,
                 'datatype': 'int', 'pred': []}])])
        before = fb.fleet.dispatches
        handles, patches = fleet_backend.apply_changes_docs(handles, per_doc)
        assert fb.fleet.dispatches == before + 1
        assert all(p is not None for p in patches)
        docs = fleet_backend.materialize_docs(handles)
        assert docs == [{f'k{d}': d} for d in range(5)]

    def test_key_grid_growth(self):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        for i in range(20):
            c = change_buf(ACTORS[0], i + 1, i + 1, [
                {'action': 'set', 'obj': '_root', 'key': f'key{i}', 'value': i,
                 'datatype': 'int', 'pred': []}],
                deps=fleet_backend.get_heads(gb))
            gb, _ = fleet_backend.apply_changes(gb, [c])
            fb.fleet.flush()
        expected = {f'key{i}': i for i in range(20)}
        assert fleet_backend.materialize_docs([gb]) == [expected]

    def test_clone_and_free(self):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        c = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 5,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c])
        gb2 = fleet_backend.clone(gb)
        c2 = change_buf(ACTORS[1], 1, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 6,
             'datatype': 'int', 'pred': []}],
            deps=fleet_backend.get_heads(gb2))
        gb2, _ = fleet_backend.apply_changes(gb2, [c2])
        assert fleet_backend.materialize_docs([gb2]) == [{'a': 5, 'b': 6}]
        assert gb['state'].materialize() == {'a': 5}
        fleet_backend.free(gb2)
        assert gb2['state'] is None


class TestTurboPath:
    def _workload(self, n_docs, n_changes, rng):
        per_doc = []
        for d in range(n_docs):
            changes, heads = [], []
            for c in range(n_changes):
                buf = change_buf(ACTORS[d % 3], c + 1, c + 1, [
                    {'action': 'set', 'obj': '_root',
                     'key': f'k{int(rng.integers(0, 4))}',
                     'value': int(rng.integers(0, 500)),
                     'datatype': 'int', 'pred': []}], deps=heads)
                heads = [am.decode_change(buf)['hash']]
                changes.append(buf)
            per_doc.append(changes)
        return per_doc

    def test_turbo_matches_exact(self):
        rng = np.random.default_rng(11)
        per_doc = self._workload(5, 8, rng)
        fb1 = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        fb2 = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        exact = fleet_backend.init_docs(5, fb1.fleet)
        turbo = fleet_backend.init_docs(5, fb2.fleet)
        exact, ep = fleet_backend.apply_changes_docs(exact, per_doc)
        turbo, tp = fleet_backend.apply_changes_docs(turbo, per_doc,
                                                     mirror=False)
        assert all(p is None for p in tp)
        assert fleet_backend.materialize_docs(exact) == \
            fleet_backend.materialize_docs(turbo)
        # Mirrors rebuild lazily and agree with the exact path
        for e, t in zip(exact, turbo):
            assert t['state']._impl.stale
            assert fleet_backend.get_patch(t) == fleet_backend.get_patch(e)
            assert not t['state']._impl.stale
            assert fleet_backend.get_heads(t) == fleet_backend.get_heads(e)
            assert bytes(fleet_backend.save(t)) == bytes(fleet_backend.save(e))

    def test_turbo_then_exact_interleave(self):
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(2, fb.fleet)
        c1 = [[change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': d + 1,
             'datatype': 'int', 'pred': []}])] for d in range(2)]
        handles, _ = fleet_backend.apply_changes_docs(handles, c1,
                                                      mirror=False)
        # Exact call on a stale doc rebuilds the mirror and keeps going
        h0 = handles[0]
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 9,
             'datatype': 'int', 'pred': []}],
            deps=fleet_backend.get_heads(h0))
        h0, patch = fleet_backend.apply_changes(h0, [c2])
        assert patch['diffs']['props']['b'] == \
            {f'2@{ACTORS[0]}': {'type': 'value', 'value': 9,
                                'datatype': 'int'}}
        assert h0['state'].materialize() == {'a': 1, 'b': 9}
        assert fleet_backend.materialize_docs([h0, handles[1]]) == \
            [{'a': 1, 'b': 9}, {'a': 2}]

    def test_turbo_queues_missing_deps(self):
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}], deps=[h1])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c2]],
                                                      mirror=False)
        assert fleet_backend.get_missing_deps(handles[0]) == [h1]
        # Dep arrives; queued change drains through the exact path
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c1]],
                                                      mirror=False)
        assert handles[0]['state'].materialize() == {'k': 2}
        assert fleet_backend.materialize_docs(handles) == [{'k': 2}]

    def test_turbo_atomic_across_docs(self):
        """A gate error on one doc must roll back every doc in the turbo
        call (regression: earlier docs kept hash-graph entries whose ops
        never reached the device)."""
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(2, fb.fleet)
        good = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 7,
             'datatype': 'int', 'pred': []}])
        bad = change_buf(ACTORS[1], 3, 1, [     # seq skip
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 1,
             'datatype': 'int', 'pred': []}])
        with pytest.raises(ValueError, match='Skipped sequence number'):
            fleet_backend.apply_changes_docs(handles, [[good], [bad]],
                                             mirror=False)
        assert fleet_backend.get_heads(handles[0]) == []
        assert handles[0]['state'].materialize() == {}
        assert fleet_backend.materialize_docs(handles) == [{}, {}]

    def test_turbo_queue_only_no_dispatch_no_interning(self):
        """A turbo call where everything queues must not issue a device
        dispatch nor intern the queued changes' keys (regression)."""
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        dangling = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'ghostkey', 'value': 1,
             'datatype': 'int', 'pred': []}], deps=['ab' * 32])
        before = fb.fleet.dispatches
        handles, _ = fleet_backend.apply_changes_docs(handles, [[dangling]],
                                                      mirror=False)
        assert fb.fleet.dispatches == before
        assert len(fb.fleet.keys) == 0
        assert fleet_backend.get_missing_deps(handles[0]) == ['ab' * 32]

    def test_turbo_duplicate_op_id_rejected(self):
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 1,
             'datatype': 'int', 'pred': []}])
        # Same opId (1@actor) from a different change in the same batch
        c2 = change_buf(ACTORS[0], 2, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 2,
             'datatype': 'int', 'pred': []}],
            deps=[am.decode_change(c1)['hash']])
        with pytest.raises(ValueError, match='duplicate operation ID'):
            fleet_backend.apply_changes_docs(handles, [[c1, c2]],
                                             mirror=False)
        assert fleet_backend.get_heads(handles[0]) == []

    def test_turbo_sync_without_rebuild(self):
        """Sync needs only the hash graph: a turbo doc syncs to a host doc
        without its mirror ever being rebuilt."""
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        c1 = [[change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 5,
             'datatype': 'int', 'pred': []}])]]
        handles, _ = fleet_backend.apply_changes_docs(handles, c1,
                                                      mirror=False)
        gb, hb = handles[0], host_backend.init()
        s1, s2 = fleet_backend.init_sync_state(), host_backend.init_sync_state()
        for _ in range(8):
            s1, m = fleet_backend.generate_sync_message(gb, s1)
            if m is not None:
                hb, s2, _ = host_backend.receive_sync_message(hb, s2, m)
            s2, r = host_backend.generate_sync_message(hb, s2)
            if r is not None:
                gb, s1, _ = fleet_backend.receive_sync_message(gb, s1, r)
            if m is None and r is None:
                break
        assert host_backend.get_heads(hb) == fleet_backend.get_heads(gb)
        assert host_backend.get_patch(hb)['diffs']['props']['k'] == \
            {f'1@{ACTORS[0]}': {'type': 'value', 'value': 5,
                                'datatype': 'int'}}


class TestPromotion:
    def test_nested_maps_stay_fleet_resident(self):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        hb = host_backend.init()
        gb = fb.init()
        flat = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 1,
             'datatype': 'int', 'pred': []}])
        hb, gb = apply_both(hb, gb, [flat])
        assert gb['state'].is_fleet
        nested = change_buf(ACTORS[0], 2, 2, [
            {'action': 'makeMap', 'obj': '_root', 'key': 'm', 'pred': []},
            {'action': 'set', 'obj': f'2@{ACTORS[0]}', 'key': 'x', 'value': 9,
             'datatype': 'int', 'pred': []}],
            deps=host_backend.get_heads(hb))
        hb, gb = apply_both(hb, gb, [nested])
        assert gb['state'].is_fleet          # two-level key interning
        assert gb['state'].fleet.metrics.promotions == 0
        assert host_backend.get_patch(hb) == fleet_backend.get_patch(gb)
        # Nested-map docs materialize from the device grid
        from automerge_tpu.fleet.backend import materialize_docs
        assert materialize_docs([gb]) == [{'a': 1, 'm': {'x': 9}}]
        more = change_buf(ACTORS[0], 3, 4, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=host_backend.get_heads(hb))
        hb, gb = apply_both(hb, gb, [more])
        assert bytes(host_backend.save(hb)) == bytes(fleet_backend.save(gb))

    def test_object_inside_sequence_stays_fleet_resident(self):
        """Rows-in-lists (a map created as a list element,
        ref new.js:1461-1528) ride the device: the element value links to
        the child object, whose keys intern as (objectId, key) grid
        columns like any nested map."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        hb = host_backend.init()
        gb = fb.init()
        nested_in_list = change_buf(ACTORS[0], 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{ACTORS[0]}', 'elemId': '_head',
             'insert': True, 'pred': []},
            {'action': 'set', 'obj': f'2@{ACTORS[0]}', 'key': 'row',
             'value': 3, 'datatype': 'int', 'pred': []}])
        hb, gb = apply_both(hb, gb, [nested_in_list])
        assert gb['state'].is_fleet
        assert fb.fleet.metrics.promotions == 0
        from automerge_tpu.fleet.backend import materialize_docs
        assert materialize_docs([gb]) == [{'l': [{'row': 3}]}]
        assert bytes(host_backend.save(hb)) == bytes(fleet_backend.save(gb))

    def test_turbo_rows_in_lists_no_fallback(self):
        """The native turbo parser emits make-inside-sequence rows (flags
        11-14), so rows-in-lists workloads keep the wire->device path:
        one turbo call, zero fallbacks, device reads and saves identical
        to the host engine."""
        import automerge_tpu as am
        a = ACTORS[0]
        ops1 = [
            {'action': 'makeList', 'obj': '_root', 'key': 'todo',
             'pred': []},
            {'action': 'makeMap', 'obj': f'1@{a}', 'elemId': '_head',
             'insert': True, 'pred': []},
            {'action': 'set', 'obj': f'2@{a}', 'key': 't', 'value': 'wash',
             'pred': []},
            {'action': 'makeList', 'obj': f'1@{a}', 'elemId': f'2@{a}',
             'insert': True, 'pred': []},
            {'action': 'set', 'obj': f'4@{a}', 'elemId': '_head',
             'insert': True, 'value': 1, 'datatype': 'int', 'pred': []},
        ]
        c1 = change_buf(a, 1, 1, ops1)
        c2 = change_buf(a, 2, 6, [
            {'action': 'set', 'obj': f'2@{a}', 'key': 'n', 'value': 5,
             'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': f'4@{a}', 'elemId': f'5@{a}',
             'insert': True, 'value': 2, 'datatype': 'int', 'pred': []}],
            deps=[am.decode_change(c1)['hash']])
        for exact in (False, True):
            fleet = DocFleet(doc_capacity=2, key_capacity=8,
                             exact_device=exact)
            handles = fleet_backend.init_docs(2, fleet)
            handles, _ = fleet_backend.apply_changes_docs(
                handles, [[c1, c2]] * 2, mirror=False)
            assert fleet.metrics.turbo_calls == 1, exact
            assert fleet.metrics.fallbacks == 0, exact
            assert fleet.metrics.promotions == 0, exact
            want = {'todo': [{'t': 'wash', 'n': 5}, [1, 2]]}
            assert fleet_backend.materialize_docs(handles) == [want] * 2
            hb = host_backend.init()
            hb, _ = host_backend.apply_changes(hb, [c1, c2])
            assert bytes(host_backend.save(hb)) == \
                bytes(fleet_backend.save(handles[0]))

    def test_link_op_rejected_loudly(self):
        """`link` is a reserved action the reference never applies
        (new.js:893 TODO); both engines reject it with the same error
        instead of silently promoting or storing a dangling child edge."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        link = change_buf(ACTORS[0], 1, 1, [
            {'action': 'link', 'obj': '_root', 'key': 'x',
             'child': f'1@{ACTORS[1]}', 'pred': []}])
        with pytest.raises(ValueError, match='link operations are not supported'):
            fleet_backend.apply_changes(gb, [link])
        with pytest.raises(ValueError, match='link operations are not supported'):
            host_backend.apply_changes(host_backend.init(), [link])
        # The rejection must be free: no promotion, no lost device slot
        assert gb['state'].is_fleet
        assert fb.fleet.metrics.promotions == 0
        # The failed call must not corrupt the handle: it still applies
        # ordinary changes afterwards, still fleet-resident
        ok = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 5,
             'datatype': 'int', 'pred': []}])
        gb, patch = fleet_backend.apply_changes(gb, [ok])
        assert patch['clock'] == {ACTORS[0]: 1}
        assert gb['state'].is_fleet

    def test_promotion_preserves_queue(self):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}], deps=[h1])
        gb, patch = fleet_backend.apply_changes(gb, [c2])
        assert patch['pendingChanges'] == 1
        # A sequence make past the packed-counter window still promotes
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        big = change_buf(ACTORS[1], 1, CTR_LIMIT + 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [big])
        assert not gb['state'].is_fleet
        gb, patch = fleet_backend.apply_changes(gb, [c1])
        assert patch['pendingChanges'] == 0
        props = fleet_backend.get_patch(gb)['diffs']['props']
        assert props['k'] == {f'2@{ACTORS[0]}':
                              {'type': 'value', 'value': 2, 'datatype': 'int'}}


class TestSyncInterop:
    def test_fleet_host_sync_convergence(self):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2))
        gb = fb.init()
        hb = host_backend.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'fleet', 'value': 1,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'host', 'value': 2,
             'datatype': 'int', 'pred': []}])
        hb, _ = host_backend.apply_changes(hb, [c2])

        s1, s2 = fleet_backend.init_sync_state(), host_backend.init_sync_state()
        for _ in range(10):
            s1, msg = fleet_backend.generate_sync_message(gb, s1)
            if msg is not None:
                hb, s2, _ = host_backend.receive_sync_message(hb, s2, msg)
            s2, reply = host_backend.generate_sync_message(hb, s2)
            if reply is not None:
                gb, s1, _ = fleet_backend.receive_sync_message(gb, s1, reply)
            if msg is None and reply is None:
                break
        assert fleet_backend.get_heads(gb) == host_backend.get_heads(hb)
        assert fleet_backend.get_patch(gb) == host_backend.get_patch(hb)
        assert gb['state'].materialize() == {'fleet': 1, 'host': 2}


class TestDropIn:
    def test_set_default_backend_public_api(self):
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        am.set_default_backend(fb)
        try:
            d1 = am.init(ACTORS[0])
            d1 = am.change(d1, lambda doc: doc.update({'title': 'fleet'}))
            d2 = am.init(ACTORS[1])
            d2 = am.merge(d2, d1)
            d2 = am.change(d2, lambda doc: doc.update({'count': 3}))
            d1 = am.merge(d1, d2)
            assert d1['title'] == 'fleet'
            assert d1['count'] == 3
            data = am.save(d1)
            d3 = am.load(data)
            assert am.equals(d3, d1)
            # Nested objects trigger transparent promotion
            d1 = am.change(d1, lambda doc: doc.update({'nested': {'x': 1}}))
            assert d1['nested']['x'] == 1
        finally:
            am.set_default_backend(host_backend)


class TestExactDeviceMode:
    """DocFleet(exact_device=True): the multi-value register engine as the
    fleet's device state — resurrection/conflict/counter corners exact on
    the device read path, not just the host mirror."""

    def _fb(self):
        return FleetBackend(DocFleet(doc_capacity=4, key_capacity=4,
                                     exact_device=True))

    def test_resurrection_exact_on_device(self):
        fb = self._fb()
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 5,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        # Concurrent: bb overwrites (2@bb), cc deletes with greater opId
        c2 = change_buf(ACTORS[1], 1, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 7,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=fleet_backend.get_heads(gb))
        c3 = change_buf(ACTORS[2], 1, 9, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{ACTORS[0]}']}],
            deps=[am.decode_change(c1)['hash']])
        gb, _ = fleet_backend.apply_changes(gb, [c2, c3])
        # Device read path must keep bb's set alive (the LWW grid would
        # have shown the key deleted: 9@cc > 2@bb)
        assert fleet_backend.materialize_docs([gb]) == [{'k': 7}]
        assert gb['state'].materialize() == {'k': 7}

    def test_conflicts_on_device(self):
        fb = self._fb()
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        c2 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 2,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1, c2])
        conflicts = fb.fleet.conflicts_all()[gb['state']._impl.slot]
        assert set(conflicts) == {'x'}
        assert sorted(conflicts['x'].values()) == [1, 2]
        assert fleet_backend.materialize_docs([gb]) == [{'x': 2}]

    def test_counter_exact_on_device(self):
        fb = self._fb()
        gb = fb.init()
        cs = []
        heads = []
        specs = [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 10,
             'datatype': 'counter', 'pred': []},
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': 3,
             'pred': [f'1@{ACTORS[0]}']},
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 100,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']},
        ]
        for i, op in enumerate(specs):
            buf = change_buf(ACTORS[0], i + 1, i + 1, [op], deps=heads)
            heads = [am.decode_change(buf)['hash']]
            cs.append(buf)
        gb, _ = fleet_backend.apply_changes(gb, cs[:2])
        assert fleet_backend.materialize_docs([gb]) == [{'c': 13}]
        gb, _ = fleet_backend.apply_changes(gb, [cs[2]])
        assert fleet_backend.materialize_docs([gb]) == [{'c': 100}]

    def test_turbo_exact_device_string_values(self):
        """Turbo on int workloads, Python-ingest flush on string values —
        both land in the same register state."""
        fb = self._fb()
        handles = fleet_backend.init_docs(2, fb.fleet)
        ints = [[change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'n', 'value': d + 1,
             'datatype': 'int', 'pred': []}])] for d in range(2)]
        handles, patches = fleet_backend.apply_changes_docs(handles, ints,
                                                           mirror=False)
        assert all(p is None for p in patches)
        strs = [[change_buf(ACTORS[1], 1, 5, [
            {'action': 'set', 'obj': '_root', 'key': 's', 'value': f'doc{d}',
             'pred': []}])] for d in range(2)]
        handles, _ = fleet_backend.apply_changes_docs(handles, strs)
        assert fleet_backend.materialize_docs(handles) == \
            [{'n': 1, 's': 'doc0'}, {'n': 2, 's': 'doc1'}]

    def test_actor_renumber_in_register_mode(self):
        fb = self._fb()
        gb = fb.init()
        c1 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        fb.fleet.flush()
        c2 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 2,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'x': 1}]

    def test_randomized_exact_device_differential(self):
        rng = np.random.default_rng(23)
        fb = self._fb()
        hb = host_backend.init()
        gb = fb.init()
        vis = {}
        heads = []
        seqs = {a: 0 for a in ACTORS[:2]}
        ctr = 0
        for step in range(25):
            actor = ACTORS[int(rng.integers(0, 2))]
            key = f'k{int(rng.integers(0, 4))}'
            ctr += 1
            seqs[actor] += 1
            cur = sorted(vis.get(key, set()))
            if rng.random() < 0.25 and cur:
                op = {'action': 'del', 'obj': '_root', 'key': key,
                      'pred': cur}
                vis[key] = set()
            else:
                op = {'action': 'set', 'obj': '_root', 'key': key,
                      'value': int(rng.integers(0, 100)), 'datatype': 'int',
                      'pred': cur}
                vis[key] = {f'{ctr}@{actor}'}
            buf = change_buf(actor, seqs[actor], ctr, [op], deps=heads)
            heads = [am.decode_change(buf)['hash']]
            hb, hp = host_backend.apply_changes(hb, [buf])
            gb, gp = fleet_backend.apply_changes(gb, [buf])
            assert hp == gp
        assert fleet_backend.materialize_docs([gb]) == \
            [gb['state'].materialize()]
        assert host_backend.get_patch(hb) == fleet_backend.get_patch(gb)

    def test_negative_one_inc_delta(self):
        """inc by -1 must not be mistaken for the DEL value sentinel
        (regression)."""
        fb = self._fb()
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 10,
             'datatype': 'counter', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'c', 'value': -1,
             'pred': [f'1@{ACTORS[0]}']}], deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'c': 9}]

    def test_renumber_beyond_slot_capacity_grows_first(self):
        """Inserting an actor that pushes an existing actor's slot past the
        current width must grow the axis, not drop registers (regression)."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=2,
                                   exact_device=True, actor_slot_capacity=1))
        gb = fb.init()
        c1 = change_buf(ACTORS[2], 1, 1, [        # 'cc…' gets slot 0
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 9,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        fb.fleet.flush()
        c2 = change_buf(ACTORS[0], 1, 1, [        # 'aa…' sorts first
            {'action': 'set', 'obj': '_root', 'key': 'y', 'value': 1,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'x': 9, 'y': 1}]

    def test_turbo_after_lazy_exact_preserves_order(self):
        """A turbo call must land lazily-pending earlier changes first: a
        delete arriving via turbo after a pending set must win (regression:
        the flush ran after the register dispatch, resurrecting the key)."""
        fb = self._fb()
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])   # pending, no flush
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{ACTORS[0]}']}], deps=fleet_backend.get_heads(gb))
        handles, _ = fleet_backend.apply_changes_docs([gb], [[c2]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{}]


class TestAdviceRegressions:
    """Round-1 advisor findings (ADVICE.md): turbo multi-chunk buffers,
    unknown pred actors, null-value register materialization."""

    def test_turbo_multichunk_buffer_not_dropped(self):
        """A buffer holding two concatenated change chunks must apply BOTH
        chunks (turbo's native parser reads one chunk per buffer, so such
        buffers must fall back to the exact path)."""
        from automerge_tpu.columnar import decode_change
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change(c1)['hash']
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'y', 'value': 2,
             'datatype': 'int', 'pred': []}], deps=[h1])
        h2 = decode_change(c2)['hash']
        handles, _ = fleet_backend.apply_changes_docs(
            [gb], [[bytes(c1) + bytes(c2)]], mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{'x': 1, 'y': 2}]
        assert handles[0]['heads'] == [h2]
        # save() must agree with heads/clock (the old bug diverged them)
        reloaded = fb.load(fleet_backend.save(handles[0]))
        assert fleet_backend.get_heads(reloaded) == [h2]

    def test_turbo_unknown_pred_actor_raises(self):
        """A pred naming an actor the fleet never registered is a
        dangling pred: turbo now rejects it at apply time with the exact
        path's error (round 5 — it used to defer to the next mirror
        rebuild via an inexact flag), and actor 0's register survives
        untouched via rollback."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4,
                                   exact_device=True))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [          # 'aa…' -> actor 0
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 7,
             'datatype': 'int', 'pred': []}])
        handles, _ = fleet_backend.apply_changes_docs([gb], [[c1]],
                                                      mirror=False)
        # actor 'cc…' never authored a change with this fleet: '1@cc…'
        # dangles, exactly like the exact path's reject
        c2 = change_buf(ACTORS[1], 1, 1, [
            {'action': 'del', 'obj': '_root', 'key': 'x',
             'pred': [f'1@{ACTORS[2]}']}],
            deps=handles[0]['heads'])
        with pytest.raises(ValueError,
                           match='no matching operation for pred'):
            fleet_backend.apply_changes_docs(handles, [[c2]], mirror=False)
        fleet = fb.fleet
        fleet.flush()
        slot = handles[0]['state']._impl.slot
        # actor 0's register for key 'x' must NOT have been killed
        kx = fleet.keys.index['x']
        a0 = fleet.actors.index[ACTORS[0]]
        assert not bool(np.asarray(fleet.reg_state.killed)[slot, kx, a0])
        assert fleet_backend.materialize_docs(handles) == [{'x': 7}]

    def test_null_value_survives_register_materialize(self):
        """A key legitimately set to null must appear (as None) in
        exact-device bulk materialization, matching the host mirror."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4,
                                   exact_device=True))
        gb = fb.init()
        c1 = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': None,
             'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'm', 'value': 3,
             'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        assert fleet_backend.materialize_docs([gb]) == [{'k': None, 'm': 3}]
        # and it matches the host mirror's view
        assert gb['state'].materialize() == {'k': None, 'm': 3}

    def test_cap_docs_stable_on_non_pow2_mesh_capacity(self):
        """Round-4 advisor finding: on a non-pow2 docs axis the stored
        doc_cap is mesh-rounded (e.g. 66 on 6 devices); _cap_docs must
        return it unchanged when sufficient instead of re-deriving
        pow2(66)=128 -> 132 and regrowing state ~2x on every flush."""
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:6]), ('docs',))
        fleet = DocFleet(doc_capacity=4, key_capacity=4, mesh=mesh)
        fleet.doc_cap = 66  # a previously mesh-rounded capacity
        assert fleet._cap_docs(10) == 66
        assert fleet._cap_docs(66) == 66
        # growth past capacity still pow2-then-rounds
        assert fleet._cap_docs(67) == 132
        # and an actual growth sequence reaches a fixed point: growing to
        # the value just returned must be a no-op
        cap = fleet._cap_docs(67)
        assert fleet._cap_docs(cap) >= cap
        fleet.doc_cap = cap
        assert fleet._cap_docs(cap) == cap


class TestSequenceTermination:
    def test_cyclic_nxt_chain_terminates(self):
        """A corrupted cyclic nxt chain whose nodes all compare greater than
        the inserted key must terminate via the hop-counter backstop instead
        of hanging the device kernel."""
        from automerge_tpu.fleet import sequence as seq
        state = seq.SeqState.empty(1, 4)
        # Two real slots pointing at each other, both with huge elem_ids
        state.nxt[0, seq.HEAD] = seq.SLOT0
        state.nxt[0, seq.SLOT0] = seq.SLOT0 + 1
        state.nxt[0, seq.SLOT0 + 1] = seq.SLOT0       # cycle
        state.elem_id[0, seq.SLOT0] = 2**30
        state.elem_id[0, seq.SLOT0 + 1] = 2**30 + 1
        state.n[0] = 2
        batch = seq.SeqOpBatch(
            np.array([[seq.INSERT]], dtype=np.int32),
            np.array([[seq.HEAD_REF]], dtype=np.int32),
            np.array([[1 << 8]], dtype=np.int32),   # packed opId 1@actor0
            np.array([[65]], dtype=np.int32))
        out, _ = seq.apply_seq_batch(state, batch)   # must not hang
        assert out.n.shape == (1,)


class TestSequenceSeam:
    """Text/list documents through the Backend seam: fleet-resident device
    state (SeqState rows), zero promotions for plain sequence docs, host
    mirror fallback only for shapes outside device LWW semantics.
    Ref: backend/new.js:50-192 (the reference's list-insertion hot path)."""

    def _fb(self):
        return FleetBackend(DocFleet(doc_capacity=4, key_capacity=8))

    def test_text_doc_stays_fleet_resident(self):
        fb = self._fb()
        hb, gb = host_backend.init(), fb.init()
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'h', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'i', 'pred': []}])
        hb, gb = apply_both(hb, gb, [c1])
        c2 = change_buf(A, 2, 4, [
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'pred': [f'2@{A}']}], deps=host_backend.get_heads(hb))
        hb, gb = apply_both(hb, gb, [c2])
        assert gb['state'].is_fleet
        assert fb.fleet.metrics.promotions == 0
        assert fleet_backend.materialize_docs([gb]) == [{'t': 'i'}]
        # device row stayed exact: the render above came from the device
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)
        # patches match host throughout (apply_both asserted) and so does
        # the serialized document
        assert bytes(fleet_backend.save(gb)) == bytes(host_backend.save(hb))

    def test_list_values_device_render(self):
        fb = self._fb()
        gb = fb.init()
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 7, 'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'str', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': True, 'value': -5, 'datatype': 'int', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        assert fleet_backend.materialize_docs([gb]) == [{'l': [7, 'str', -5]}]
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)

    def test_rga_concurrent_insert_order_matches_host(self):
        """Two actors inserting at the same position: device RGA order must
        equal the host engine's (ref new.js:145-163)."""
        from automerge_tpu.columnar import decode_change
        fb = self._fb()
        hb, gb = host_backend.init(), fb.init()
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'm', 'pred': []}])
        h1 = decode_change(c1)['hash']
        hb, gb = apply_both(hb, gb, [c1])
        c2 = change_buf(A, 2, 3, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}], deps=[h1])
        c3 = change_buf(B, 1, 3, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'b', 'pred': []}], deps=[h1])
        hb, gb = apply_both(hb, gb, [c2, c3])
        expect = host_backend.get_patch(hb)
        got = fleet_backend.get_patch(gb)
        assert expect == got
        # device render agrees with the host's element order
        mat = fleet_backend.materialize_docs([gb])[0]['t']
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)
        assert mat == 'bam'   # higher actor's concurrent insert first

    def test_concurrent_set_vs_del_stays_exact_on_device(self):
        """Delete concurrent with a set: the reference keeps the element
        visible (the del only kills its preds, ref new.js:1204-1217). The
        actor-slotted element registers resolve this exactly on device —
        the row must NOT flag inexact."""
        from automerge_tpu.columnar import decode_change
        fb = self._fb()
        gb = fb.init()
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 1, 'datatype': 'int', 'pred': []}])
        h1 = decode_change(c1)['hash']
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(A, 2, 3, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'value': 9, 'datatype': 'int', 'pred': [f'2@{A}']}], deps=[h1])
        c3 = change_buf(B, 1, 3, [
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'pred': [f'2@{A}']}], deps=[h1])
        gb, _ = fleet_backend.apply_changes(gb, [c2, c3])
        # reference semantics: the concurrent set survives the delete
        assert fleet_backend.materialize_docs([gb]) == [{'l': [9]}]
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)

    def test_counter_in_list_exact_on_device(self):
        """Counters inside sequences accumulate exactly in per-lane
        counter registers (round 4) — no inexact fallback; device reads
        fold the winning lane's deltas onto the boxed counter base."""
        fb = self._fb()
        gb = fb.init()
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 10, 'datatype': 'counter', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        c2 = change_buf(A, 2, 3, [
            {'action': 'inc', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'value': 5, 'pred': [f'2@{A}']}],
            deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'l': [15]}]
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)

    def test_counter_in_list_patch_shapes_match_host(self):
        """Whole-doc patches for counters in lists replay the reference's
        counterStates edit shapes: insert for 0/1 consumed incs, the
        remove->update conversion for >= 2 — across per-doc, turbo, and
        bulk-load paths in both fleet modes."""
        import automerge_tpu as am
        from automerge_tpu.fleet.loader import load_docs
        A, B = ACTORS[0], ACTORS[1]
        for n_incs in (1, 2, 3):
            ops = [{'action': 'makeList', 'obj': '_root', 'key': 'l',
                    'pred': []},
                   {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
                    'insert': True, 'value': 10, 'datatype': 'counter',
                    'pred': []}]
            for i in range(n_incs):
                ops.append({'action': 'inc', 'obj': f'1@{A}',
                            'elemId': f'2@{A}', 'value': i + 1,
                            'datatype': 'counter', 'pred': [f'2@{A}']})
            c1 = change_buf(A, 1, 1, ops)
            hb = host_backend.init()
            hb, _ = host_backend.apply_changes(hb, [c1])
            want = host_backend.get_patch(hb)
            saved = bytes(host_backend.save(hb))
            for exact in (False, True):
                for turbo in (False, True):
                    fleet = DocFleet(doc_capacity=2, key_capacity=8,
                                     exact_device=exact)
                    gb = fleet_backend.init(fleet)
                    if turbo:
                        [gb], _ = fleet_backend.apply_changes_docs(
                            [gb], [[c1]], mirror=False)
                    else:
                        gb, _ = fleet_backend.apply_changes(gb, [c1])
                    assert fleet_backend.get_patch(gb) == want, \
                        (n_incs, exact, turbo)
                    assert bytes(fleet_backend.save(gb)) == saved
                fresh = DocFleet(doc_capacity=2, key_capacity=8,
                                 exact_device=exact)
                hb2 = load_docs([saved], fresh)[0]
                assert fresh.metrics.docs_bulk_loaded == 1
                assert fleet_backend.get_patch(hb2) == want, \
                    ('bulk', n_incs, exact)

    def test_randomized_sequence_counter_differential(self):
        """Backend-level fuzz of counters inside lists with REAL
        concurrency: two replicas diverge (each creating counter elements
        and incrementing what they see) and periodically merge, so
        conflicted counter sets, cross-branch incs, and deletes all occur;
        every converged state is compared across host and both fleet modes
        (patches, reads, and save bytes)."""
        import automerge_tpu as am
        rng = np.random.default_rng(7)
        A, B = ACTORS[0], ACTORS[1]

        for trial in range(4):
            # Two host replicas drive op generation (their visible state
            # decides preds, like a real frontend would)
            reps = [host_backend.init(), host_backend.init()]
            boot = change_buf(A, 1, 1, [
                {'action': 'makeList', 'obj': '_root', 'key': 'l',
                 'pred': []}])
            for i in (0, 1):
                reps[i], _ = host_backend.apply_changes(reps[i], [boot])
            list_id = f'1@{A}'
            seqs = {A: 1, B: 0}

            def visible_elems(rep):
                """[(elemId, [set opIds], is_counter)] via the host patch."""
                diffs = host_backend.get_patch(rep)['diffs']
                lst = diffs['props'].get('l', {}).get(list_id)
                out = []
                if not lst:
                    return out
                idx = -1
                for edit in lst.get('edits', []):
                    if edit['action'] in ('insert', 'update'):
                        ops = [edit['opId']]
                        val = edit['value']
                        out.append((edit.get('elemId', ops[0]), ops,
                                    isinstance(val, dict) and
                                    val.get('datatype') == 'counter'))
                return out

            for step in range(int(rng.integers(12, 20))):
                r = int(rng.integers(0, 2))
                actor = (A, B)[r]
                rep = reps[r]
                elems = visible_elems(rep)
                roll = rng.random()
                counters = [e for e in elems if e[2]]
                if roll < 0.45 or not elems:
                    # insert a counter (or plain) element at random ref
                    ref = '_head' if not elems or rng.random() < 0.4 \
                        else elems[int(rng.integers(0, len(elems)))][0]
                    op = {'action': 'set', 'obj': list_id, 'elemId': ref,
                          'insert': True,
                          'value': int(rng.integers(0, 50)),
                          'pred': []}
                    if rng.random() < 0.7:
                        op['datatype'] = 'counter'
                    else:
                        op['datatype'] = 'int'
                elif roll < 0.8 and counters:
                    eid, preds, _ = counters[int(rng.integers(
                        0, len(counters)))]
                    op = {'action': 'inc', 'obj': list_id, 'elemId': eid,
                          'value': int(rng.integers(-3, 9)),
                          'datatype': 'counter', 'pred': preds}
                else:
                    eid, preds, _ = elems[int(rng.integers(0, len(elems)))]
                    op = {'action': 'del', 'obj': list_id, 'elemId': eid,
                          'pred': preds}
                seqs[actor] += 1
                # startOp = maxOp + 1 like the reference frontend: op
                # counters must exceed every causally-seen op's counter
                start = host_backend.get_patch(rep)['maxOp'] + 1
                buf = change_buf(actor, seqs[actor], start, [op],
                                 deps=host_backend.get_heads(rep))
                reps[r], _ = host_backend.apply_changes(reps[r], [buf])
                if rng.random() < 0.3:
                    # merge the other replica in (concurrency point):
                    # get_changes_added(a, b) = changes in b missing
                    # from a
                    other = reps[1 - r]
                    missing = host_backend.get_changes_added(reps[r], other)
                    if missing:
                        reps[r], _ = host_backend.apply_changes(
                            reps[r], [bytes(c) for c in missing])

            # converge both replicas, then differentially replay the full
            # history through both fleet modes
            for r in (0, 1):
                missing = host_backend.get_changes_added(reps[r],
                                                         reps[1 - r])
                if missing:
                    reps[r], _ = host_backend.apply_changes(
                        reps[r], [bytes(c) for c in missing])
            assert host_backend.get_heads(reps[0]) == \
                host_backend.get_heads(reps[1])
            history = [bytes(c) for c in
                       host_backend.get_all_changes(reps[0])]
            want = host_backend.get_patch(reps[0])
            saved = bytes(host_backend.save(reps[0]))
            for exact in (False, True):
                fleet = DocFleet(doc_capacity=2, key_capacity=8,
                                 exact_device=exact)
                gb = fleet_backend.init(fleet)
                gb, _ = fleet_backend.apply_changes(gb, history)
                assert fleet_backend.get_patch(gb) == want, (trial, exact)
                assert bytes(fleet_backend.save(gb)) == saved, \
                    (trial, exact)

    def test_clone_and_free_with_seq_rows(self):
        fb = self._fb()
        gb = fb.init()
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'x', 'pred': []}])
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        clone = fleet_backend.clone(gb)
        # divergent edits after cloning must not interfere
        c2 = change_buf(A, 2, 3, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'y', 'pred': []}],
            deps=fleet_backend.get_heads(gb))
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb, clone]) == \
            [{'t': 'xy'}, {'t': 'x'}]
        fleet_backend.free(clone)
        assert fleet_backend.materialize_docs([gb]) == [{'t': 'xy'}]

    def test_actor_renumber_remaps_seq_rows(self):
        """A later actor that sorts before existing ones renumbers packed
        elemIds in device rows; RGA order must stay correct."""
        from automerge_tpu.columnar import decode_change
        fb = self._fb()
        gb = fb.init()
        A, early = ACTORS[2], ACTORS[3]     # 'cc…' then '11…' (sorts first)
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}])
        h1 = decode_change(c1)['hash']
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        fb.fleet.flush()                     # device rows exist pre-renumber
        c2 = change_buf(early, 1, 3, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'b', 'pred': []}], deps=[h1])
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        assert fleet_backend.materialize_docs([gb]) == [{'t': 'ab'}]
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)

    def test_public_api_text_promotionless(self):
        import automerge_tpu as am
        from automerge_tpu import Text
        import automerge_tpu.frontend as fe
        fb = self._fb()
        old = am.Backend()
        am.set_default_backend(fb)
        try:
            d = am.init(ACTORS[0])
            d = am.change(d, lambda doc: doc.__setitem__('t', Text('hello')))
            d = am.change(d, lambda doc: doc['t'].insert_at(5, '!', '?'))
            d = am.change(d, lambda doc: doc['t'].delete_at(0, 2))
            assert str(d['t']) == 'llo!?'
            handle = fe.get_backend_state(d)
            assert handle['state'].is_fleet
            assert fb.fleet.metrics.promotions == 0
            assert fleet_backend.materialize_docs([handle]) == \
                [{'t': 'llo!?'}]
            loaded = am.load(am.save(d))
            assert str(loaded['t']) == 'llo!?'
        finally:
            am.set_default_backend(old)

    def test_turbo_renumber_remaps_seq_rows(self):
        """Turbo applies that insert an early-sorting actor must remap the
        actor bits of live SeqState rows, exactly as flush() does
        (regression: the turbo site skipped _remap_seq_actors, leaving
        stale packed elemIds in every device text row)."""
        from automerge_tpu.columnar import decode_change
        fb = self._fb()
        g1, g2 = fb.init(), fb.init()
        A, early = ACTORS[2], ACTORS[3]     # 'cc…' index 0, then '11…'
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}])
        h1 = decode_change(c1)['hash']
        g1, _ = fleet_backend.apply_changes(g1, [c1])
        fb.fleet.flush()                     # text row live on device
        # flat turbo batch on another doc by an actor sorting before 'cc…'
        flat = change_buf(early, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        handles, _ = fleet_backend.apply_changes_docs([g2], [[flat]],
                                                      mirror=False)
        g2 = handles[0]
        # the text row's packed elemIds must reflect the new numbering:
        # further edits (packed with new actor numbers) must still hit
        c2 = change_buf(A, 2, 3, [
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'pred': [f'2@{A}']}], deps=[h1])
        g1, _ = fleet_backend.apply_changes(g1, [c2])
        assert fleet_backend.materialize_docs([g1, g2]) == \
            [{'t': ''}, {'k': 1}]
        fb.fleet.flush()
        assert not fb.fleet.seq_row_inexact(0)


class TestTurboSequence:
    """mirror=False applies with sequence ops: op columns go wire -> native
    C++ parse -> SeqState dispatch with no per-op Python objects and no
    mirror work; reads come straight from the device."""

    def _fb(self):
        return FleetBackend(DocFleet(doc_capacity=4, key_capacity=8))

    def _text_changes(self):
        from automerge_tpu.columnar import decode_change
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'b', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': True, 'value': 'c', 'pred': []}])
        h1 = decode_change(c1)['hash']
        c2 = change_buf(A, 2, 5, [
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'pred': [f'3@{A}']}], deps=[h1])
        return c1, c2

    def test_turbo_text_no_mirror_no_fallback(self):
        fb = self._fb()
        g = fb.init()
        c1, c2 = self._text_changes()
        handles, _ = fleet_backend.apply_changes_docs([g], [[c1, c2]],
                                                      mirror=False)
        assert fb.fleet.metrics.fallbacks == 0
        assert fb.fleet.metrics.turbo_calls == 1
        assert fleet_backend.materialize_docs(handles) == [{'t': 'ac'}]
        # the device served the read: no lazy mirror rebuild happened
        assert fb.fleet.metrics.mirror_rebuilds == 0
        assert not fb.fleet.seq_row_inexact(0)

    def test_turbo_text_differential_vs_exact(self):
        """Turbo and exact paths produce identical patches and bytes."""
        fb, fb2 = self._fb(), self._fb()
        g, g2 = fb.init(), fb2.init()
        c1, c2 = self._text_changes()
        A = ACTORS[0]
        handles, _ = fleet_backend.apply_changes_docs([g], [[c1, c2]],
                                                      mirror=False)
        c3 = change_buf(A, 3, 6, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'4@{A}',
             'insert': True, 'values': ['€', 'x'], 'pred': []}],
            deps=fleet_backend.get_heads(handles[0]))
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c3]],
                                                      mirror=False)
        assert fb.fleet.metrics.fallbacks == 0
        for c in (c1, c2, c3):
            g2, _ = fleet_backend.apply_changes(g2, [c])
        assert fleet_backend.materialize_docs(handles) == [{'t': 'ac€x'}]
        assert fleet_backend.get_patch(handles[0]) == \
            fleet_backend.get_patch(g2)
        assert bytes(fleet_backend.save(handles[0])) == \
            bytes(fleet_backend.save(g2))

    def test_turbo_seq_register_mode(self):
        """Turbo sequence dispatch under exact_device (register) mode."""
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=8,
                                   exact_device=True))
        g = fb.init()
        c1, c2 = self._text_changes()
        handles, _ = fleet_backend.apply_changes_docs([g], [[c1, c2]],
                                                      mirror=False)
        assert fb.fleet.metrics.fallbacks == 0
        assert fleet_backend.materialize_docs(handles) == [{'t': 'ac'}]

    def test_turbo_unknown_seq_object_falls_back(self):
        """Ops on an object the fleet has never seen route to the exact
        path (which raises the reference's error)."""
        A = ACTORS[0]
        fb = self._fb()
        g = fb.init()
        bogus = change_buf(A, 1, 1, [
            {'action': 'set', 'obj': f'9@{A}', 'elemId': '_head',
             'insert': True, 'value': 'x', 'pred': []}])
        with pytest.raises(ValueError, match='unknown object'):
            fleet_backend.apply_changes_docs([g], [[bogus]], mirror=False)


class TestTurboNestedMaps:
    """Nested map/table changes take the native turbo wire->device path
    (the parser emits keyed rows with their containing object; makes
    flag-code as 9/10) — no fallback to the Python decode."""

    @pytest.mark.parametrize('exact', [False, True])
    def test_nested_tree_through_turbo(self, exact):
        from automerge_tpu.columnar import encode_change, decode_change_meta
        A1 = ACTORS[0]
        fleet = DocFleet(doc_capacity=4, key_capacity=16,
                         exact_device=exact)
        fb = FleetBackend(fleet)
        handles = [fb.init() for _ in range(2)]
        per_doc = []
        for d in range(2):
            c1 = encode_change({
                'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0,
                'message': '', 'deps': [], 'ops': [
                    {'action': 'makeMap', 'obj': '_root', 'key': 'cfg',
                     'pred': []},
                    {'action': 'set', 'obj': f'1@{A1}', 'key': 'x',
                     'value': 5 + d, 'datatype': 'int', 'pred': []},
                    {'action': 'makeTable', 'obj': '_root', 'key': 'tbl',
                     'pred': []}]})
            heads = [decode_change_meta(c1, True)['hash']]
            c2 = encode_change({
                'actor': A1, 'seq': 2, 'startOp': 4, 'time': 0,
                'message': '', 'deps': heads, 'ops': [
                    {'action': 'set', 'obj': f'1@{A1}', 'key': 'y',
                     'value': 7, 'datatype': 'int', 'pred': []},
                    {'action': 'del', 'obj': f'1@{A1}', 'key': 'x',
                     'pred': [f'2@{A1}']},
                    {'action': 'set', 'obj': '_root', 'key': 'top',
                     'value': 1, 'datatype': 'int', 'pred': []}]})
            per_doc.append([c1, c2])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        assert fleet.metrics.turbo_calls == 1
        assert fleet.metrics.fallbacks == 0
        mats = fleet_backend.materialize_docs(handles)
        assert mats == [{'cfg': {'y': 7}, 'tbl': {}, 'top': 1}] * 2
        if exact:
            # nested patches still device-served after turbo
            patch = fleet_backend.get_patch(handles[0])
            cfg = patch['diffs']['props']['cfg'][f'1@{A1}']
            assert cfg['props']['y'] == {
                f'4@{A1}': {'type': 'value', 'value': 7,
                            'datatype': 'int'}}
            assert fleet.metrics.mirror_rebuilds == 0

    @pytest.mark.parametrize('exact', [False, True])
    def test_boxed_values_ride_turbo(self, exact):
        """Strings, bools, None, floats, negative ints, and nested trees
        built with the real frontend all take the turbo wire->device path
        (the native parser boxes non-inline payloads via its value arena)
        with reads and patches identical to the host."""
        import automerge_tpu as A
        fleet = DocFleet(doc_capacity=8, key_capacity=64,
                         exact_device=exact)
        src = []
        for i in range(3):
            d = A.from_({'cfg': {'name': f'doc{i}', 'opts': {'d': 2}},
                         'tbl': A.Table(), 'n': i, 'f': 2.5, 'ok': True,
                         'nil': None, 'neg': -7}, ACTORS[0])
            d = A.change(d, lambda r: (
                r['cfg'].__setitem__('rev', 3),
                r['tbl'].add({'row': 'textual'})))
            d = A.change(d, lambda r: r['cfg']['opts'].__setitem__(
                'extra', 'yes!'))
            src.append(d)
        per_doc = [[bytes(c) for c in A.get_all_changes(d)] for d in src]
        fb = FleetBackend(fleet)
        handles = [fb.init() for _ in src]
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        assert fleet.metrics.turbo_calls == 1
        assert fleet.metrics.fallbacks == 0
        mats = fleet_backend.materialize_docs(handles)
        assert mats[0]['cfg'] == {'name': 'doc0',
                                  'opts': {'d': 2, 'extra': 'yes!'},
                                  'rev': 3}
        assert mats[1]['f'] == 2.5 and mats[1]['nil'] is None
        assert mats[2]['neg'] == -7 and mats[2]['ok'] is True
        expected = [host_backend.get_patch(host_backend.load(A.save(d)))
                    for d in src]
        got = [fleet_backend.get_patch(h) for h in handles]
        assert got == expected
        if exact:
            assert fleet.metrics.mirror_rebuilds == 0

    def test_undecodable_boxed_payload_falls_back_cleanly(self):
        """A crafted wire change whose boxed payload decode_value rejects
        (uint64 past the 2^53 read limit — constructible only by a foreign
        or malicious peer, our encoder caps at 53 bits) must route to the
        exact path BEFORE the turbo commit point: the doc stays untouched
        instead of heads/logs advancing around a raised decode."""
        from automerge_tpu.columnar import encode_container, \
            CHUNK_TYPE_CHANGE
        from automerge_tpu.encoding import Encoder, RLEEncoder
        A1 = ACTORS[0]

        def uleb(v):
            out = bytearray()
            while True:
                b = v & 0x7f
                v >>= 7
                out.append(b | (0x80 if v else 0))
                if not v:
                    return bytes(out)

        raw = uleb(2 ** 60)                 # 9-byte LEB128 uint
        ks = RLEEncoder('utf8')
        ks.append_value('x')
        ks.finish()
        act = RLEEncoder('uint')
        act.append_value(1)                 # set
        act.finish()
        vlen = RLEEncoder('uint')
        vlen.append_value((len(raw) << 4) | 3)   # LEB128_UINT tag
        vlen.finish()
        pn = RLEEncoder('uint')
        pn.append_value(0)
        pn.finish()
        cols = [(0x15, ks.buffer), (0x42, act.buffer),
                (0x56, vlen.buffer), (0x57, raw), (0x70, pn.buffer)]
        body = Encoder()
        body.append_uint53(0)               # deps
        body.append_hex_string(A1)
        body.append_uint53(1)               # seq
        body.append_uint53(1)               # startOp
        body.append_int53(0)                # time
        body.append_prefixed_string('')     # message
        body.append_uint53(0)               # other actors
        body.append_uint53(len(cols))
        for cid, buf in cols:
            body.append_uint53(cid)
            body.append_uint53(len(buf))
        for _cid, buf in cols:
            body.append_raw_bytes(buf)
        _h, big = encode_container(CHUNK_TYPE_CHANGE, body.buffer)

        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        fb = FleetBackend(fleet)
        handle = fb.init()
        with pytest.raises(ValueError):
            fleet_backend.apply_changes_docs([handle], [[big]],
                                             mirror=False)
        # the turbo guard bailed pre-commit; the exact path raised with
        # the doc untouched
        assert fleet.metrics.turbo_calls == 0
        assert handle['state'].heads == []
        assert len(handle['state'].changes) == 0

    def test_dangling_nested_object_falls_back(self):
        """A keyed op on an unknown map object routes to the exact path
        (which raises the reference's error) instead of corrupting."""
        from automerge_tpu.columnar import encode_change
        A1 = ACTORS[0]
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        fb = FleetBackend(fleet)
        handle = fb.init()
        bad = encode_change({
            'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
            'deps': [], 'ops': [
                {'action': 'set', 'obj': f'99@{A1}', 'key': 'x',
                 'value': 1, 'datatype': 'int', 'pred': []}]})
        with pytest.raises(Exception):
            fleet_backend.apply_changes_docs([handle], [[bad]],
                                             mirror=False)


class TestSeqSizeClasses:
    """Sequence rows live in pow2 size-class pools (fleet/sequence.py
    SeqPools): memory follows each document's own length, and a long
    document no longer pads the whole fleet's sequence arrays."""

    def _text_doc(self, fb, actor, text):
        import automerge_tpu as A
        from automerge_tpu import backend as _hb
        d = A.from_({'t': A.Text(text)}, actor)
        gb = fb.init()
        gb, _ = fleet_backend.apply_changes(
            gb, [bytes(c) for c in A.get_all_changes(d)])
        return gb

    def test_long_doc_does_not_inflate_small_class(self):
        fleet = DocFleet(doc_capacity=4, key_capacity=8)
        fb = FleetBackend(fleet)
        short = self._text_doc(fb, ACTORS[0], 'hi')
        long = self._text_doc(fb, ACTORS[1], 'x' * 300)
        fleet.flush()
        assert fleet_backend.materialize_docs([short, long]) == \
            [{'t': 'hi'}, {'t': 'x' * 300}]
        pools = fleet.seq_pools
        classes = sorted(pools.pools)
        assert len(classes) >= 2
        # the small class stays at base capacity: the 300-element doc
        # lives in its own class instead of padding everyone
        assert pools.state(classes[0]).capacity == fleet.seq_elem_cap
        assert pools.state(classes[-1]).capacity >= 300
        short_place = fleet.seq_place[fleet.slot_seq[
            short['state']._impl.slot].popitem()[1]]
        assert short_place[0] == classes[0]

    def test_row_migrates_up_classes_preserving_content(self):
        import automerge_tpu as A
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        A.set_default_backend(FleetBackend(fleet))
        try:
            d = A.from_({'t': A.Text('ab')}, ACTORS[0])
            fleet.flush()
            row = next(iter(fleet.slot_seq[list(fleet.slot_seq)[0]].values()))
            first_place = fleet.seq_place[row]
            for chunk in range(6):
                d = A.change(d, lambda r: r['t'].insert_at(
                    len(r['t']), *('y' * 40)))
            fleet.flush()
            assert str(d['t']) == 'ab' + 'y' * 240
            second_place = fleet.seq_place[row]
            assert second_place[0] > first_place[0]   # moved up a class
            # the vacated idx is reusable
            assert first_place[1] in fleet.seq_pools.free.get(
                first_place[0], [])
        finally:
            A.set_default_backend(host_backend)

    def test_tail_sorted_new_actor_widens_lanes(self):
        """A 5th actor whose hex id sorts AFTER all existing actors causes
        no remap (identity perm); the pools must still widen their lane
        axis before its ops apply, or the row would flag inexact and lose
        the device path forever."""
        import automerge_tpu as A
        fleet = DocFleet(doc_capacity=8, key_capacity=8)
        A.set_default_backend(FleetBackend(fleet))
        try:
            first = ['01' * 8, '22' * 8, '44' * 8, '66' * 8]
            base = A.from_({'t': A.Text('abcd')}, first[0])
            replicas = [base] + [A.merge(A.init(a), base) for a in first[1:]]
            for i, rep in enumerate(replicas[1:], start=1):
                replicas[i] = A.change(rep, lambda r, i=i: r['t'].set(i, '!'))
            merged = replicas[0]
            for rep in replicas[1:]:
                merged = A.merge(merged, rep)
            fleet.flush()
            assert len(fleet.actors) == 4
            # 5th actor sorts after every existing one -> identity perm
            late = A.merge(A.init('ff' * 8), merged)
            late = A.change(late, lambda r: r['t'].insert_at(0, 'Z'))
            fleet.flush()
            for row, info in enumerate(fleet.seq_rows):
                if info is not None:
                    assert not fleet.seq_row_inexact(row)
            assert str(late['t']) == 'Za!!!'
        finally:
            A.set_default_backend(host_backend)

    def test_free_slot_releases_pool_rows(self):
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        fb = FleetBackend(fleet)
        gb = self._text_doc(fb, ACTORS[0], 'abc')
        fleet.flush()
        slot = gb['state']._impl.slot
        row = next(iter(fleet.slot_seq[slot].values()))
        place = fleet.seq_place[row]
        assert place is not None
        fleet_backend.free(gb)
        assert place[1] in fleet.seq_pools.free.get(place[0], [])
        # the freed idx is handed to the next allocation in that class
        gb2 = self._text_doc(fb, ACTORS[0], 'def')
        fleet.flush()
        row2 = next(iter(fleet.slot_seq[gb2['state']._impl.slot].values()))
        assert fleet.seq_place[row2] == place
        assert fleet_backend.materialize_docs([gb2]) == [{'t': 'def'}]


class TestValueTableDedup:
    def test_boxed_values_dedup_by_value(self):
        """Repeated boxed values (strings across a long change log) intern
        once: the value table grows with distinct values, not op count
        (round-2 VERDICT weak item 7 — long-run fleet memory leak)."""
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        gb = fb.init()
        heads = []
        for seq in range(1, 21):
            buf = change_buf(ACTORS[0], seq, seq, [
                {'action': 'set', 'obj': '_root', 'key': 'status',
                 'value': 'active' if seq % 2 else 'idle',
                 'pred': [f'{seq - 1}@{ACTORS[0]}'] if seq > 1 else []}],
                deps=heads)
            heads = [am.decode_change(buf)['hash']]
            gb, _ = fleet_backend.apply_changes(gb, [buf])
        fleet = gb['state'].fleet
        fleet.flush()
        boxed = [v for v in fleet.value_table if isinstance(v, str)]
        assert sorted(set(boxed)) == ['active', 'idle']
        assert len(boxed) == 2


class TestCounterRebasing:
    """Packed-opId headroom (round-2 VERDICT item 9): op counters past the
    int32 packing window (CTR_LIMIT = 2^23) rebase the slot's window on
    device instead of crashing or promoting — history length is unbounded;
    only the LIVE counter spread is window-bounded."""

    def _chain(self, start_ops, key_of=None):
        """Chained single-op changes at the given startOps."""
        A = ACTORS[0]
        changes, heads = [], []
        for seq, start in enumerate(start_ops, 1):
            buf = change_buf(A, seq, start, [
                {'action': 'set', 'obj': '_root',
                 'key': key_of(seq) if key_of else 'k',
                 'value': seq, 'datatype': 'int',
                 'pred': []}], deps=heads)
            heads = [am.decode_change(buf)['hash']]
            changes.append(buf)
        return changes

    def test_counters_past_the_window_stay_fleet_resident(self):
        # A long-lived doc whose winners keep advancing (the editing-trace
        # regime): each overwrite moves the live window forward, so rebasing
        # keeps the doc on the grid across multiple windows of history
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        gb = fb.init()
        step = CTR_LIMIT - 100
        starts = [1, step, 2 * step, 3 * step, 4 * step]   # ~4 windows deep
        fleet = gb['state'].fleet
        A = ACTORS[0]
        heads, pred = [], []
        for seq, start in enumerate(starts, 1):
            buf = change_buf(A, seq, start, [
                {'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': seq, 'datatype': 'int', 'pred': pred}],
                deps=heads)
            heads = [am.decode_change(buf)['hash']]
            pred = [f'{start}@{A}']
            gb, _ = fleet_backend.apply_changes(gb, [buf])
            fleet.flush()      # incremental flushes: live window advances
        assert gb['state'].is_fleet
        assert fleet.metrics.promotions == 0
        from automerge_tpu.fleet.backend import materialize_docs
        assert materialize_docs([gb]) == [{'k': len(starts)}]
        # The grid itself served the read (no overflow fallback): the live
        # winner advanced with each overwrite, so every rebase succeeded
        assert gb['state']._impl.slot not in fleet.grid_overflow
        assert fleet.ctr_base[gb['state']._impl.slot] > 0

    def test_irreducible_spread_falls_back_to_mirror(self):
        # A key set once at counter 1 and never touched again, then an op
        # past 2*CTR_LIMIT: the live spread cannot fit one window; reads
        # stay correct via the host mirror, still without promotion
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        gb = fb.init()
        starts = [1, 2 * CTR_LIMIT + 3]
        for buf in self._chain(starts, key_of=lambda s: f'k{s}'):
            gb, _ = fleet_backend.apply_changes(gb, [buf])
        fleet = gb['state'].fleet
        fleet.flush()
        assert gb['state'].is_fleet
        assert fleet.metrics.promotions == 0
        from automerge_tpu.fleet.backend import materialize_docs
        assert materialize_docs([gb]) == [{'k1': 1, 'k2': 2}]
        assert gb['state']._impl.slot in fleet.grid_overflow

    def test_exact_device_promotes_cleanly_at_the_boundary(self):
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4,
                                   exact_device=True))
        gb = fb.init()
        for buf in self._chain([1, CTR_LIMIT + 1], key_of=lambda s: f'k{s}'):
            gb, _ = fleet_backend.apply_changes(gb, [buf])
        # Registers pack raw counters: past the window the doc promotes
        # (pre-commit, no partial state) and stays correct on host
        assert not gb['state'].is_fleet
        assert fleet_backend.get_patch(gb)['diffs']['props']['k2'] == {
            f'{CTR_LIMIT + 1}@{ACTORS[0]}': {
                'type': 'value', 'value': 2, 'datatype': 'int'}}

    def test_clone_carries_counter_window_state(self):
        # A clone of a rebased/overflowed slot must not read its grid row
        # as authoritative with the wrong base (review regression)
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        from automerge_tpu.fleet.backend import materialize_docs
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
        gb = fb.init()
        A = ACTORS[0]
        b1 = change_buf(A, 1, CTR_LIMIT - 10, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 111,
             'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(b1)['hash']
        b2 = change_buf(A, 2, 2 * CTR_LIMIT + 3, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 222,
             'datatype': 'int', 'pred': [f'{CTR_LIMIT - 10}@{A}']}],
            deps=[h1])
        gb, _ = fleet_backend.apply_changes(gb, [b1])
        gb['state'].fleet.flush()
        gb, _ = fleet_backend.apply_changes(gb, [b2])
        gb['state'].fleet.flush()
        clone = fleet_backend.clone(gb)
        assert materialize_docs([gb]) == [{'k': 222}]
        assert materialize_docs([clone]) == [{'k': 222}]

    def test_rebased_slot_does_not_disable_fleet_turbo(self):
        # One long-lived doc crossing the window must not push every OTHER
        # doc in the fleet off the native/turbo paths (review regression)
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        fleet = DocFleet(doc_capacity=4, key_capacity=4)
        fb = FleetBackend(fleet)
        gb = fb.init()
        step = CTR_LIMIT - 100
        heads, pred = [], []
        for seq, start in enumerate([1, step, 2 * step], 1):
            buf = change_buf(ACTORS[0], seq, start, [
                {'action': 'set', 'obj': '_root', 'key': 'k', 'value': seq,
                 'datatype': 'int', 'pred': pred}], deps=heads)
            heads = [am.decode_change(buf)['hash']]
            pred = [f'{start}@{ACTORS[0]}']
            gb, _ = fleet_backend.apply_changes(gb, [buf])
            fleet.flush()
        assert fleet.ctr_base          # the long doc rebased
        other = fb.init()
        before = fleet.metrics.turbo_calls
        handles, _ = fleet_backend.apply_changes_docs(
            [other], [[change_buf(ACTORS[1], 1, 1, [
                {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
                 'datatype': 'int', 'pred': []}])]], mirror=False)
        assert fleet.metrics.turbo_calls == before + 1


class TestRegisterPatches:
    """Exact-device get_patch comes straight from RegisterState — no mirror
    rebuild (round-2 VERDICT item 10). Differentially equal to the host
    backend's patch on the same history."""

    def _scenarios(self):
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'bird',
             'value': 'magpie', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'n', 'value': 7,
             'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'u', 'value': 3,
             'datatype': 'uint', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'f', 'value': 2.5,
             'datatype': 'float64', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'ok', 'value': True,
             'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'nothing',
             'value': None, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'when',
             'value': 1589032171000, 'datatype': 'timestamp', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'score', 'value': 10,
             'datatype': 'counter', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        # concurrent conflicting writes + counter inc + delete
        c2 = change_buf(A, 2, 9, [
            {'action': 'inc', 'obj': '_root', 'key': 'score', 'value': 5,
             'pred': [f'8@{A}']},
            {'action': 'del', 'obj': '_root', 'key': 'nothing',
             'pred': [f'6@{A}']}], deps=[h1])
        c3 = change_buf(B, 1, 9, [
            {'action': 'set', 'obj': '_root', 'key': 'bird',
             'value': 'wren', 'pred': [f'1@{A}']}], deps=[h1])
        return [c1, c2, c3]

    def test_patch_differential_and_no_mirror_rebuilds(self):
        changes = self._scenarios()
        hb = host_backend.init()
        for c in changes:
            hb, _ = host_backend.apply_changes(hb, [c])
        expected = host_backend.get_patch(hb)

        fleet = DocFleet(doc_capacity=2, key_capacity=16, exact_device=True)
        fb = FleetBackend(fleet)
        gb = fb.init()
        for c in changes:
            gb, _ = fleet_backend.apply_changes(gb, [c])
        got = fleet_backend.get_patch(gb)
        assert got == expected
        assert gb['state'].is_fleet
        assert fleet.metrics.mirror_rebuilds == 0

    def test_typed_values_survive_mixed_exact_flush(self):
        """A flush batch mixing one doc's typed root sets (counter + inc)
        with another doc's sequence ops routes through _flush_exact_mixed —
        which must box datatypes like changes_to_op_rows does, or the
        device-served patch degrades counters to plain ints."""
        changes = self._scenarios()
        hb = host_backend.init()
        for c in changes:
            hb, _ = host_backend.apply_changes(hb, [c])
        expected = host_backend.get_patch(hb)

        fleet = DocFleet(doc_capacity=4, key_capacity=16, exact_device=True)
        fb = FleetBackend(fleet)
        gb = fb.init()
        other = fb.init()
        A = ACTORS[0]
        seq_change = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'x', 'pred': []}])
        for c in changes:
            gb, _ = fleet_backend.apply_changes(gb, [c])
        # same pending batch: forces the mixed exact flush for every doc
        other, _ = fleet_backend.apply_changes(other, [seq_change])
        fleet.flush()
        got = fleet_backend.get_patch(gb)
        assert got == expected
        assert fleet.metrics.mirror_rebuilds == 0

    def test_typed_values_survive_turbo_exact(self):
        """The turbo wire->device path on an exact fleet must box typed
        root sets (counter/uint/timestamp) before the register dispatch so
        device-served patches keep datatypes and counter folds."""
        changes = self._scenarios()
        hb = host_backend.init()
        for c in changes:
            hb, _ = host_backend.apply_changes(hb, [c])
        expected = host_backend.get_patch(hb)

        fleet = DocFleet(doc_capacity=2, key_capacity=16, exact_device=True)
        fb = FleetBackend(fleet)
        handles = [fb.init()]
        handles, patches = fleet_backend.apply_changes_docs(
            handles, [changes], mirror=False)
        if fleet.metrics.turbo_calls:
            got = fleet_backend.get_patch(handles[0])
            assert got == expected
            assert fleet.metrics.mirror_rebuilds == 0

    def _differential(self, changes, turbo=False):
        """Apply `changes` to host and exact fleet; device patch must equal
        the host patch with zero mirror rebuilds."""
        hb = host_backend.init()
        for c in changes:
            hb, _ = host_backend.apply_changes(hb, [c])
        expected = host_backend.get_patch(hb)
        fleet = DocFleet(doc_capacity=2, key_capacity=32, exact_device=True)
        fb = FleetBackend(fleet)
        gb = fb.init()
        if turbo:
            handles, _ = fleet_backend.apply_changes_docs(
                [gb], [list(changes)], mirror=False)
            gb = handles[0]
        else:
            for c in changes:
                gb, _ = fleet_backend.apply_changes(gb, [c])
        got = fleet_backend.get_patch(gb)
        assert got == expected
        assert fleet.metrics.mirror_rebuilds == 0
        return fleet, gb

    def test_text_patch_from_device(self):
        """Whole-doc patches for text documents come straight from the
        device sequence registers (round-3 extension of VERDICT item 10)."""
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 't', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 'h', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 'i', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(B, 1, 4, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'value': 'H', 'pred': [f'2@{A}']},
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'pred': [f'3@{A}']}], deps=[h1])
        for turbo in (False, True):
            self._differential([c1, c2], turbo=turbo)

    def test_list_conflict_and_resurrection_patch_from_device(self):
        """Concurrent set-vs-set (conflict edits) and set-vs-del
        (resurrection) on list elements patch identically to the host."""
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 1, 'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 2, 'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(A, 2, 4, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'value': 10, 'datatype': 'int', 'pred': [f'2@{A}']}],
            deps=[h1])
        c3 = change_buf(B, 1, 4, [
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'value': 20, 'datatype': 'int', 'pred': [f'2@{A}']},
            {'action': 'del', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'pred': [f'3@{A}']}], deps=[h1])
        for turbo in (False, True):
            self._differential([c1, c2, c3], turbo=turbo)

    def test_nested_tree_patch_from_device(self):
        """Nested map/table trees patch from the two-level device grid."""
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeMap', 'obj': '_root', 'key': 'cfg', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'key': 'inner', 'value': 5,
             'datatype': 'int', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{A}', 'key': 'deep',
             'pred': []},
            {'action': 'set', 'obj': f'3@{A}', 'key': 'leaf',
             'value': 'v', 'pred': []},
            {'action': 'makeTable', 'obj': '_root', 'key': 'tbl',
             'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'top', 'value': True,
             'pred': []}])
        for turbo in (False, True):
            self._differential([c1], turbo=turbo)

    def test_objects_inside_lists_patch_from_device(self):
        """Rows-in-lists serve whole-doc patches straight from the device
        registers (round 4): the make element rows flow through the same
        child-linking path map cells use, no mirror rebuild."""
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'todo',
             'pred': []},
            {'action': 'makeMap', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'pred': []},
            {'action': 'set', 'obj': f'2@{A}', 'key': 't', 'value': 'wash',
             'pred': []},
            {'action': 'makeList', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'pred': []},
            {'action': 'set', 'obj': f'4@{A}', 'elemId': '_head',
             'insert': True, 'value': 7, 'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'4@{A}',
             'insert': True, 'value': 3, 'datatype': 'int', 'pred': []}])
        h1 = am.decode_change(c1)['hash']
        c2 = change_buf(A, 2, 7, [
            {'action': 'set', 'obj': f'2@{A}', 'key': 'n', 'value': 5,
             'datatype': 'int', 'pred': []}], deps=[h1])
        for turbo in (False, True):
            self._differential([c1, c2], turbo=turbo)

    def test_typed_list_elements_patch_from_device(self):
        """uint/timestamp/float64 list elements keep their datatypes in
        device-served patches (TypedValue boxing on the seq paths)."""
        A = ACTORS[0]
        c1 = change_buf(A, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': '_head',
             'insert': True, 'value': 3, 'datatype': 'uint', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'2@{A}',
             'insert': True, 'value': 1589032171000,
             'datatype': 'timestamp', 'pred': []},
            {'action': 'set', 'obj': f'1@{A}', 'elemId': f'3@{A}',
             'insert': True, 'value': 2.5, 'datatype': 'float64',
             'pred': []}])
        for turbo in (False, True):
            fleet, gb = self._differential([c1], turbo=turbo)
            # reads unwrap the boxed TypedValues back to plain payloads
            assert fleet_backend.materialize_docs([gb]) == \
                [{'l': [3, 1589032171000, 2.5]}]

    def test_conflict_patch_from_device(self):
        A, B = ACTORS[0], ACTORS[1]
        c1 = change_buf(A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        c2 = change_buf(B, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'value': 2,
             'datatype': 'int', 'pred': []}])
        fleet = DocFleet(doc_capacity=2, key_capacity=4, exact_device=True)
        fb = FleetBackend(fleet)
        gb = fb.init()
        gb, _ = fleet_backend.apply_changes(gb, [c1])
        gb, _ = fleet_backend.apply_changes(gb, [c2])
        patch = fleet_backend.get_patch(gb)
        assert patch['diffs']['props']['x'] == {
            f'1@{A}': {'type': 'value', 'value': 1, 'datatype': 'int'},
            f'1@{B}': {'type': 'value', 'value': 2, 'datatype': 'int'}}
        assert fleet.metrics.mirror_rebuilds == 0


class TestBulkInitEquivalence:
    def test_bulk_init_matches_constructor(self):
        """init_docs' allocation-only constructor (_FlatEngine._bulk_new)
        must initialize exactly the attributes the real constructor chain
        does — the keep-in-sync contract for the bulk fast path."""
        from automerge_tpu.fleet.backend import _FlatEngine

        fleet = DocFleet(doc_capacity=4, key_capacity=4)
        via_bulk = fleet_backend.init_docs(1, fleet)[0]['state']._impl
        via_ctor = _FlatEngine(fleet, fleet.alloc_slot())

        def slot_attrs(obj):
            out = {}
            for klass in type(obj).__mro__:
                for name in getattr(klass, '__slots__', ()):
                    if hasattr(obj, name):
                        out[name] = type(getattr(obj, name))
            return out

        a, b = slot_attrs(via_bulk), slot_attrs(via_ctor)
        assert a == b
        # every HashGraph slot must be live on both (nothing skipped) —
        # several are property shadows over the fleet's _DocCols columns
        # (heads/clock/max_op/changes/_deferred), which hasattr resolves
        # the same way
        from automerge_tpu.backend.hash_graph import HashGraph
        for name in HashGraph.__slots__:
            assert name in a, name


class TestBatchedInitFreeDispatches:
    """O(1)-dispatch contracts for the batched host-side seam paths: an
    N-doc init and an N-doc free must issue a size-independent number of
    device dispatches (DocFleet.dispatches()), and the batched paths must
    produce state identical to the per-doc paths they replace."""

    def _seed(self, handles, n_changes=2):
        per_doc = []
        for d in range(len(handles)):
            changes, heads = [], []
            for c in range(n_changes):
                buf = change_buf(ACTORS[d % 3], c + 1, c + 1, [
                    {'action': 'set', 'obj': '_root', 'key': f'k{c}',
                     'value': d * 10 + c, 'datatype': 'int', 'pred': []}],
                    deps=heads)
                heads = [am.decode_change(buf)['hash']]
                changes.append(buf)
            per_doc.append(changes)
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        return handles

    def test_init_docs_dispatches_size_independent(self):
        counts = {}
        for n in (4, 32):
            fb = FleetBackend(DocFleet(doc_capacity=64, key_capacity=8))
            # materialize device state first: a fresh fleet's lazy init
            # would trivially dispatch nothing
            seeded = self._seed(fleet_backend.init_docs(1, fb.fleet))
            fb.fleet.flush()
            before = fb.fleet.dispatches
            handles = fleet_backend.init_docs(n, fb.fleet)
            counts[n] = fb.fleet.dispatches - before
            handles = self._seed(handles)
            assert fleet_backend.materialize_docs(handles) == \
                [{'k0': d * 10, 'k1': d * 10 + 1} for d in range(n)]
        assert counts[4] == counts[32], counts
        assert counts[32] <= 2, counts   # grid (+ registers when present)

    def test_init_docs_fresh_fleet_zero_dispatches(self):
        fb = FleetBackend(DocFleet(doc_capacity=64, key_capacity=8))
        before = fb.fleet.dispatches
        fleet_backend.init_docs(32, fb.fleet)
        assert fb.fleet.dispatches == before   # lazy: first flush allocates

    def test_free_docs_dispatches_size_independent(self):
        counts = {}
        for n in (4, 16):
            fb = FleetBackend(DocFleet(doc_capacity=32, key_capacity=8))
            handles = self._seed(fleet_backend.init_docs(n, fb.fleet))
            fb.fleet.flush()
            before = fb.fleet.dispatches
            fleet_backend.free_docs(handles)
            counts[n] = fb.fleet.dispatches - before
            assert all(h['state'] is None and h['frozen'] for h in handles)
        assert counts[4] == counts[16], counts
        assert counts[16] <= 2, counts

    def test_alloc_slots_zero_is_noop(self):
        """alloc_slots(0) must not touch the free list or n_slots (the
        [-0:] slice aliases the whole list; a 0-doc init or an all-bad
        bulk load would otherwise hand live slots to the next alloc)."""
        fb = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        handles = self._seed(fleet_backend.init_docs(3, fb.fleet))
        fleet_backend.free_docs(handles[1:2])
        free_before = list(fb.fleet.free_slots)
        n_before = fb.fleet.n_slots
        assert fb.fleet.alloc_slots(0) == []
        assert fb.fleet.free_slots == free_before
        assert fb.fleet.n_slots == n_before

    def test_free_docs_matches_per_doc_free(self):
        """Batched free leaves device state identical to the per-doc
        free() chain: same zeroed rows, same recycled slots on re-init."""
        fleets = []
        for batched in (False, True):
            fb = FleetBackend(DocFleet(doc_capacity=16, key_capacity=8))
            handles = self._seed(fleet_backend.init_docs(6, fb.fleet))
            fb.fleet.flush()
            victims = [handles[i] for i in (1, 3, 4)]
            if batched:
                fleet_backend.free_docs(victims)
            else:
                for h in victims:
                    fleet_backend.free(h)
            survivors = [handles[i] for i in (0, 2, 5)]
            assert fleet_backend.materialize_docs(survivors) == \
                [{'k0': d * 10, 'k1': d * 10 + 1} for d in (0, 2, 5)]
            fleets.append(fb.fleet)
        a, b = fleets
        assert np.array_equal(np.asarray(a.state.winners),
                              np.asarray(b.state.winners))
        assert np.array_equal(np.asarray(a.state.values),
                              np.asarray(b.state.values))
        assert sorted(a.free_slots) == sorted(b.free_slots)
        # recycled slots hand out in the same order afterwards
        assert a.alloc_slots(3) == [b.alloc_slot() for _ in range(3)]

    def test_batched_init_matches_per_doc_init(self):
        """init_docs handles are byte-identical (materialize + save) to
        per-doc FleetBackend.init() handles under the same turbo applies."""
        fb1 = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        fb2 = FleetBackend(DocFleet(doc_capacity=8, key_capacity=8))
        batched = fleet_backend.init_docs(4, fb1.fleet)
        perdoc = [fb2.init() for _ in range(4)]
        batched = self._seed(batched)
        perdoc = self._seed(perdoc)
        assert fleet_backend.materialize_docs(batched) == \
            fleet_backend.materialize_docs(perdoc)
        for hb, hp in zip(batched, perdoc):
            assert fleet_backend.get_heads(hb) == fleet_backend.get_heads(hp)
            assert bytes(fleet_backend.save(hb)) == \
                bytes(fleet_backend.save(hp))


class TestDeleteResurrection:
    """Pred-scoped delete semantics in the default (LWW grid) mode, ref
    new.js:1204-1217 / test/new_backend_test.js:1660-class histories: a
    delete kills ONLY the ops it preds. A concurrent set the delete never
    saw stays visible — even when the delete's own opId packs higher —
    and a causally-later straggler set resurrects a deleted key."""

    A, B = 'aa' * 16, 'bb' * 16   # sorted: A -> actor 0, B -> actor 1

    def _chain(self):
        from automerge_tpu.columnar import decode_change_meta
        c1 = change_buf(self.A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change_meta(c1, True)['hash']
        # concurrent wrt each other; the del's packed id (2@B) is HIGHER
        # than the concurrent set's (2@A)
        c_del = change_buf(self.B, 1, 2, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{self.A}']}], deps=[h1])
        c_set = change_buf(self.A, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 7,
             'datatype': 'int', 'pred': [f'1@{self.A}']}], deps=[h1])
        return c1, c_del, c_set

    def _host_result(self, batches):
        doc = am.init()
        for chs in batches:
            doc, _ = am.apply_changes(doc, [bytes(b) for b in chs])
        return dict(doc)

    @pytest.mark.parametrize('mirror', [True, False])
    def test_concurrent_del_and_set_same_batch(self, mirror):
        c1, c_del, c_set = self._chain()
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1, c_del, c_set]], mirror=mirror)
        want = self._host_result([[c1, c_del, c_set]])
        assert fleet_backend.materialize_docs(handles) == [want]
        assert want == {'k': 7}   # the un-pred'd set survives

    @pytest.mark.parametrize('mirror', [True, False])
    def test_concurrent_del_then_set_across_batches(self, mirror):
        """Standing-winner kill first, then the concurrent set arrives in
        a LATER apply: the key must resurrect with the set's value."""
        c1, c_del, c_set = self._chain()
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1, c_del]], mirror=mirror)
        assert fleet_backend.materialize_docs(handles) == [{}]
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c_set]], mirror=mirror)
        want = self._host_result([[c1, c_del], [c_set]])
        assert fleet_backend.materialize_docs(handles) == [want] == [{'k': 7}]

    @pytest.mark.parametrize('mirror', [True, False])
    def test_delete_still_deletes_when_it_pred_everything(self, mirror):
        c1, c_del, _ = self._chain()
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1, c_del]], mirror=mirror)
        assert fleet_backend.materialize_docs(handles) == \
            [self._host_result([[c1, c_del]])] == [{}]

    @pytest.mark.parametrize('mirror', [True, False])
    def test_set_after_delete_overwrites(self, mirror):
        """A set that preds the delete's surviving state (normal causal
        overwrite after deletion) lands as usual."""
        from automerge_tpu.columnar import decode_change_meta
        c1, c_del, _ = self._chain()
        h_del = decode_change_meta(c_del, True)['hash']
        c_new = change_buf(self.B, 2, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
             'datatype': 'int', 'pred': []}], deps=[h_del])
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1, c_del, c_new]], mirror=mirror)
        want = self._host_result([[c1, c_del, c_new]])
        assert fleet_backend.materialize_docs(handles) == [want] == \
            [{'k': 9}]


class TestDeleteHiddenLosers:
    """Round-5 review finds: the single-winner grid cannot resurrect a
    concurrent LOSER it never stored. (1) When a delete clears a standing
    winner while other visible ops remain from earlier batches, the slot
    must go mirror-authoritative and reads must still match the
    reference. (2) The host winner mirror must replicate the device's
    same-batch lane masking, or later counter-attribution checks pass
    against a winner the device never kept."""

    A, B, C = 'aa' * 16, 'bb' * 16, 'cc' * 16

    def _host(self, batches):
        doc = am.init()
        for chs in batches:
            doc, _ = am.apply_changes(doc, [bytes(b) for b in chs])
        return dict(doc)

    def test_cross_batch_kill_with_hidden_loser(self):
        """Batch 1: concurrent sets 1@A (loses LWW) and 1@C (wins).
        Batch 2: delete preds ONLY 1@C. Reference: 1@A resurrects
        (k = 5). The grid dropped 1@A's value, so the slot must fall
        back to the mirror and still answer k = 5."""
        from automerge_tpu.columnar import decode_change_meta
        cA = change_buf(self.A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 5,
             'datatype': 'int', 'pred': []}])
        cC = change_buf(self.C, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
             'datatype': 'int', 'pred': []}])
        hC = decode_change_meta(cC, True)['hash']
        c_del = change_buf(self.B, 1, 2, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{self.C}']}], deps=[hC])
        for mirror in (True, False):
            fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
            handles = fleet_backend.init_docs(1, fb.fleet)
            handles, _ = fleet_backend.apply_changes_docs(
                handles, [[cA, cC]], mirror=mirror)
            handles, _ = fleet_backend.apply_changes_docs(
                handles, [[c_del]], mirror=mirror)
            want = self._host([[cA, cC], [c_del]])
            got = fleet_backend.materialize_docs(handles)
            assert got == [want] == [{'k': 5}], f'mirror={mirror}: {got}'
            fb.fleet.flush()
            slot = handles[0]['state']._impl.slot
            assert slot in fb.fleet.del_fallback

    def test_mirror_replicates_same_batch_lane_masking(self):
        """Same batch: set 2@B (pred 1@A), del pred [2@B], concurrent
        set 2@A. Device winner is 2@A; the mirror must agree — and a
        later inc pred'ing the dead 2@B must flag, not pass."""
        from automerge_tpu.columnar import decode_change_meta
        c1 = change_buf(self.A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change_meta(c1, True)['hash']
        cB = change_buf(self.B, 1, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{self.A}']}], deps=[h1])
        hB = decode_change_meta(cB, True)['hash']
        c_del = change_buf(self.C, 1, 3, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'2@{self.B}']}], deps=[hB])
        cA2 = change_buf(self.A, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 7,
             'datatype': 'int', 'pred': [f'1@{self.A}']}], deps=[h1])
        for mirror in (True, False):
            fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
            handles = fleet_backend.init_docs(1, fb.fleet)
            handles, _ = fleet_backend.apply_changes_docs(
                handles, [[c1, cB, c_del, cA2]], mirror=mirror)
            want = self._host([[c1, cB, c_del, cA2]])
            got = fleet_backend.materialize_docs(handles)
            assert got == [want] == [{'k': 7}], f'mirror={mirror}: {got}'
            fleet = fb.fleet
            fleet.flush()
            fleet._fold_pending_winners()
            slot = handles[0]['state']._impl.slot
            kx = fleet.keys.index['k']
            a_num = fleet.actors.index[self.A]
            # mirror holds the device's winner 2@A, not the masked 2@B
            assert int(fleet.host_winners[slot, kx]) == (2 << 8) | a_num, \
                f'mirror={mirror}'


class TestDeleteChains:
    """Round-5 second-review finds: same-batch supersession chains and
    shared preds across concurrent ops — shapes where single-winner
    bookkeeping is provably insufficient, so the slot must serve reads
    from the exact mirror and match the reference."""

    A, B, C = 'aa' * 16, 'bb' * 16, 'cc' * 16

    def _host(self, batches):
        doc = am.init()
        for chs in batches:
            doc, _ = am.apply_changes(doc, [bytes(b) for b in chs])
        return dict(doc)

    @pytest.mark.parametrize('mirror', [True, False])
    def test_set_then_delete_same_batch_after_standing_winner(self, mirror):
        """Batch 1: set k=1 (1@A). Batch 2 (one flush): overwrite set
        k=2 (2@A pred 1@A) then del (3@A pred 2@A). Reference: key
        deleted. An ordinary sequential edit split across two syncs."""
        from automerge_tpu.columnar import decode_change_meta
        c1 = change_buf(self.A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change_meta(c1, True)['hash']
        c2 = change_buf(self.A, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{self.A}']}], deps=[h1])
        h2 = decode_change_meta(c2, True)['hash']
        c3 = change_buf(self.A, 3, 3, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'2@{self.A}']}], deps=[h2])
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1]], mirror=mirror)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c2, c3]], mirror=mirror)
        want = self._host([[c1], [c2, c3]])
        got = fleet_backend.materialize_docs(handles)
        assert got == [want] == [{}], f'mirror={mirror}: {got}'

    @pytest.mark.parametrize('mirror', [True, False])
    def test_concurrent_ops_sharing_a_pred(self, mirror):
        """Concurrent set 2@A and del 2@B both pred the same 1@A (both
        causally saw only it), with a hidden concurrent loser 1@C from
        batch 1; batch 3 deletes the surviving winner. Reference: the
        hidden loser 1@C resurrects (k = 9)."""
        from automerge_tpu.columnar import decode_change_meta
        cA = change_buf(self.A, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 5,
             'datatype': 'int', 'pred': []}])
        hA = decode_change_meta(cA, True)['hash']
        cC = change_buf(self.C, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
             'datatype': 'int', 'pred': []}])
        set2 = change_buf(self.A, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 6,
             'datatype': 'int', 'pred': [f'1@{self.A}']}], deps=[hA])
        h2 = decode_change_meta(set2, True)['hash']
        del2 = change_buf(self.B, 1, 2, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'1@{self.A}']}], deps=[hA])
        hd = decode_change_meta(del2, True)['hash']
        del3 = change_buf(self.B, 2, 3, [
            {'action': 'del', 'obj': '_root', 'key': 'k',
             'pred': [f'2@{self.A}']}], deps=sorted([h2, hd]))
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=4))
        handles = fleet_backend.init_docs(1, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[cA, cC]], mirror=mirror)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[set2, del2]], mirror=mirror)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[del3]], mirror=mirror)
        want = self._host([[cA, cC], [set2, del2], [del3]])
        got = fleet_backend.materialize_docs(handles)
        assert got == [want] == [{'k': 9}], f'mirror={mirror}: {got}'


class TestTurboDanglingPreds:
    """Round-5 VERDICT item 4: the turbo path rejects dangling preds at
    apply time with the exact path's error and full rollback, instead of
    deferring detection to the next mirror rebuild."""

    def _setup_turbo(self, exact=False):
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=8,
                                   exact_device=exact))
        handles = fleet_backend.init_docs(1, fb.fleet)
        setup = change_buf(ACTORS[0], 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 1,
             'datatype': 'int', 'pred': []}])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[setup]],
                                                      mirror=False)
        return fb, handles

    @pytest.mark.parametrize('exact', [False, True])
    def test_dangling_pred_raises_and_rolls_back(self, exact):
        fb, handles = self._setup_turbo(exact)
        heads = handles[0]['heads']
        bad = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
             'datatype': 'int', 'pred': [f'9@{ACTORS[1]}']}], deps=heads)
        with pytest.raises(ValueError,
                           match='no matching operation for pred'):
            fleet_backend.apply_changes_docs(handles, [[bad]], mirror=False)
        # state unchanged, handle still live
        assert handles[0]['state'].heads == heads
        assert fleet_backend.materialize_docs(handles) == [{'k': 1}]

    @pytest.mark.parametrize('exact', [False, True])
    def test_dangling_inc_pred_raises(self, exact):
        fb, handles = self._setup_turbo(exact)
        bad = change_buf(ACTORS[0], 2, 2, [
            {'action': 'inc', 'obj': '_root', 'key': 'k', 'value': 1,
             'pred': [f'7@{ACTORS[0]}']}], deps=handles[0]['heads'])
        with pytest.raises(ValueError,
                           match='no matching operation for pred'):
            fleet_backend.apply_changes_docs(handles, [[bad]], mirror=False)

    def test_valid_preds_still_apply(self):
        """Overwrites pred'ing standing ops, batch-internal preds, and
        preds resolved via the op index across separate turbo calls."""
        from automerge_tpu.columnar import decode_change_meta
        fb, handles = self._setup_turbo()
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=handles[0]['heads'])
        h2 = decode_change_meta(c2, True)['hash']
        c3 = change_buf(ACTORS[0], 3, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 3,
             'datatype': 'int', 'pred': [f'2@{ACTORS[0]}']}], deps=[h2])
        # same batch (batch-internal pred) ...
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c2, c3]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{'k': 3}]
        # ... and across calls (standing-index pred)
        c4 = change_buf(ACTORS[0], 4, 4, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 4,
             'datatype': 'int', 'pred': [f'3@{ACTORS[0]}']}],
            deps=handles[0]['heads'])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c4]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{'k': 4}]

    def test_mixed_exact_then_turbo_pred_resolves(self):
        """Ops applied via the EXACT path must be visible to the turbo
        pred check (index fed from every ingest path)."""
        fb, handles = self._setup_turbo()
        c2 = change_buf(ACTORS[1], 1, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'm', 'value': 5,
             'datatype': 'int', 'pred': []}], deps=handles[0]['heads'])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c2]],
                                                      mirror=True)
        c3 = change_buf(ACTORS[1], 2, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'm', 'value': 6,
             'datatype': 'int', 'pred': [f'2@{ACTORS[1]}']}],
            deps=handles[0]['heads'])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c3]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{'k': 1, 'm': 6}]

    def test_loaded_docs_validate_preds(self):
        """Bulk-loaded docs feed the op index at LOAD time (round-5
        VERDICT weak #6 closed): a dangling pred against loaded history
        raises the exact path's error with full rollback, while valid
        preds against loaded ops still apply."""
        from automerge_tpu.fleet.loader import load_docs
        fb, handles = self._setup_turbo()
        data = fleet_backend.save(handles[0])
        fresh = DocFleet(doc_capacity=2, key_capacity=8)
        loaded = load_docs([data], fresh)
        assert fresh.metrics.docs_bulk_loaded == 1   # native path taken
        heads = loaded[0]['heads']
        bad = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 9,
             'datatype': 'int', 'pred': [f'9@{ACTORS[1]}']}], deps=heads)
        with pytest.raises(ValueError,
                           match='no matching operation for pred'):
            fleet_backend.apply_changes_docs(loaded, [[bad]], mirror=False)
        assert loaded[0]['state'].heads == heads     # rolled back
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}], deps=heads)
        loaded, _ = fleet_backend.apply_changes_docs(loaded, [[c2]],
                                                     mirror=False)
        assert fleet_backend.materialize_docs(loaded) == [{'k': 2}]

    def test_loaded_docs_validate_overwritten_pred(self):
        """An op pred'ing a LOADED, already-overwritten op is still valid
        (concurrent writer that never saw the overwrite) — the load-time
        index must cover dead rows, not just the visible winners."""
        from automerge_tpu.fleet.loader import load_docs
        fb, handles = self._setup_turbo()
        c2 = change_buf(ACTORS[0], 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 2,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=handles[0]['heads'])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c2]],
                                                      mirror=False)
        data = fleet_backend.save(handles[0])
        fresh = DocFleet(doc_capacity=2, key_capacity=8)
        loaded = load_docs([data], fresh)
        assert fresh.metrics.docs_bulk_loaded == 1
        # Concurrent actor B saw only 1@A (now overwritten by 2@A): its
        # pred must resolve against the loaded dead row, creating a
        # conflict rather than a false reject
        conc = change_buf(ACTORS[1], 1, 5, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 7,
             'datatype': 'int', 'pred': [f'1@{ACTORS[0]}']}],
            deps=loaded[0]['heads'])
        loaded, _ = fleet_backend.apply_changes_docs(loaded, [[conc]],
                                                     mirror=False)
        assert fleet_backend.materialize_docs(loaded) == [{'k': 7}]


class TestFleetRebuild:
    """The donation-failure contract (fleet/apply.py): after a device
    state loss, documents rebuild into a fresh fleet from their change
    logs — heads, reads, and further edits identical to never losing
    the device."""

    def test_rebuild_from_logs(self):
        from automerge_tpu.columnar import encode_change, decode_change_meta
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=8))
        handles = fleet_backend.init_docs(3, fb.fleet)
        actor = ACTORS[0]
        per_doc = []
        for d in range(3):
            c1 = change_buf(actor, 1, 1, [
                {'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': d, 'datatype': 'int', 'pred': []}])
            h1 = decode_change_meta(c1, True)['hash']
            c2 = change_buf(actor, 2, 2, [
                {'action': 'set', 'obj': '_root', 'key': 's',
                 'value': 'x' * (d + 1), 'pred': []}], deps=[h1])
            per_doc.append([c1, c2])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        want = fleet_backend.materialize_docs(handles)
        heads = [h['heads'] for h in handles]
        # simulate total device loss: rebuild into a FRESH fleet
        fresh = DocFleet(doc_capacity=4, key_capacity=8)
        rebuilt = fleet_backend.rebuild_docs(handles, fresh)
        assert [h['heads'] for h in rebuilt] == heads
        assert fleet_backend.materialize_docs(rebuilt) == want
        # further edits land on the new fleet
        c3 = change_buf(actor, 3, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 99,
             'datatype': 'int', 'pred': [f'1@{actor}']}], deps=heads[0])
        rebuilt, _ = fleet_backend.apply_changes_docs(
            rebuilt, [[c3], [], []], mirror=False)
        assert fleet_backend.materialize_docs(rebuilt)[0]['k'] == 99

    def test_rebuild_requeues_held_back_changes(self):
        """Causally-premature queue entries survive the rebuild and apply
        once their deps arrive."""
        from automerge_tpu.columnar import encode_change, decode_change_meta
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=8))
        handles = fleet_backend.init_docs(1, fb.fleet)
        actor = ACTORS[0]
        c1 = change_buf(actor, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change_meta(c1, True)['hash']
        c2 = change_buf(actor, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 2,
             'datatype': 'int', 'pred': []}], deps=[h1])
        h2 = decode_change_meta(c2, True)['hash']
        c3 = change_buf(actor, 3, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 3,
             'datatype': 'int', 'pred': []}], deps=[h2])
        # apply c1 and c3 (c3 queues: missing c2)
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c1, c3]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(handles) == [{'a': 1}]
        rebuilt = fleet_backend.rebuild_docs(
            handles, DocFleet(doc_capacity=2, key_capacity=8))
        assert fleet_backend.materialize_docs(rebuilt) == [{'a': 1}]
        # c2 arrives: the re-queued c3 must drain
        rebuilt, _ = fleet_backend.apply_changes_docs(rebuilt, [[c2]],
                                                      mirror=False)
        assert fleet_backend.materialize_docs(rebuilt) == \
            [{'a': 1, 'b': 2, 'c': 3}]


class TestMakeKindMemo:
    def test_same_opid_different_make_kinds_across_docs(self):
        """Round-5 review find: one turbo batch where the SAME packed
        opId is makeMap on doc A but makeText on doc B (independent docs
        share actor numbering). Each doc must get its own object type —
        the memo must not leak doc A's kind into doc B."""
        actor = ACTORS[0]
        cA = change_buf(actor, 1, 1, [
            {'action': 'makeMap', 'obj': '_root', 'key': 'obj', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'key': 'x', 'value': 1,
             'datatype': 'int', 'pred': []}])
        cB = change_buf(actor, 1, 1, [
            {'action': 'makeText', 'obj': '_root', 'key': 'obj',
             'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': 'h', 'pred': []}])
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=8))
        handles = fleet_backend.init_docs(2, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[cA], [cB]], mirror=False)
        got = fleet_backend.materialize_docs(handles)
        assert got[0] == {'obj': {'x': 1}}, got[0]
        assert got[1] == {'obj': 'h'}, got[1]
        # engine-side object registries agree with the types
        eA = handles[0]['state']._impl
        eB = handles[1]['state']._impl
        assert f'1@{actor}' in eA.map_objects
        assert f'1@{actor}' in eB.seq_objects


class TestSeqPoolReserve:
    def test_bulk_fresh_rows_grow_each_pool_once(self):
        """Round-5 on-chip find: placing N fresh sequence rows one alloc
        at a time grew the size-class pool ~log2(N) times, each growth an
        eager device re-pad of all 8 pool arrays — a dispatch storm on a
        tunneled TPU. The reserve() pre-pass must bound growth to O(1)
        device copies per size class per dispatch."""
        actor = ACTORS[0]
        n_docs = 64
        c1 = change_buf(actor, 1, 1, [
            {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': 7, 'datatype': 'int', 'pred': []}])
        fb = FleetBackend(DocFleet(doc_capacity=n_docs, key_capacity=8))
        handles = fleet_backend.init_docs(n_docs, fb.fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c1]] * n_docs, mirror=False)
        pools = fb.fleet.seq_pools
        # one class in play (all rows are 1-element lists): the initial
        # empty() plus at most one growth — NOT ~log2(64) regrowths
        assert pools.grow_events <= 2, pools.grow_events
        assert fleet_backend.materialize_docs(handles) == \
            [{'l': [7]}] * n_docs


class TestParkDocs:
    """park_docs demotes a live doc's host state to its canonical chunk
    (BASELINE.md's 100k-doc host-memory plan): reads, history, saves,
    sync, and further turbo applies must be observationally unchanged."""

    def _mk_handles(self, n=3):
        actor = ACTORS[0]
        fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=16))
        handles = fleet_backend.init_docs(n, fb.fleet)
        per_doc = []
        for d in range(n):
            c1 = change_buf(actor, 1, 1, [
                {'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': d, 'datatype': 'int', 'pred': []},
                {'action': 'makeText', 'obj': '_root', 'key': 't',
                 'pred': []}])
            from automerge_tpu.columnar import decode_change_meta
            h1 = decode_change_meta(c1, True)['hash']
            c2 = change_buf(actor, 2, 3, [
                {'action': 'set', 'obj': f'2@{actor}', 'elemId': '_head',
                 'insert': True, 'value': 'x', 'pred': []}], deps=[h1])
            per_doc.append([c1, c2])
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
        return fb, handles

    def test_park_preserves_reads_history_saves_and_applies(self):
        fb, handles = self._mk_handles()
        want_reads = fleet_backend.materialize_docs(handles)
        want_saves = [bytes(fleet_backend.save(h)) for h in handles]
        want_changes = [[bytes(b) for b in
                         fleet_backend.get_changes(h, [])] for h in handles]
        heads = [h['heads'] for h in handles]
        before = fleet_backend.host_memory_stats(handles)
        assert fleet_backend.park_docs(handles) == 3
        after = fleet_backend.host_memory_stats(handles)
        assert after['change_log_bytes'] == 0
        assert after['parked_doc_bytes'] > 0
        assert before['change_log_bytes'] > 0
        # device reads, saves, heads: unchanged
        assert fleet_backend.materialize_docs(handles) == want_reads
        assert [h['heads'] for h in handles] == heads
        assert [bytes(fleet_backend.save(h)) for h in handles] == want_saves
        # history rematerializes from the chunk, hash-identical
        got = [[bytes(b) for b in fleet_backend.get_changes(h, [])]
               for h in handles]
        assert got == want_changes
        # further changes land through the turbo gate on parked docs
        actor = ACTORS[0]
        c3 = change_buf(actor, 3, 4, [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'value': 99,
             'datatype': 'int', 'pred': [f'1@{actor}']}],
            deps=handles[0]['heads'])
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[c3], [], []], mirror=False)
        reads = fleet_backend.materialize_docs(handles)
        assert reads[0]['k'] == 99
        assert reads[1:] == want_reads[1:]

    def test_repark_drops_rematerialized_history(self):
        """Review find (round 6): a history read between parks revives
        the change log; re-parking must drop it (and the accounting must
        surface it while it lingers). The NATIVE extractor never pins
        decoded change dicts at all — docs_with_decoded_history counts
        only the Python-fallback path's decoded dicts."""
        from automerge_tpu import native
        fb, handles = self._mk_handles(1)
        assert fleet_backend.park_docs(handles) == 1
        fleet_backend.get_changes(handles[0], [])   # rematerializes
        stats = fleet_backend.host_memory_stats(handles)
        expect_decoded = 0 if native.available() else 1
        assert stats['docs_with_decoded_history'] == expect_decoded
        assert stats['change_log_bytes'] > 0
        assert fleet_backend.park_docs(handles) == 1
        stats = fleet_backend.host_memory_stats(handles)
        assert stats['docs_with_decoded_history'] == 0
        assert stats['change_log_bytes'] == 0
        assert handles[0]['state']._impl._doc_decoded is None

    def test_park_then_sync_converges(self):
        fb, handles = self._mk_handles(1)
        assert fleet_backend.park_docs(handles) == 1
        handle = handles[0]
        peer = host_backend.init()
        s1, s2 = am.init_sync_state(), am.init_sync_state()
        for _ in range(12):
            s1, msg = fleet_backend.generate_sync_message(handle, s1)
            if msg is not None:
                peer, s2, _ = host_backend.receive_sync_message(peer, s2,
                                                                msg)
            s2, msg2 = host_backend.generate_sync_message(peer, s2)
            if msg2 is not None:
                handle, s1, _ = fleet_backend.receive_sync_message(
                    handle, s1, msg2)
            if msg is None and msg2 is None:
                break
        assert host_backend.get_heads(peer) == \
            fleet_backend.get_heads(handle)

    def test_park_skips_queued_docs(self):
        actor = ACTORS[0]
        fb = FleetBackend(DocFleet(doc_capacity=2, key_capacity=8))
        handles = fleet_backend.init_docs(1, fb.fleet)
        from automerge_tpu.columnar import decode_change_meta
        c1 = change_buf(actor, 1, 1, [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'value': 1,
             'datatype': 'int', 'pred': []}])
        h1 = decode_change_meta(c1, True)['hash']
        c2 = change_buf(actor, 2, 2, [
            {'action': 'set', 'obj': '_root', 'key': 'b', 'value': 2,
             'datatype': 'int', 'pred': []}], deps=[h1])
        h2 = decode_change_meta(c2, True)['hash']
        c3 = change_buf(actor, 3, 3, [
            {'action': 'set', 'obj': '_root', 'key': 'c', 'value': 3,
             'datatype': 'int', 'pred': []}], deps=[h2])
        handles, _ = fleet_backend.apply_changes_docs(handles, [[c1, c3]],
                                                      mirror=False)
        assert fleet_backend.park_docs(handles) == 0   # c3 queued
