"""Cross-backend chaos differential: one randomized multi-actor workload
driven through the host backend and BOTH fleet device modes at once, with
save/load round-trips, bulk loads, clones, sync convergence, and history
queries interleaved — every read compared across implementations.

This is the wasm.js differential harness (ref test/wasm.js:27-36) scaled to
the whole surface: the host OpSet is the executable spec; the fleet paths
must be observationally identical through the public Backend contract."""

import os
import random

import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend
from automerge_tpu import native
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend
from automerge_tpu.fleet.faults import LossyLink, sync_until_quiet
from automerge_tpu.fleet.loader import load_docs

# Three founding actors plus two that join mid-history. The joiners' hex
# sorts BEFORE every founder, so a join forces the fleet's sorted actor
# renumbering (tensor remap) in the middle of live device state.
FOUNDERS = ['89' * 8, 'ab' * 8, 'fe' * 8]
JOINERS = ['01' * 8, '34' * 8]
ALPHA = 'abcdefghijklmnop'

# Dose knobs: the in-tree default is ~10x the round-3 dose (5 seeds x 80
# steps x up to 5 actors + mid-run joins + rows-in-lists edits, vs
# 2 x 30 x 3) while staying inside the CI budget on this image's single
# core (~4 min); CHAOS_SEEDS / CHAOS_STEPS scale it 50x+ for deeper
# offline fuzzing (e.g. CHAOS_SEEDS=20 CHAOS_STEPS=250).
N_SEEDS = int(os.environ.get('CHAOS_SEEDS', '5'))
N_STEPS = int(os.environ.get('CHAOS_STEPS', '80'))
# Offset for chunked offline doses: tools/chaos_dose.py runs the deep dose
# as several fresh pytest processes (the accumulated XLA CPU compile cache
# can segfault the compiler inside one long-lived process), each covering
# seeds [BASE, BASE + N_SEEDS).
SEED_BASE = int(os.environ.get('CHAOS_SEED_BASE', '0'))


def _random_edit(edit_seed):
    """One random mutation closure over the public proxy API. All draws
    come from a per-edit PRNG seeded up front, so applying the closure to
    identical documents in different universes performs identical edits."""

    def edit(r):
        rng = random.Random(edit_seed)
        roll = rng.random()
        t = r['text']
        lst = r['list']
        if roll < 0.14:
            t.insert_at(rng.randrange(len(t) + 1), rng.choice(ALPHA))
        elif roll < 0.22 and len(t):
            t.delete_at(rng.randrange(len(t)))
        elif roll < 0.30 and len(t):
            t.set(rng.randrange(len(t)), rng.choice(ALPHA).upper())
        elif roll < 0.40:
            key = rng.choice(ALPHA)
            choice = rng.random()
            if choice < 0.5:
                r[key] = rng.randrange(1000)
            elif choice < 0.7:
                r[key] = rng.choice(['str', 2.5, True, None])
            else:
                r[key] = A.Int(1589032171000) if choice < 0.8 else \
                    A.Uint(rng.randrange(99))
        elif roll < 0.48:
            k = rng.choice('xyz')
            m = r['counts']
            if k in m and hasattr(m[k], 'increment'):
                m[k].increment(1)    # Counters cannot be overwritten
            else:
                m[k] = A.Counter(rng.randrange(10))
        elif roll < 0.56:
            m = r['counts']
            k = rng.choice('xyz')
            if k in m and hasattr(m[k], 'increment'):
                m[k].increment(rng.randrange(-3, 9))
            else:
                m[k] = A.Counter(0)
        elif roll < 0.62:
            lst.insert(rng.randrange(len(lst) + 1), rng.randrange(100))
        elif roll < 0.67 and len(lst):
            lst[rng.randrange(len(lst))] = rng.randrange(100, 200)
        elif roll < 0.72 and len(lst):
            lst.delete_at(rng.randrange(len(lst)))
        elif roll < 0.78:
            # Objects nested inside sequences (fleet-resident since round
            # 4): insert a row map into the rows list
            rows = r['rows']
            rows.insert(rng.randrange(len(rows) + 1),
                        {'v': rng.randrange(50)})
        elif roll < 0.83 and len(r['rows']):
            # ... or edit a key inside an existing row
            rows = r['rows']
            row = rows[rng.randrange(len(rows))]
            if hasattr(row, 'keys'):
                row[rng.choice('vw')] = rng.randrange(500)
        elif roll < 0.86 and len(r['rows']):
            r['rows'].delete_at(rng.randrange(len(r['rows'])))
        elif roll < 0.90:
            r['nested'][rng.choice('pq')] = {'v': rng.randrange(50)}
        elif roll < 0.96:
            key = rng.choice(ALPHA)
            if key in r:
                del r[key]
        else:
            pass    # empty change
    return edit


class _Universe:
    """One backend implementation's replica set for the shared trace."""

    def __init__(self, name, backend):
        self.name = name
        self.backend = backend
        self.docs = []

    def with_backend(self, fn):
        prev = A.Backend()
        A.set_default_backend(self.backend)
        try:
            return fn()
        finally:
            A.set_default_backend(prev)


def fleet_handles(u):
    """Backend handles for every replica in a fleet universe."""
    return u.with_backend(
        lambda: [A.frontend.get_backend_state(d, 'chaos') for d in u.docs])


_seeds_run = [0]


@pytest.fixture(autouse=True)
def _bounded_jit_cache():
    """Each seed spawns fresh fleets whose pool shapes compile anew; at
    high offline doses (~20+ seeds in one process) the accumulated XLA
    CPU compile cache has crashed the compiler (segfault inside
    backend_compile_and_load). Clearing every few seeds bounds it
    without paying full recompiles per seed in the default CI dose."""
    yield
    _seeds_run[0] += 1
    if _seeds_run[0] % 8 == 0:
        import jax
        jax.clear_caches()


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
@pytest.mark.parametrize('seed', list(range(SEED_BASE, SEED_BASE + N_SEEDS)))
def test_chaos_differential(seed):
    rng = random.Random(seed)
    fleet_lww = DocFleet(doc_capacity=8, key_capacity=64)
    fleet_exact = DocFleet(doc_capacity=8, key_capacity=64,
                           exact_device=True)
    universes = [
        _Universe('host', host_backend),
        _Universe('fleet-lww', FleetBackend(fleet_lww)),
        _Universe('fleet-exact', FleetBackend(fleet_exact)),
    ]
    actors = list(FOUNDERS)
    # Actors joining mid-history (exercises the fleet's sorted-actor
    # renumbering: both joiners sort before every founder)
    joins = {N_STEPS * 2 // 5: JOINERS[0], N_STEPS * 3 // 5: JOINERS[1]}
    compare_every = max(10, N_STEPS // 4)
    # Mid-run total device loss: at this step every fleet replica is
    # rebuilt from its change logs into a FRESH DocFleet (the donation-
    # failure contract, fleet/apply.py) and the run continues on the
    # rebuilt state — all later compares prove the loss was invisible.
    rebuild_at = N_STEPS // 2

    def conflict_views(doc):
        """Conflict sets for every root key (winners can agree while the
        losing branches diverge — the round-4 counter-attribution bug hid
        exactly there)."""
        return {k: A.get_conflicts(doc, k) for k in doc.keys()}

    def compare(tag):
        base = None
        for u in universes:
            views = [dict(d) for d in u.docs]
            conflicts = [u.with_backend(lambda d=d: conflict_views(d))
                         for d in u.docs]
            saves = [bytes(u.with_backend(lambda d=d: A.save(d)))
                     for d in u.docs]
            if base is None:
                base = (u.name, views, saves, conflicts)
            else:
                assert views == base[1], \
                    f'{tag}: {u.name} reads diverge from {base[0]}'
                assert saves == base[2], \
                    f'{tag}: {u.name} save bytes diverge from {base[0]}'
                assert conflicts == base[3], \
                    f'{tag}: {u.name} conflicts diverge from {base[0]}'
        return base[2]

    # seed replicas: identical initial change everywhere — change times are
    # pinned to 0 throughout, or wall-clock seconds straddling a universe
    # boundary would legitimately fork the change hashes
    for u in universes:
        def build():
            base = A.change(
                A.init(actors[0]), {'message': 'Initialization', 'time': 0},
                lambda d: d.update({'text': A.Text('seed'), 'list': [1, 2],
                                    'rows': [], 'counts': {}, 'nested': {}}))
            return [base] + [A.merge(A.init(a), base) for a in actors[1:]]
        u.docs = u.with_backend(build)

    for step in range(N_STEPS):
        if step == rebuild_at:
            for u in universes[1:]:
                fresh = DocFleet(doc_capacity=8, key_capacity=64,
                                 exact_device=u.backend.fleet.exact_device)
                rebuilt = fleet_backend.rebuild_docs(fleet_handles(u), fresh)
                for d, h in zip(u.docs, rebuilt):
                    d._state['backendState'] = h
                u.backend.fleet = fresh
        if step in joins:
            actor = joins[step]
            actors.append(actor)
            for u in universes:
                u.docs.append(u.with_backend(
                    lambda u=u: A.merge(A.init(actor), u.docs[0])))
        i = rng.randrange(len(actors))
        action = rng.random()
        if action < 0.55:
            edit = _random_edit(rng.getrandbits(32))
            for u in universes:
                u.docs[i] = u.with_backend(
                    lambda u=u, i=i: A.change(u.docs[i], {'time': 0}, edit))
        elif action < 0.75:
            j = rng.randrange(len(actors))
            if j != i:
                for u in universes:
                    u.docs[i] = u.with_backend(
                        lambda u=u: A.merge(u.docs[i], u.docs[j]))
        elif action < 0.85:
            # save/load round-trip replaces the replica
            for u in universes:
                def reload(u=u, i=i):
                    buf = A.save(u.docs[i])
                    return A.load(buf, actors[i])
                u.docs[i] = u.with_backend(reload)
        elif action < 0.95:
            for u in universes:
                u.docs[i] = u.with_backend(
                    lambda u=u, i=i: A.clone(u.docs[i], actors[i]))
        else:
            for u in universes:
                u.docs[i] = u.with_backend(
                    lambda u=u, i=i: A.empty_change(u.docs[i], {'time': 0}))
        if step % compare_every == compare_every - 1:
            # full convergence point: merge everything into replica 0
            for u in universes:
                def converge(u=u):
                    out = A.clone(u.docs[0])
                    for d in u.docs[1:]:
                        out = A.merge(out, d)
                    return out
                merged = u.with_backend(converge)
                u.docs.append(merged)
            compare(f'step {step}')
            for u in universes:
                u.docs.pop()

    saves = compare('final')

    # LIVE-fleet bulk device reads (materialize_docs — the default-mode
    # grid / register readback, incl. the round-5 pred-scoped delete
    # semantics) must match the host frontend views in BOTH device modes
    host_views = [dict(d) for d in universes[0].docs]
    for u in universes[1:]:
        handles = fleet_handles(u)
        mats = u.with_backend(
            lambda h=handles: fleet_backend.materialize_docs(h))
        for k, (m, e) in enumerate(zip(mats, host_views)):
            assert m == e, f'live bulk read {u.name} doc {k}'

    # histories and heads agree everywhere
    for u in universes[1:]:
        for d0, d1 in zip(universes[0].docs, u.docs):
            h0 = universes[0].with_backend(lambda: A.get_history(d0))
            h1 = u.with_backend(lambda: A.get_history(d1))
            assert [e.change['hash'] for e in h0] == \
                [e.change['hash'] for e in h1]

    # bulk-load every final save into fresh fleets: reads must match
    for exact in (False, True):
        fresh = DocFleet(doc_capacity=8, key_capacity=64,
                         exact_device=exact)
        handles = load_docs(saves, fresh)
        mats = fleet_backend.materialize_docs(handles)
        expect = [dict(d) for d in universes[0].docs]
        for k, (m, e) in enumerate(zip(mats, expect)):
            assert m == e, f'bulk-load(exact={exact}) doc {k}'
        # and the loaded docs save back verbatim
        for h, buf in zip(handles, saves):
            assert bytes(fleet_backend.save(h)) == buf

    # sync convergence: bulk-loaded fleet replicas (BOTH device modes)
    # sync against fresh host peers until both sides go quiet, ending on
    # identical heads
    for exact in (False, True):
        sync_fleet = DocFleet(doc_capacity=4, key_capacity=64,
                              exact_device=exact)
        handle = load_docs([saves[0]], sync_fleet)[0]
        peer = host_backend.init()
        s1, s2 = A.init_sync_state(), A.init_sync_state()
        for _ in range(16):
            s1, msg = fleet_backend.generate_sync_message(handle, s1)
            if msg is not None:
                peer, s2, _ = host_backend.receive_sync_message(peer, s2,
                                                                msg)
            s2, msg2 = host_backend.generate_sync_message(peer, s2)
            if msg2 is not None:
                handle, s1, _ = fleet_backend.receive_sync_message(
                    handle, s1, msg2)
            if msg is None and msg2 is None:
                break
        assert host_backend.get_heads(peer) == \
            fleet_backend.get_heads(handle), f'sync exact={exact}'


# ---------------------------------------------------------------------------
# Wire-fault universe: the same divergent two-actor workload synced over a
# seeded LossyLink (drop/dup/reorder/truncate/bit-flip) in the host universe
# and BOTH fleet device modes. Sync messages are byte-identical across
# universes, so one wire seed produces the SAME fault trace everywhere —
# all universes must converge to identical heads and byte-identical saves,
# proving loss is survivable and corruption contained, never propagated.
# ---------------------------------------------------------------------------

N_WIRE_SEEDS = int(os.environ.get('CHAOS_WIRE_SEEDS', '3'))


def _divergent_pair(backend_impl, edits_a, edits_b):
    """Two replicas sharing a seeded base, then editing independently
    (no merges): maximal divergence for the sync wire to reconcile."""
    prev = A.Backend()
    A.set_default_backend(backend_impl)
    try:
        base = A.change(
            A.init(FOUNDERS[0]), {'message': 'Initialization', 'time': 0},
            lambda d: d.update({'text': A.Text('seed'), 'list': [1, 2],
                                'rows': [], 'counts': {}, 'nested': {}}))
        doc_b = A.merge(A.init(FOUNDERS[1]), base)
        doc_a = base
        for edit in edits_a:
            doc_a = A.change(doc_a, {'time': 0}, edit)
        for edit in edits_b:
            doc_b = A.change(doc_b, {'time': 0}, edit)
        return (A.frontend.get_backend_state(doc_a, 'wire'),
                A.frontend.get_backend_state(doc_b, 'wire'))
    finally:
        A.set_default_backend(prev)


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
@pytest.mark.parametrize('wire_seed', list(range(N_WIRE_SEEDS)))
def test_chaos_lossy_wire(wire_seed):
    rng = random.Random(1000 + wire_seed)
    edits_a = [_random_edit(rng.getrandbits(32)) for _ in range(12)]
    edits_b = [_random_edit(rng.getrandbits(32)) for _ in range(12)]
    fault_p = dict(p_drop=0.12, p_dup=0.08, p_reorder=0.08,
                   p_truncate=0.08, p_flip=0.08)

    results = []
    for name, impl in (
            ('host', host_backend),
            ('fleet-lww', FleetBackend(DocFleet(doc_capacity=4,
                                                key_capacity=64))),
            ('fleet-exact', FleetBackend(DocFleet(doc_capacity=4,
                                                  key_capacity=64,
                                                  exact_device=True)))):
        ha, hb = _divergent_pair(impl, edits_a, edits_b)
        link_ab = LossyLink(seed=wire_seed, budget=10, **fault_p)
        link_ba = LossyLink(seed=wire_seed + 500, budget=10, **fault_p)
        na, nb, rounds, stats = sync_until_quiet(
            ha, hb, impl, impl, link_ab, link_ba)
        heads_a = impl.get_heads(na)
        assert heads_a == impl.get_heads(nb), \
            f'{name} seed {wire_seed}: replicas diverged after quiet'
        views = None
        if name != 'host':
            # bulk device readback: the converged state must be served
            # from the device grids too, not just the host change log
            views = fleet_backend.materialize_docs([na, nb])
        results.append((name, heads_a,
                        bytes(impl.save(na)), bytes(impl.save(nb)),
                        link_ab.stats, link_ba.stats, views))

    base = results[0]
    host_views = [dict(A.load(base[2])), dict(A.load(base[3]))]
    for name, _h, _sa, _sb, _la, _lb, views in results[1:]:
        assert views == host_views, \
            f'{name}: device readback diverges from host universe'
    for other in results[1:]:
        assert other[1] == base[1], \
            f'{other[0]} heads diverge from {base[0]}'
        assert other[2] == base[2] and other[3] == base[3], \
            f'{other[0]} save bytes diverge from {base[0]}'
        # identical wire seeds + byte-identical messages => the fault
        # trace itself must align across universes
        assert other[4] == base[4] and other[5] == base[5], \
            f'{other[0]} fault trace diverged (messages not byte-identical?)'


# ---------------------------------------------------------------------------
# Durability universe: the divergent pair syncs over a LossyLink while peer
# A journals to disk (fleet universes through the backend seam hooks, the
# host universe through explicit journal records — same frames either way),
# checkpoints mid-run, then CRASHES: its in-memory state is dropped and
# rebuilt from the durability directory alone. The recovered peer resumes
# lossy sync to quiet. All three universes (host + both device modes) must
# converge to identical heads and byte-identical saves — a crash plus
# recovery is invisible at the wire level.
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_chaos_checkpoint_crash_recover(tmp_path):
    from automerge_tpu.errors import AutomergeError
    from automerge_tpu.fleet import durability as D
    from automerge_tpu.fleet.durability import DurableFleet, read_state

    rng = random.Random(4242)
    edits_a = [_random_edit(rng.getrandbits(32)) for _ in range(10)]
    edits_b = [_random_edit(rng.getrandbits(32)) for _ in range(10)]
    # canonical divergent saves (change bytes are backend-independent —
    # pinned by test_chaos_differential — so one build serves all
    # universes byte-identically)
    ha0, hb0 = _divergent_pair(host_backend, edits_a, edits_b)
    save_a = bytes(host_backend.save(ha0))
    save_b = bytes(host_backend.save(hb0))
    fault_p = dict(p_drop=0.15, p_dup=0.05, p_truncate=0.1, p_flip=0.1)
    n_pre_rounds = 6

    results = []
    for name, exact in (('host', None), ('fleet-lww', False),
                        ('fleet-exact', True)):
        ddir = str(tmp_path / name)
        if exact is None:
            impl = host_backend
            mgr = DurableFleet(ddir)
            ha = impl.load(save_a)
            hb = impl.load(save_b)
            # explicit baseline record (no seam hooks on the host path)
            did = mgr.journal.doc_id_for(ha['state'])
            mgr.journal.append(did, save_a)
            mgr.journal.commit()
            auto_journal = False
        else:
            impl = fleet_backend
            fleet_a = DocFleet(doc_capacity=4, key_capacity=64,
                               exact_device=exact)
            fleet_b = DocFleet(doc_capacity=4, key_capacity=64,
                               exact_device=exact)
            mgr = DurableFleet(ddir, fleet=fleet_a)
            # load goes through the apply seam, so the baseline chunk is
            # journaled by the hook — no explicit plumbing
            ha = fleet_backend.load(save_a, fleet_a)
            hb = fleet_backend.load(save_b, fleet_b)
            did = ha['state']._dur_id
            auto_journal = True

        # phase 1: lossy duplex rounds with a mid-run checkpoint
        link_ab = LossyLink(seed=9000, budget=8, **fault_p)
        link_ba = LossyLink(seed=9500, budget=8, **fault_p)
        sa, sb = impl.init_sync_state(), impl.init_sync_state()
        for r in range(n_pre_rounds):
            sa, msg_ab = impl.generate_sync_message(ha, sa)
            sb, msg_ba = impl.generate_sync_message(hb, sb)
            for payload in link_ab.transmit(msg_ab):
                try:
                    hb, sb, _ = impl.receive_sync_message(hb, sb, payload)
                except AutomergeError:
                    pass                       # corrupt == dropped
            for payload in link_ba.transmit(msg_ba):
                old_heads = impl.get_heads(ha)
                try:
                    ha, sa, _ = impl.receive_sync_message(ha, sa, payload)
                except AutomergeError:
                    continue
                if not auto_journal:
                    new = [bytes(c)
                           for c in impl.get_changes(ha, old_heads)]
                    if new:
                        mgr.journal.record_changes(ha['state'], new)
            if r == n_pre_rounds // 2:
                mgr.checkpoint()
        mgr.close()

        # CRASH: peer A's in-memory state is gone; rebuild from disk only
        pre_crash_save = bytes(impl.save(ha))
        del ha
        mgr2 = None
        if auto_journal:
            mgr2, rec, report = DurableFleet.recover(ddir,
                                                     exact_device=exact)
            ha2 = rec[did]
            assert report.ok, report
        else:
            st = read_state(ddir)
            ha2 = impl.load(st['docs'][did]) if did in st['docs'] \
                else impl.init()
            suffix = [bytes(p) for k, d2, p in st['journal_records']
                      if d2 == did and k == D.KIND_CHANGE]
            if suffix:
                ha2, _patch = impl.apply_changes(ha2, suffix)
        assert bytes(impl.save(ha2)) == pre_crash_save, \
            f'{name}: recovery lost acknowledged state'

        # phase 2: resume lossy sync (fresh links + sync states — a real
        # reconnect) until quiet
        link2_ab = LossyLink(seed=9100, budget=6, **fault_p)
        link2_ba = LossyLink(seed=9600, budget=6, **fault_p)
        na, nb, _rounds, _stats = sync_until_quiet(
            ha2, hb, impl, impl, link2_ab, link2_ba)
        heads = impl.get_heads(na)
        assert heads == impl.get_heads(nb), \
            f'{name}: replicas diverged after crash-recovery sync'
        if mgr2 is not None:
            # the recovered peer stayed durable through phase 2: one more
            # crash-recover round trip must reproduce the converged state
            mgr2.close()
            mgr3, rec3, _rep3 = DurableFleet.recover(ddir,
                                                     exact_device=exact)
            assert bytes(impl.save(rec3[did])) == bytes(impl.save(na)), \
                f'{name}: post-sync recovery diverges'
            mgr3.close()
        results.append((name, heads, bytes(impl.save(na)),
                        bytes(impl.save(nb)),
                        link_ab.stats, link_ba.stats))

    base = results[0]
    for other in results[1:]:
        assert other[1] == base[1], \
            f'{other[0]} heads diverge from {base[0]}'
        assert other[2] == base[2] and other[3] == base[3], \
            f'{other[0]} save bytes diverge from {base[0]}'
        # byte-identical messages => identical phase-1 fault traces
        assert other[4] == base[4] and other[5] == base[5], \
            f'{other[0]} fault trace diverged (messages not byte-identical?)'


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_chaos_lossy_wire_moves_health_counters():
    """The containment counters must actually move under wire faults —
    silent success would mean the faults were never injected."""
    from automerge_tpu.observability import health_counts
    rng = random.Random(77)
    edits_a = [_random_edit(rng.getrandbits(32)) for _ in range(6)]
    edits_b = [_random_edit(rng.getrandbits(32)) for _ in range(6)]
    before = health_counts()
    ha, hb = _divergent_pair(host_backend, edits_a, edits_b)
    link_ab = LossyLink(seed=3, budget=16, p_drop=0.2, p_flip=0.25,
                        p_truncate=0.25)
    link_ba = LossyLink(seed=4, budget=16, p_drop=0.2, p_flip=0.25,
                        p_truncate=0.25)
    na, nb, _rounds, _stats = sync_until_quiet(ha, hb, host_backend,
                                               host_backend, link_ab,
                                               link_ba)
    assert host_backend.get_heads(na) == host_backend.get_heads(nb)
    after = health_counts()
    assert after['wire_faults'] > before['wire_faults']
