"""Shard scale-out unit + chaos coverage (ISSUE-11).

The tentpole properties, each pinned small enough for tier-1 (the full
kill matrices live in tests/test_service_chaos.py and under ``-m
slow``):

- RING: deterministic placement, preference lists of distinct shards,
  liveness filtering that skips dead shards without mutating the ring,
  and kill -> revive round-tripping to the original placement.
- REPLICATION ACK CONTRACT: an 'apply' acks only once its changes are
  on BOTH the home and replica docs; post-quiet the pair is
  byte-identical.
- FAILOVER: a killed shard's tenants re-home onto their replicas
  within the lease window; acked writes survive; the re-homed session
  gets the ``reset=True`` reconnect and its standing subscription
  cursor back — a cursor naming heads the replica never received
  resolves as a TYPED resync event, never a silently stale patch.
- MIGRATION: planned rebalance moves a tenant through park ->
  ingest_chunks -> revive with a real reads-only window (writes typed
  /retried, reads served) and byte-identical content.
- LINK FAULTS: LossyLink's stateful partition/crash classes go dark
  for K ticks and heal, counted in wire_faults, and sync_until_quiet
  converges across them.
- OBSERVABILITY: the Prometheus page stamps shard="..." on every
  sample; --stitch labels shard inputs and DISCLOSES a restarted
  shard's span-ring truncation while trace ids stitch across it.
"""

import io
import json
import os
import random
import sys

import pytest

from automerge_tpu import backend as host_backend
from automerge_tpu import native
from automerge_tpu.backend import get_change_by_hash, get_heads
from automerge_tpu.columnar import decode_change_meta, encode_change
from automerge_tpu.errors import (AutomergeError, Overloaded,
                                  ShardUnavailable)
from automerge_tpu.fleet.faults import LossyLink, sync_until_quiet
from automerge_tpu.observability import (clear_spans, disable as obs_off,
                                         enable as obs_on,
                                         export_chrome_trace, span)
from automerge_tpu.observability.export import render_prometheus
from automerge_tpu.service.backoff import Backoff
from automerge_tpu.shard import HashRing, ShardRouter, shard_stats

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


def _change(actor, seq, value=1):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': [],
        'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                 'value': value, 'datatype': 'int', 'pred': []}]})


def _router(n, clk, **kwargs):
    kwargs.setdefault('backoff', Backoff(base=0.02, factor=1.5,
                                         cap=0.32, retries=14, seed=1))
    kwargs.setdefault('lease_ticks', 3)
    return ShardRouter(n_shards=n, clock=lambda: clk[0], **kwargs)


def _pump(router, clk, n=1, dt=0.02):
    for _ in range(n):
        router.pump(now=clk[0])
        clk[0] += dt


def _settle(router, clk, ticket, limit=200):
    for _ in range(limit):
        if ticket.done:
            return ticket
        _pump(router, clk)
    return ticket


class TestHashRing:
    def test_deterministic_and_distinct(self):
        a = HashRing(['s0', 's1', 's2', 's3'])
        b = HashRing(['s0', 's1', 's2', 's3'])
        for key in ('tenant0', 'tenant1', 'zebra'):
            pref = a.preference(key, 3)
            assert pref == b.preference(key, 3)
            assert len(pref) == len(set(pref)) == 3
            assert a.primary(key) == pref[0]
            assert a.replica(key) == pref[1]

    def test_alive_filter_skips_dead_without_mutating(self):
        ring = HashRing(['s0', 's1', 's2', 's3'])
        keys = [f'tenant{i}' for i in range(64)]
        before = {k: ring.primary(k) for k in keys}
        dead = before[keys[0]]
        alive = {s for s in ring.shard_ids() if s != dead}
        for k in keys:
            p = ring.primary(k, alive=alive)
            assert p in alive
            if before[k] != dead:
                # only the dead shard's tenants move
                assert p == before[k]
        # revival restores the original placement exactly
        assert {k: ring.primary(k) for k in keys} == before

    def test_balance_rough(self):
        ring = HashRing(['s0', 's1', 's2', 's3'])
        homes = [ring.primary(f't{i}') for i in range(400)]
        for sid in ring.shard_ids():
            share = homes.count(sid) / len(homes)
            assert 0.05 < share < 0.60, (sid, share)


class TestReplicationAck:
    def test_apply_acks_on_both_copies_and_converges(self):
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        payload = [_change('aa' * 16, 1)]
        ticket = _settle(router, clk, router.submit('t0', 'apply',
                                                    payload))
        assert ticket.status == 'ok', ticket.error
        h = decode_change_meta(payload[0], True)['hash']
        # the ack CONTRACT: resolved ok means both copies hold it NOW
        assert get_change_by_hash(rec.session.handle, h) is not None
        assert get_change_by_hash(rec.replica_handle, h) is not None
        assert router.run_until_quiet(200, advance=0.02)
        assert bytes(host_backend.save(rec.session.handle)) == \
            bytes(host_backend.save(rec.replica_handle))

    def test_replication_rides_lossy_links(self):
        links = {}

        def factory(src, dst):
            links[(src, dst)] = LossyLink(
                seed=len(links) + 7, p_drop=0.15, p_flip=0.1,
                p_dup=0.05, budget=24)
            return links[(src, dst)]

        clk = [0.0]
        router = _router(2, clk, link_factory=factory)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        tickets = []
        for seq in range(1, 6):
            tickets.append(_settle(router, clk, router.submit(
                't0', 'apply', [_change('bb' * 16, seq, seq)]),
                limit=400))
        assert all(t.status == 'ok' for t in tickets), \
            [(t.status, t.error) for t in tickets]
        assert router.run_until_quiet(600, advance=0.02)
        assert bytes(host_backend.save(rec.session.handle)) == \
            bytes(host_backend.save(rec.replica_handle))
        assert any(link.stats['sent'] > link.stats['delivered']
                   or link.stats['flipped'] for link in links.values())

    def test_quiet_pairs_skip_replication_rounds(self):
        """A converged-quiet pair with unmoved heads costs no
        replication round (steady state is O(dirty pairs)); the next
        committed apply wakes it."""
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('aa' * 16, 1)]))
        assert ticket.status == 'ok', ticket.error
        assert router.run_until_quiet(200, advance=0.02)
        idle_base = shard_stats()['shard_repl_rounds']
        _pump(router, clk, 20)
        assert shard_stats()['shard_repl_rounds'] == idle_base
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('aa' * 16, 2)]))
        assert ticket.status == 'ok', ticket.error
        assert shard_stats()['shard_repl_rounds'] > idle_base

    def test_repl_every_group_commit_keeps_ack_contract(self):
        """repl_every > 1 batches replication rounds; the ack still
        waits for both copies, and the pair still converges."""
        clk = [0.0]
        router = _router(2, clk, repl_every=3)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        tickets = [router.submit('t0', 'apply', [_change('cc' * 16, s)])
                   for s in (1,)]
        for s in (2, 3):
            _pump(router, clk)
            tickets.append(router.submit(
                't0', 'apply', [_change('dd' * 16, s - 1, s)]))
        for t in tickets:
            _settle(router, clk, t, limit=400)
        assert all(t.status == 'ok' for t in tickets), \
            [(t.status, t.error) for t in tickets]
        for t, payload_seq in ((tickets[0], 1),):
            h = decode_change_meta(_change('cc' * 16, payload_seq),
                                   True)['hash']
            assert get_change_by_hash(rec.replica_handle, h) is not None
        assert router.run_until_quiet(400, advance=0.02)
        assert bytes(host_backend.save(rec.session.handle)) == \
            bytes(host_backend.save(rec.replica_handle))

    def test_corrupt_apply_bytes_resolve_typed_not_raised(self):
        """Bytes that don't even decode can never meet the ack
        contract: a fixed corrupt payload resolves typed immediately
        (no exception out of submit/pump), and a payload_fn transport
        retries with a fresh draw until clean bytes land."""
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        bad = _settle(router, clk, router.submit(
            't0', 'apply', [b'\x00garbage not a change']))
        assert bad.status == 'error'
        assert isinstance(bad.error, AutomergeError)
        draws = [b'\xffflip', bytes(_change('ee' * 16, 1))]
        healed = _settle(router, clk, router.submit(
            't0', 'apply', payload_fn=lambda: [draws.pop(0)]
            if draws else [bytes(_change('ee' * 16, 1))]), limit=400)
        assert healed.status == 'ok', healed.error

    def test_dead_replica_window_defers_ack_until_failover(self):
        """A killed replica shard's memory can't accept bytes even
        before the lease notices: an apply submitted in that window
        stays pending and acks only through the re-placed replica."""
        clk = [0.0]
        router = _router(3, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        dead = rec.replica_on
        router.kill_shard(dead)
        ticket = router.submit('t0', 'apply', [_change('ab' * 16, 1)])
        # within the lease window: committed on home, NOT acked (the
        # only other copy would be a zombie)
        _pump(router, clk, router.lease_ticks)
        assert not ticket.done
        _settle(router, clk, ticket)
        assert ticket.status == 'ok', ticket.error
        assert rec.replica_on != dead and rec.replica_on is not None
        h = decode_change_meta(_change('ab' * 16, 1), True)['hash']
        assert get_change_by_hash(rec.replica_handle, h) is not None

    def test_revive_before_lease_expiry_still_fails_over(self):
        """kill -> revive inside the lease window: the crash destroyed
        the shard's memory regardless of detection timing, so revive
        forces the failover — no tenant may keep a session into the
        dead incarnation."""
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        first = _settle(router, clk, router.submit(
            't0', 'apply', [_change('cd' * 16, 1)]))
        assert first.status == 'ok', first.error
        home = rec.home
        base = shard_stats()['shard_failovers']
        router.kill_shard(home)
        _pump(router, clk)                     # < lease_ticks
        router.revive_shard(home)
        assert shard_stats()['shard_failovers'] == base + 1
        assert rec.home != home                # re-homed on the replica
        after = _settle(router, clk, router.submit(
            't0', 'apply', [_change('cd' * 16, 2)]), limit=400)
        assert after.status == 'ok', after.error
        h = decode_change_meta(_change('cd' * 16, 1), True)['hash']
        assert get_change_by_hash(rec.session.handle, h) is not None

    def test_threaded_pump_matches_serial(self):
        """Thread-per-shard pumping changes wall time, never state:
        the same workload acks the same tickets and converges to the
        same bytes as the serial pump."""
        saves = []
        for threads in (None, 4):
            clk = [0.0]
            router = _router(4, clk, pump_threads=threads)
            for i in range(6):
                router.open_tenant(f't{i}')
            tickets = [router.submit(f't{i}', 'apply',
                                     [_change(f'{i:02x}' * 16, 1, i)])
                       for i in range(6)]
            for t in tickets:
                _settle(router, clk, t)
            assert all(t.status == 'ok' for t in tickets), \
                [(t.status, t.error) for t in tickets]
            assert router.run_until_quiet(300, advance=0.02)
            saves.append(tuple(
                bytes(host_backend.save(
                    router.tenant_record(f't{i}').session.handle))
                for i in range(6)))
            router.close()
        assert saves[0] == saves[1]


class TestFailover:
    def test_kill_one_of_four_rehomes_within_lease(self):
        clk = [0.0]
        router = _router(4, clk)
        tenants = [f'tenant{i}' for i in range(8)]
        acked = {t: [] for t in tenants}
        for t in tenants:
            router.open_tenant(t)
        for i, t in enumerate(tenants):
            p = [_change(f'{i:08x}' + 'ab' * 12, 1)]
            tk = _settle(router, clk, router.submit(t, 'apply', p))
            assert tk.status == 'ok'
            acked[t].append(p)
        victim = router.tenant_record(tenants[0]).home
        doomed = router.tenants_on(victim)
        assert doomed
        router.kill_shard(victim)
        kill_tick = router.ticks
        inflight = []
        for t in doomed:
            i = tenants.index(t)
            p = [_change(f'{i:08x}' + 'ab' * 12, 2)]
            inflight.append((router.submit(t, 'apply', p), t, p))
        mttr = None
        for _ in range(200):
            _pump(router, clk)
            if mttr is None:
                for tk, t, _p in inflight:
                    if tk.done and tk.status == 'ok' and \
                            router.tenant_record(t).home != victim:
                        mttr = router.ticks - kill_tick
            if all(tk.done for tk, _t, _p in inflight):
                break
        for tk, t, p in inflight:
            assert tk.status == 'ok', (t, tk.error)
            acked[t].append(p)
            assert router.tenant_record(t).home != victim
        # served by the replica within the lease window (+ detection
        # tick + one retry hop)
        assert mttr is not None and mttr <= router.lease_ticks + 6, mttr
        assert router.run_until_quiet(400, advance=0.02)
        for t in tenants:
            rec = router.tenant_record(t)
            for p in acked[t]:
                for b in p:
                    h = decode_change_meta(bytes(b), True)['hash']
                    assert get_change_by_hash(rec.session.handle, h) \
                        is not None, (t, 'acked write lost')
            assert bytes(host_backend.save(rec.session.handle)) == \
                bytes(host_backend.save(rec.replica_handle))

    def test_reset_rule_and_subscription_resync_after_failover(self):
        """The satellite: a re-homed session handshakes fresh
        (reset=True) and its standing subscription cursor re-registers
        — heads the replica never received resolve as a TYPED resync
        event, never a silently stale patch."""
        links = {}

        def factory(src, dst):
            links[(src, dst)] = LossyLink(seed=3)   # clean until darkened
            return links[(src, dst)]

        clk = [0.0]
        # retries=0: the in-flight change-2 apply resolves TYPED at
        # failover instead of racing its retransmit ahead of the
        # subscribe (the retransmit path is pinned at the end)
        router = _router(2, clk, link_factory=factory,
                         backoff=Backoff(base=0.02, retries=0, seed=1))
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        victim, backup = rec.home, rec.replica_on
        # change 1 fully acked (on both copies), cursor caught up
        tk = _settle(router, clk, router.submit(
            't0', 'apply', [_change('cc' * 16, 1)]))
        assert tk.status == 'ok'
        sub = _settle(router, clk, router.submit('t0', 'subscribe'))
        assert sub.status == 'ok' and sub.result['kind'] == 'patch'
        # darken replication, then land change 2 on the HOME only: the
        # subscription serves it (cursor advances past what the replica
        # will ever see), the ack stays pending
        for link in links.values():
            link.crash(10_000)
        pend = router.submit('t0', 'apply', [_change('cc' * 16, 2)])
        for _ in range(20):
            _pump(router, clk)
        assert not pend.done          # await_replica: links are dark
        sub2 = _settle(router, clk, router.submit('t0', 'subscribe'))
        assert sub2.status == 'ok' and sub2.result['kind'] == 'patch'
        assert sub2.result['changes']
        stale_cursor = list(rec.cursor)
        # crash the home: failover promotes the replica
        router.kill_shard(victim)
        for _ in range(router.lease_ticks + 3):
            _pump(router, clk)
        assert rec.home == backup
        assert rec.needs_reset
        assert rec.session.sub_cursor == stale_cursor
        # the never-replicated change was NOT acked: typed, never lost
        # silently (its copy died with the primary)
        assert pend.done and pend.status == 'error'
        assert isinstance(pend.error, ShardUnavailable)
        # the standing subscription resolves TYPED resync (the cursor
        # names change 2, which the replica never received)
        sub3 = _settle(router, clk, router.submit('t0', 'subscribe'))
        assert sub3.status == 'ok', sub3.error
        assert sub3.result['kind'] == 'resync'
        # the first sync request after re-home runs the reset=True rule
        sync = _settle(router, clk, router.submit('t0', 'sync', None))
        assert sync.status == 'ok', sync.error
        assert not rec.needs_reset
        # the client retransmits the un-acked payload byte-identically
        # and it lands on the promoted home (degraded single-copy ack:
        # no second live shard)
        done = _settle(router, clk, router.submit(
            't0', 'apply', [_change('cc' * 16, 2)]), limit=400)
        assert done.status == 'ok', done.error
        h = decode_change_meta(_change('cc' * 16, 2), True)['hash']
        assert get_change_by_hash(rec.session.handle, h) is not None

    def test_unavailable_is_typed_after_budget(self):
        clk = [0.0]
        router = _router(1, clk,
                         backoff=Backoff(base=0.02, cap=0.08,
                                         retries=3, seed=2))
        router.open_tenant('t0')
        router.kill_shard(router.tenant_record('t0').home)
        for _ in range(router.lease_ticks + 2):
            _pump(router, clk)
        before = shard_stats()['shard_unavailable']
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('dd' * 16, 1)]), limit=100)
        assert ticket.status == 'error'
        assert isinstance(ticket.error, ShardUnavailable)
        assert isinstance(ticket.error, AutomergeError)
        assert shard_stats()['shard_unavailable'] > before

    def test_replica_less_tenant_heals_on_revive(self):
        """A failover that found no spare shard leaves the tenant on
        degraded single-copy acks; the next revive must re-place its
        replica — not leave it single-copy forever."""
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        dead = rec.replica_on
        router.kill_shard(dead)
        for _ in range(router.lease_ticks + 2):
            _pump(router, clk)
        assert rec.replica_on is None       # no spare: replica-less
        degraded = _settle(router, clk, router.submit(
            't0', 'apply', [_change('ba' * 16, 1)]))
        assert degraded.status == 'ok', degraded.error
        router.revive_shard(dead)
        assert rec.replica_on == dead       # healed immediately
        full = _settle(router, clk, router.submit(
            't0', 'apply', [_change('ba' * 16, 2)]), limit=400)
        assert full.status == 'ok', full.error
        h = decode_change_meta(_change('ba' * 16, 2), True)['hash']
        assert get_change_by_hash(rec.replica_handle, h) is not None

    def test_full_outage_open_and_submit_stay_typed(self):
        """submit() for a FIRST-SEEN tenant during a full outage must
        not raise: the tenant records unplaced, its ticket resolves
        typed, and the next revive places it fresh."""
        clk = [0.0]
        router = _router(1, clk,
                         backoff=Backoff(base=0.02, cap=0.08,
                                         retries=2, seed=3))
        only = router.ring.shard_ids()[0]
        router.kill_shard(only)
        for _ in range(router.lease_ticks + 2):
            _pump(router, clk)
        ticket = _settle(router, clk, router.submit(
            'newcomer', 'apply', [_change('ad' * 16, 1)]), limit=100)
        assert ticket.status == 'error'
        assert isinstance(ticket.error, ShardUnavailable)
        router.revive_shard(only)
        rec = router.tenant_record('newcomer')
        assert rec.home == only and rec.session is not None
        ok = _settle(router, clk, router.submit(
            'newcomer', 'apply', [_change('ad' * 16, 1)]), limit=200)
        assert ok.status == 'ok', ok.error


class TestMigration:
    def test_rebalance_readonly_window_and_byte_identity(self):
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        origin = rec.home
        tk = _settle(router, clk, router.submit(
            't0', 'apply', [_change('ee' * 16, 1)]))
        assert tk.status == 'ok'
        assert router.run_until_quiet(200, advance=0.02)
        before_bytes = bytes(host_backend.save(rec.session.handle))
        # crash+revive the home: the tenant fails over, then rebalance
        # migrates it back through park -> ingest -> revive
        router.kill_shard(origin)
        for _ in range(router.lease_ticks + 3):
            _pump(router, clk)
        assert rec.home != origin
        router.revive_shard(origin)
        started = router.rebalance()
        assert started == 1
        saw_readonly = False
        migrations_before = shard_stats()['shard_migrations']
        for _ in range(60):
            _pump(router, clk)
            saw_readonly = saw_readonly or rec.read_only
            if rec.migrating is None and rec.home == origin:
                break
        assert rec.home == origin
        assert saw_readonly            # the reads-only window was real
        assert not rec.read_only
        assert shard_stats()['shard_migrations'] == migrations_before + 1
        assert bytes(host_backend.save(rec.session.handle)) == \
            before_bytes
        assert router.run_until_quiet(300, advance=0.02)
        assert bytes(host_backend.save(rec.replica_handle)) == \
            before_bytes

    def test_write_during_migration_gets_pushback_then_lands(self):
        clk = [0.0]
        router = _router(2, clk)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        origin = rec.home
        router.kill_shard(origin)
        for _ in range(router.lease_ticks + 3):
            _pump(router, clk)
        router.revive_shard(origin)
        router.rebalance()
        _pump(router, clk)             # enter the readonly window
        assert rec.read_only
        ticket = router.submit('t0', 'apply', [_change('ff' * 16, 1)])
        done = _settle(router, clk, ticket, limit=300)
        # the write rode the router's backoff across the window and
        # landed on the migrated-home doc (never silently dropped)
        assert done.status == 'ok', done.error
        h = decode_change_meta(_change('ff' * 16, 1), True)['hash']
        assert rec.home == origin
        assert get_change_by_hash(rec.session.handle, h) is not None


class TestTickTelemetry:
    def test_tick_budget_counts_slips_per_shard(self):
        """A router given a serving cadence attributes overrunning
        pumps PER SHARD (ISSUE-12 satellite) and the Prometheus page
        renders the labeled counters; a free-running router (no
        budget) never counts."""
        clk = [0.0]
        router = _router(2, clk, tick_budget_s=0.0)   # everything slips
        router.open_tenant('t0')
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('aa' * 16, 1)]))
        assert ticket.status == 'ok', ticket.error
        slips = {sid: s.ticks_slipped for sid, s in router.shards.items()}
        assert all(n > 0 for n in slips.values())
        assert sum(slips.values()) <= shard_stats()['shard_ticks_slipped']
        page = render_prometheus(router=router)
        for sid in router.shards:
            assert (f'automerge_tpu_shard_ticks_slipped_total'
                    f'{{shard="{sid}"}}') in page
            assert f'automerge_tpu_shard_pump_seconds{{shard="{sid}"}}' \
                in page
        free = _router(2, [0.0])
        free.open_tenant('t0')
        free.pump(now=0.0)
        assert all(s.ticks_slipped == 0 for s in free.shards.values())

    def test_obs_report_metrics_mode_surfaces_slips(self, tmp_path):
        from automerge_tpu.observability.export import MetricsExporter
        clk = [0.0]
        router = _router(2, clk, tick_budget_s=0.0)
        router.open_tenant('t0')
        _settle(router, clk, router.submit('t0', 'apply',
                                           [_change('aa' * 16, 1)]))
        snap = tmp_path / 'metrics.prom'
        MetricsExporter(port=None, router=router,
                        snapshot_path=str(snap)).write_snapshot()
        import obs_report
        out = io.StringIO()
        obs_report.render_metrics(str(snap), out=out)
        text = out.getvalue()
        assert 'per-shard slipped ticks' in text
        assert 'shard_ticks_slipped_total{shard="shard0"}' in text


class TestAntiEntropyScrub:
    def test_scrub_flags_silent_divergence_and_heals(self):
        """A replica whose state rotted OUT OF BAND (stand-in: the
        handle swapped for an empty doc) while the pair believes itself
        converged-quiet: the scrub flags it with a typed mismatch event
        and resets the handshake, and the next rounds re-converge the
        pair byte-identically — earlier than the tenant's next write
        would have surfaced it."""
        from automerge_tpu.fleet import backend as fleet_backend
        base = shard_stats()['shard_scrub_mismatches']
        clk = [0.0]
        router = _router(2, clk, scrub_every=5)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('aa' * 16, 1)]))
        assert ticket.status == 'ok', ticket.error
        assert router.run_until_quiet(200, advance=0.02)
        assert rec.quiet
        # memory-rot stand-in: replica doc replaced by an empty one,
        # with the pair's bookkeeping still claiming convergence
        rec.replica_handle = fleet_backend.init(
            router.shards[rec.replica_on].fleet)
        rec.last_pair_heads = (rec.last_pair_heads[0], ())
        rec.quiet = True
        found = router.scrub_frontiers()
        assert found == 1
        assert shard_stats()['shard_scrub_mismatches'] == base + 1
        assert router.scrub_mismatches[-1]['tenant'] == 't0'
        assert not rec.quiet
        assert router.run_until_quiet(400, advance=0.02)
        assert bytes(host_backend.save(rec.session.handle)) == \
            bytes(host_backend.save(rec.replica_handle))

    def test_scrub_skips_lagging_and_racing_pairs(self):
        """Normal replication lag (quiet=False) and a home write that
        raced the scrub must NOT flag — divergence events mean damage,
        not traffic."""
        clk = [0.0]
        router = _router(2, clk, scrub_every=0)
        router.open_tenant('t0')
        rec = router.tenant_record('t0')
        ticket = _settle(router, clk, router.submit(
            't0', 'apply', [_change('aa' * 16, 1)]))
        assert ticket.status == 'ok', ticket.error
        assert router.run_until_quiet(200, advance=0.02)
        before = shard_stats()['shard_scrub_mismatches']
        # a home-side write the rounds have not replicated yet: heads
        # differ, home frontier moved -> the scrub must stay silent
        rec.session.handle = host_backend.apply_changes(
            rec.session.handle, [_change('aa' * 16, 2)])[0]
        assert router.scrub_frontiers() == 0
        assert shard_stats()['shard_scrub_mismatches'] == before
        assert router.run_until_quiet(200, advance=0.02)


class TestLinkFaults:
    def test_partition_darkens_then_heals(self):
        link = LossyLink(seed=0)
        assert link.partition(3)
        assert link.dark
        assert link.transmit(b'hello') == []
        assert link.stats['partitioned'] == 1
        assert link.stats['dark_dropped'] == 1
        for _ in range(3):
            link.tick()
        assert not link.dark
        assert link.transmit(b'hello') == [b'hello']

    def test_crash_drops_held_reorder_state(self):
        link = LossyLink(seed=1, p_reorder=1.0)
        assert link.transmit(b'first') == []      # held by the reorder
        assert link._held is not None
        assert link.crash(2)
        assert link._held is None                  # died with the peer
        assert link.stats['crashed'] == 1
        assert link.transmit(b'second') == []      # dark
        link.tick()
        link.tick()
        assert not link.dark

    def test_budget_bounds_dark_windows(self):
        link = LossyLink(seed=2, budget=1)
        assert link.partition(2)
        assert not link.partition(2)       # budget dry: no new window
        assert link.stats['partitioned'] == 1

    def test_wire_faults_health_counts_dark_windows(self):
        from automerge_tpu.observability import health_counts
        before = health_counts()['wire_faults']
        link = LossyLink(seed=3)
        link.partition(1)
        link.crash(1)
        assert health_counts()['wire_faults'] == before + 2

    def test_sync_until_quiet_converges_across_partition(self):
        """A dead-peer window mid-handshake (distinct from per-message
        loss: EVERY message in the window vanishes) heals and the
        protocol + reconnect policy still converge."""
        rng = random.Random(0)
        doc_a = host_backend.init()
        doc_b = host_backend.init()
        for seq in range(1, 6):
            doc_a, _ = host_backend.apply_changes(doc_a, [_change(
                'aa' * 16, seq, rng.randrange(100))])
        link_ab = LossyLink(seed=4, p_partition=0.3, partition_ticks=4,
                            budget=3)
        link_ba = LossyLink(seed=5)
        a, b, rounds, stats = sync_until_quiet(
            doc_a, doc_b, host_backend, host_backend,
            link_ab=link_ab, link_ba=link_ba, stall_reset=4)
        assert sorted(host_backend.get_heads(a)) == \
            sorted(host_backend.get_heads(b))
        assert link_ab.stats['partitioned'] >= 1
        assert link_ab.stats['dark_dropped'] >= 1


class TestShardObservability:
    def test_prometheus_shard_label_on_every_sample(self):
        page = render_prometheus(shard='shard7')
        samples = [line for line in page.splitlines()
                   if line and not line.startswith('#')]
        assert samples
        assert all('shard="shard7"' in line for line in samples), \
            [line for line in samples if 'shard=' not in line][:3]
        assert 'shard=' not in render_prometheus()

    def test_exporter_carries_shard_label(self):
        from automerge_tpu.observability.export import MetricsExporter
        exporter = MetricsExporter(port=None, shard='s1')
        assert 'shard="s1"' in exporter.render()

    def test_stitch_shard_labels_and_ring_truncation(self, tmp_path):
        """A restarted shard exports a WRAPPED span ring: stitch must
        label both shard inputs, disclose the truncation, and still
        report the trace id continuous across the failover."""
        import obs_report
        trace_id = 'deadbeef00000001'
        obs_on(span_capacity=128)
        try:
            clear_spans()
            with span('service_tick', trace=trace_id):
                pass
            export_chrome_trace(str(tmp_path / 'a.json'))
            # the 'restarted' shard: its ring wrapped, older spans gone
            clear_spans()
            for i in range(130):       # > capacity: forces the wrap
                with span('filler', i=i):
                    pass
            with span('sync_receive', trace=trace_id):
                pass
            export_chrome_trace(str(tmp_path / 'b.json'))
        finally:
            obs_off()
        out = io.StringIO()
        shared = obs_report.render_stitch(
            [f'shard0={tmp_path / "a.json"}',
             f'shard1={tmp_path / "b.json"}'],
            str(tmp_path / 'stitched.json'), out=out)
        text = out.getvalue()
        assert trace_id in shared          # continuous across the wrap
        assert 'shard shard1: span ring truncated' in text
        with open(tmp_path / 'stitched.json') as f:
            merged = json.load(f)
        names = [e['args']['name'] for e in merged['traceEvents']
                 if e.get('ph') == 'M']
        assert names == ['shard0', 'shard1']
