"""Backend conformance tests, ported from reference test/backend_test.js.

These pin the exact patch grammar and (via hard-coded SHA-256 change hashes
from the reference test suite) cross-implementation wire compatibility.
"""

import pytest

from automerge_tpu import backend as Backend
from automerge_tpu.columnar import encode_change, decode_change

ACTOR1 = '111111'
ACTOR2 = '222222'
ACTOR3 = '333333'


def hash_of(change):
    return decode_change(encode_change(change))['hash']


def set_op(obj, key, value, pred=(), **kw):
    op = {'action': 'set', 'obj': obj, 'key': key, 'value': value,
          'pred': list(pred)}
    op.update(kw)
    return op


class TestIncrementalDiffs:
    def test_assign_to_map_key(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            set_op('_root', 'bird', 'magpie')]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 1,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {f'1@{actor}': {'type': 'value', 'value': 'magpie'}}}}}

    def test_increment_map_key(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            set_op('_root', 'counter', 1, datatype='counter')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter', 'value': 2,
             'pred': [f'1@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'counter': {f'1@{actor}': {'type': 'value', 'value': 3,
                                           'datatype': 'counter'}}}}}

    def test_conflict_on_assignment(self):
        change1 = {'actor': ACTOR1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': ACTOR2, 'seq': 1, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)],
                   'ops': [set_op('_root', 'bird', 'blackbird')]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {ACTOR1: 1, ACTOR2: 1}, 'deps': [hash_of(change2)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {'1@111111': {'type': 'value', 'value': 'magpie'},
                         '2@222222': {'type': 'value', 'value': 'blackbird'}}}}}

    def test_delete_map_key(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'bird', 'pred': [f'1@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'bird': {}}}}

    def test_create_nested_maps(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{actor}', 'wrens', 3)]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map', 'props': {
                    'wrens': {f'2@{actor}': {'type': 'value', 'value': 3,
                                             'datatype': 'int'}}}}}}}}

    def test_assign_in_nested_maps(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{actor}', 'wrens', 3)]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            set_op(f'1@{actor}', 'sparrows', 15)]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map', 'props': {
                    'sparrows': {f'3@{actor}': {'type': 'value', 'value': 15,
                                                'datatype': 'int'}}}}}}}}

    def test_delete_nested_map(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{actor}', 'wrens', 3)]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'birds', 'pred': [f'1@{actor}']}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        assert patch1 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {}}}}

    def test_conflicts_on_nested_maps(self):
        a1, a2 = '012345', '89abcd'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{a1}', 'wrens', 3)]}
        change2 = {'actor': a1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': [f'1@{a1}']},
            set_op(f'3@{a1}', 'hawks', 1)]}
        change3 = {'actor': a2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': [f'1@{a1}']},
            set_op(f'3@{a2}', 'sparrows', 15)]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(
            s0, [encode_change(c) for c in (change1, change2, change3)])
        assert patch1 == {
            'clock': {a1: 2, a2: 1},
            'deps': sorted([hash_of(change2), hash_of(change3)]),
            'maxOp': 4, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'3@{a1}': {'objectId': f'3@{a1}', 'type': 'map', 'props': {
                    'hawks': {f'4@{a1}': {'type': 'value', 'value': 1,
                                          'datatype': 'int'}}}},
                f'3@{a2}': {'objectId': f'3@{a2}', 'type': 'map', 'props': {
                    'sparrows': {f'4@{a2}': {'type': 'value', 'value': 15,
                                             'datatype': 'int'}}}}}}}}

    def test_updates_inside_conflicted_map_keys(self):
        a1, a2 = '012345', '89abcd'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{a1}', 'hawks', 1)]}
        change2 = {'actor': a2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{a2}', 'sparrows', 15)]}
        change3 = {'actor': a1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': sorted([hash_of(change1), hash_of(change2)]), 'ops': [
            set_op(f'1@{a2}', 'sparrows', 17, pred=[f'2@{a2}'])]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change3)])
        assert patch2 == {
            'clock': {a1: 2, a2: 1}, 'deps': [hash_of(change3)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{a1}': {'objectId': f'1@{a1}', 'type': 'map', 'props': {}},
                f'1@{a2}': {'objectId': f'1@{a2}', 'type': 'map', 'props': {
                    'sparrows': {f'3@{a1}': {'type': 'value', 'value': 17,
                                             'datatype': 'int'}}}}}}}}

    def test_updates_inside_deleted_maps(self):
        a1, a2 = '012345', '89abcd'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{a1}', 'hawks', 1)]}
        change2 = {'actor': a2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'birds', 'pred': [f'1@{a1}']}]}
        change3 = {'actor': a1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            set_op(f'1@{a1}', 'hawks', 2, pred=[f'2@{a1}'])]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change3)])
        assert patch1 == {
            'clock': {a1: 1, a2: 1}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {}}}}
        assert patch2 == {
            'clock': {a1: 2, a2: 1},
            'deps': sorted([hash_of(change2), hash_of(change3)]), 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {}}}

    def test_create_lists(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 'chaffinch'}}]}}}}}

    def test_apply_updates_inside_lists(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'value': 'greenfinch', 'pred': [f'2@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'update', 'opId': f'3@{actor}', 'index': 0,
                     'value': {'type': 'value', 'value': 'greenfinch'}}]}}}}}

    def test_updates_to_objects_in_list_elements(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'pred': []},
            set_op(f'2@{actor}', 'title', 'buy milk'),
            set_op(f'2@{actor}', 'done', False)]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'pred': []},
            set_op(f'5@{actor}', 'title', 'water plants'),
            set_op(f'5@{actor}', 'done', False),
            set_op(f'2@{actor}', 'done', True, pred=[f'4@{actor}'])]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 8,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'5@{actor}',
                     'opId': f'5@{actor}', 'value': {
                         'objectId': f'5@{actor}', 'type': 'map', 'props': {
                             'title': {f'6@{actor}': {'type': 'value',
                                                      'value': 'water plants'}},
                             'done': {f'7@{actor}': {'type': 'value',
                                                     'value': False}}}}},
                    {'action': 'update', 'index': 1, 'opId': f'2@{actor}', 'value': {
                        'objectId': f'2@{actor}', 'type': 'map', 'props': {
                            'done': {f'8@{actor}': {'type': 'value',
                                                    'value': True}}}}}]}}}}}

    def test_updates_inside_conflicted_list_elements(self):
        a1, a2 = '01234567', '89abcdef'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{a1}', 'elemId': '_head',
             'insert': True, 'pred': []}]}
        change2 = {'actor': a1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': f'1@{a1}', 'elemId': f'2@{a1}',
             'pred': [f'2@{a1}']},
            set_op(f'3@{a1}', 'title', 'buy milk'),
            set_op(f'3@{a1}', 'done', False)]}
        change3 = {'actor': a2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': f'1@{a1}', 'elemId': f'2@{a1}',
             'pred': [f'2@{a1}']},
            set_op(f'3@{a2}', 'title', 'water plants'),
            set_op(f'3@{a2}', 'done', False)]}
        change4 = {'actor': a1, 'seq': 3, 'startOp': 6, 'time': 0,
                   'deps': sorted([hash_of(change2), hash_of(change3)]), 'ops': [
            set_op(f'3@{a1}', 'done', True, pred=[f'5@{a1}'])]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(
            s0, [encode_change(c) for c in (change1, change2, change3)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change4)])
        assert patch2 == {
            'clock': {a1: 3, a2: 1}, 'deps': [hash_of(change4)], 'maxOp': 6,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{a1}': {'objectId': f'1@{a1}', 'type': 'list', 'edits': [
                    {'action': 'update', 'index': 0, 'opId': f'3@{a1}', 'value': {
                        'objectId': f'3@{a1}', 'type': 'map', 'props': {
                            'done': {f'6@{a1}': {'type': 'value', 'value': True}}}}},
                    {'action': 'update', 'index': 0, 'opId': f'3@{a2}', 'value': {
                        'objectId': f'3@{a2}', 'type': 'map', 'props': {}}}]}}}}}

    def test_overwrite_list_elements(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'pred': []},
            set_op(f'2@{actor}', 'title', 'buy milk'),
            set_op(f'2@{actor}', 'done', False)]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': False, 'pred': [f'2@{actor}']},
            set_op(f'5@{actor}', 'title', 'water plants'),
            set_op(f'5@{actor}', 'done', False)]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        assert patch1 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 7,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'5@{actor}', 'value': {
                         'objectId': f'5@{actor}', 'type': 'map', 'props': {
                             'title': {f'6@{actor}': {'type': 'value',
                                                      'value': 'water plants'}},
                             'done': {f'7@{actor}': {'type': 'value',
                                                     'value': False}}}}}]}}}}}

    def test_delete_list_elements(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'pred': [f'2@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'remove', 'index': 0, 'count': 1}]}}}}}

    def test_insert_and_delete_same_change(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'pred': [f'2@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 3,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 'chaffinch'}},
                    {'action': 'remove', 'index': 0, 'count': 1}]}}}}}

    def test_changes_within_conflicted_objects(self):
        a1, a2 = '012345', '89abcd'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'conflict', 'pred': []}]}
        change2 = {'actor': a2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'conflict', 'pred': []}]}
        change3 = {'actor': a2, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change2)], 'ops': [
            set_op(f'1@{a2}', 'sparrows', 12)]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, _ = Backend.apply_changes(s1, [encode_change(change2)])
        s3, patch3 = Backend.apply_changes(s2, [encode_change(change3)])
        assert patch3 == {
            'clock': {a1: 1, a2: 2}, 'maxOp': 2, 'pendingChanges': 0,
            'deps': sorted([hash_of(change1), hash_of(change3)]),
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'conflict': {
                f'1@{a1}': {'objectId': f'1@{a1}', 'type': 'list', 'edits': []},
                f'1@{a2}': {'objectId': f'1@{a2}', 'type': 'map', 'props': {
                    'sparrows': {f'2@{a2}': {'type': 'value', 'value': 12,
                                             'datatype': 'int'}}}}}}}}

    def test_timestamp_at_root(self):
        actor = 'aaaa11'
        now = 1609459200123
        change = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            set_op('_root', 'now', now, datatype='timestamp')]}
        s0 = Backend.init()
        s1, patch = Backend.apply_changes(s0, [encode_change(change)])
        assert patch == {
            'clock': {actor: 1}, 'deps': [hash_of(change)], 'maxOp': 1,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'now': {f'1@{actor}': {'type': 'value', 'value': now,
                                       'datatype': 'timestamp'}}}}}

    def test_updates_to_deleted_object(self):
        a1, a2 = '012345', '89abcd'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{a1}', 'blackbirds', 2)]}
        change2 = {'actor': a2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'birds', 'pred': [f'1@{a1}']}]}
        change3 = {'actor': a1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            set_op(f'1@{a1}', 'blackbirds', 2)]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, _ = Backend.apply_changes(s1, [encode_change(change2)])
        s3, patch3 = Backend.apply_changes(s2, [encode_change(change3)])
        assert patch3 == {
            'clock': {a1: 2, a2: 1}, 'maxOp': 3, 'pendingChanges': 0,
            'deps': sorted([hash_of(change2), hash_of(change3)]),
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {}}}

    def test_multi_insert_int(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True, 'elemId': '_head',
             'pred': [], 'datatype': 'int', 'values': [1, 2, 3, 4, 5]}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 6,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                    {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{actor}',
                     'datatype': 'int', 'values': [1, 2, 3, 4, 5]}]}}}}}

    def test_multi_insert_bool(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True, 'elemId': '_head',
             'pred': [], 'values': [True, True, False, True, False]}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1['diffs']['props']['todos'][f'1@{actor}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{actor}',
             'values': [True, True, False, True, False]}]

    def test_multi_insert_null(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True, 'elemId': '_head',
             'pred': [], 'values': [None, None, None]}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1['maxOp'] == 4
        assert patch1['diffs']['props']['todos'][f'1@{actor}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{actor}',
             'values': [None, None, None]}]

    def test_multi_delete(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True, 'elemId': '_head',
             'pred': [], 'datatype': 'int', 'values': [1, 2, 3, 4, 5]}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 7, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'multiOp': 3, 'pred': [f'3@{actor}']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change2)])
        assert patch2['diffs']['props']['todos'][f'1@{actor}']['edits'] == [
            {'action': 'remove', 'index': 1, 'count': 3}]


class TestApplyLocalChange:
    def test_apply_change_requests(self):
        change1 = {'actor': ACTOR1, 'seq': 1, 'time': 0, 'startOp': 1, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        s0 = Backend.init()
        s1, patch1, _bin = Backend.apply_local_change(s0, change1)
        changes01 = [decode_change(c) for c in Backend.get_all_changes(s1)]
        assert patch1 == {
            'actor': ACTOR1, 'seq': 1, 'clock': {ACTOR1: 1}, 'deps': [],
            'maxOp': 1, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {'1@111111': {'type': 'value', 'value': 'magpie'}}}}}
        # exact hash from the reference implementation (backend_test.js:745)
        assert changes01 == [{
            'hash': '2c2845859ce4336936f56410f9161a09ba269f48aee5826782f1c389ec01d054',
            'actor': ACTOR1, 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
            'deps': [], 'ops': [
                {'action': 'set', 'obj': '_root', 'key': 'bird', 'insert': False,
                 'value': 'magpie', 'pred': []}]}]

    def test_duplicate_requests_throw(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'time': 0, 'startOp': 1, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': actor, 'seq': 2, 'time': 0, 'startOp': 2, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'jay')]}
        s0 = Backend.init()
        s1, _, _ = Backend.apply_local_change(s0, change1)
        s2, _, _ = Backend.apply_local_change(s1, change2)
        with pytest.raises(ValueError, match='Change request has already been applied'):
            Backend.apply_local_change(s2, dict(change1))
        with pytest.raises(ValueError, match='Change request has already been applied'):
            Backend.apply_local_change(s2, dict(change2))

    def test_concurrent_frontend_backend_changes(self):
        local1 = {'actor': ACTOR1, 'seq': 1, 'time': 0, 'startOp': 1, 'deps': [],
                  'ops': [set_op('_root', 'bird', 'magpie')]}
        local2 = {'actor': ACTOR1, 'seq': 2, 'time': 0, 'startOp': 2, 'deps': [],
                  'ops': [set_op('_root', 'bird', 'jay', pred=['1@111111'])]}
        remote1 = {'actor': ACTOR2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'fish', 'goldfish')]}
        s0 = Backend.init()
        s1, _, _ = Backend.apply_local_change(s0, local1)
        s2, _ = Backend.apply_changes(s1, [encode_change(remote1)])
        s3, _, _ = Backend.apply_local_change(s2, local2)
        changes = [decode_change(c) for c in Backend.get_all_changes(s3)]
        assert changes[0]['hash'] == \
            '2c2845859ce4336936f56410f9161a09ba269f48aee5826782f1c389ec01d054'
        assert changes[1]['hash'] == \
            'efc7e9b1b809364fb1b7029d2838dd3c7cf539eea595b22f9ae665505187f6c4'
        assert changes[2]['hash'] == \
            'e7ed7a790432aba39fe7ad75fa9e02a9fc8d8e9ee4ec8c81dcc93da15a561f8a'
        assert changes[2]['deps'] == [changes[0]['hash']]

    def test_insert_delete_same_local_change(self):
        local1 = {'actor': ACTOR1, 'seq': 1, 'startOp': 1, 'deps': [], 'time': 0,
                  'ops': [{'obj': '_root', 'action': 'makeList', 'key': 'birds',
                           'pred': []}]}
        local2 = {'actor': ACTOR1, 'seq': 2, 'startOp': 2, 'deps': [], 'time': 0,
                  'ops': [
            {'obj': '1@111111', 'action': 'set', 'elemId': '_head', 'insert': True,
             'value': 'magpie', 'pred': []},
            {'obj': '1@111111', 'action': 'del', 'elemId': '2@111111',
             'pred': ['2@111111']}]}
        s0 = Backend.init()
        s1, _, _ = Backend.apply_local_change(s0, local1)
        s2, patch2, _ = Backend.apply_local_change(s1, local2)
        assert patch2 == {
            'actor': ACTOR1, 'seq': 2, 'clock': {ACTOR1: 2}, 'deps': [],
            'maxOp': 3, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                '1@111111': {'objectId': '1@111111', 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': '2@111111',
                     'opId': '2@111111',
                     'value': {'type': 'value', 'value': 'magpie'}},
                    {'action': 'remove', 'index': 0, 'count': 1}]}}}}}
        changes = [decode_change(c) for c in Backend.get_all_changes(s2)]
        assert changes[1]['hash'] == \
            'deef4c9b9ca378844144c4bbc5d82a52f30c95a8624f13f243fe8f1214e8e833'

    def test_conflict_resolution(self):
        change1 = {'actor': ACTOR1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': ACTOR2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'blackbird')]}
        change3 = {'actor': ACTOR3, 'seq': 1, 'startOp': 2, 'time': 0,
                   'deps': sorted([hash_of(change1), hash_of(change2)]),
                   'ops': [set_op('_root', 'bird', 'robin',
                                  pred=['1@111111', '1@222222'])]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        s2, patch2, _ = Backend.apply_local_change(s1, dict(change3))
        assert patch2 == {
            'clock': {ACTOR1: 1, ACTOR2: 1, ACTOR3: 1}, 'deps': [],
            'actor': ACTOR3, 'seq': 1, 'maxOp': 2, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {'2@333333': {'type': 'value', 'value': 'robin'}}}}}

    def test_deflate_changes(self):
        long_string = 'a' * 1024
        change1 = {'actor': ACTOR1, 'seq': 1, 'time': 0, 'startOp': 1, 'deps': [],
                   'ops': [set_op('_root', 'longString', long_string)]}
        s1, _, _ = Backend.apply_local_change(Backend.init(), change1)
        changes = Backend.get_all_changes(s1)
        assert len(changes[0]) < 100
        s2, patch2 = Backend.apply_changes(Backend.init(), changes)
        assert patch2['diffs']['props']['longString'] == {
            '1@111111': {'type': 'value', 'value': long_string}}


class TestSaveLoad:
    def test_reconstruct_conflict_resolving_changes(self):
        a1, a2 = '8765', '1234'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': a2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'blackbird')]}
        change3 = {'actor': a1, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': sorted([hash_of(change1), hash_of(change2)]),
                   'ops': [set_op('_root', 'bird', 'robin',
                                  pred=[f'1@{a1}', f'1@{a2}'])]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(c) for c in (change1, change2, change3)])
        s2 = Backend.load(Backend.save(s1))
        assert Backend.get_heads(s2) == [hash_of(change3)]

    def test_deflate_columns(self):
        long_string = 'a' * 1024
        change1 = {'actor': ACTOR1, 'seq': 1, 'time': 0, 'startOp': 1, 'deps': [],
                   'ops': [set_op('_root', 'longString', long_string)]}
        doc = Backend.save(Backend.load_changes(Backend.init(), [encode_change(change1)]))
        assert len(doc) < 200
        patch = Backend.get_patch(Backend.load(doc))
        assert patch == {
            'clock': {ACTOR1: 1}, 'deps': [hash_of(change1)], 'maxOp': 1,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'longString': {'1@111111': {'type': 'value', 'value': long_string}}}}}

    def test_save_load_round_trip_lists(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'goldfinch', 'pred': []}]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change1)])
        s2 = Backend.load(Backend.save(s1))
        assert Backend.get_patch(s2) == Backend.get_patch(
            Backend.load_changes(Backend.init(), [encode_change(change1)]))


class TestGetPatch:
    def test_most_recent_value(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)],
                   'ops': [set_op('_root', 'bird', 'blackbird', pred=[f'1@{actor}'])]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(change1), encode_change(change2)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {f'2@{actor}': {'type': 'value', 'value': 'blackbird'}}}}}

    def test_conflicting_values(self):
        change1 = {'actor': ACTOR1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': ACTOR2, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'blackbird')]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(change1), encode_change(change2)])
        assert Backend.get_patch(s1)['diffs']['props']['bird'] == {
            '1@111111': {'type': 'value', 'value': 'magpie'},
            '1@222222': {'type': 'value', 'value': 'blackbird'}}

    def test_counter_increments(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'counter', 1, datatype='counter')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter', 'value': 2,
             'pred': [f'1@{actor}']}]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(change1), encode_change(change2)])
        assert Backend.get_patch(s1)['diffs']['props']['counter'] == {
            f'1@{actor}': {'type': 'value', 'value': 3, 'datatype': 'counter'}}

    def test_counter_deletion(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'counter', 1, datatype='counter')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'inc', 'obj': '_root', 'key': 'counter', 'value': 2,
             'pred': [f'1@{actor}']}]}
        change3 = {'actor': actor, 'seq': 3, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change2)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'counter',
             'pred': [f'1@{actor}']}]}
        s1 = Backend.load_changes(
            Backend.init(),
            [encode_change(c) for c in (change1, change2, change3)])
        assert Backend.get_patch(s1)['diffs'] == \
            {'objectId': '_root', 'type': 'map', 'props': {}}

    def test_latest_list_state(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'goldfinch', 'pred': []}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'pred': [f'2@{actor}']},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'greenfinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'value': 'goldfinches!!', 'pred': [f'3@{actor}']}]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(change1), encode_change(change2)])
        assert Backend.get_patch(s1)['diffs']['props']['birds'][f'1@{actor}'] == {
            'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                {'action': 'insert', 'index': 0, 'elemId': f'5@{actor}',
                 'opId': f'5@{actor}',
                 'value': {'type': 'value', 'value': 'greenfinch'}},
                {'action': 'insert', 'index': 1, 'elemId': f'3@{actor}',
                 'opId': f'6@{actor}',
                 'value': {'type': 'value', 'value': 'goldfinches!!'}}]}

    def test_conflicts_on_list_elements(self):
        a1, a2 = '01234567', '89abcdef'
        change1 = {'actor': a1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{a1}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{a1}', 'elemId': f'2@{a1}', 'insert': True,
             'value': 'magpie', 'pred': []}]}
        change2 = {'actor': a1, 'seq': 2, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{a1}', 'elemId': f'2@{a1}',
             'value': 'greenfinch', 'pred': [f'2@{a1}']}]}
        change3 = {'actor': a2, 'seq': 1, 'startOp': 4, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'set', 'obj': f'1@{a1}', 'elemId': f'2@{a1}',
             'value': 'goldfinch', 'pred': [f'2@{a1}']}]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(c) for c in (change1, change2, change3)])
        assert Backend.get_patch(s1)['diffs']['props']['birds'][f'1@{a1}'] == {
            'objectId': f'1@{a1}', 'type': 'list', 'edits': [
                {'action': 'insert', 'index': 0, 'elemId': f'2@{a1}',
                 'opId': f'4@{a1}',
                 'value': {'type': 'value', 'value': 'greenfinch'}},
                {'action': 'update', 'index': 0, 'opId': f'4@{a2}',
                 'value': {'type': 'value', 'value': 'goldfinch'}},
                {'action': 'insert', 'index': 1, 'elemId': f'3@{a1}',
                 'opId': f'3@{a1}',
                 'value': {'type': 'value', 'value': 'magpie'}}]}

    def test_condense_multiple_inserts(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'goldfinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'insert': True, 'values': ['bullfinch', 'greenfinch'], 'pred': []}]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change1)])
        assert Backend.get_patch(s1)['diffs']['props']['birds'][f'1@{actor}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'2@{actor}',
             'values': ['chaffinch', 'goldfinch', 'bullfinch', 'greenfinch']}]

    def test_multi_insert_only_consecutive(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'value': 'goldfinch', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head', 'insert': True,
             'values': ['bullfinch', 'greenfinch'], 'pred': []}]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change1)])
        assert Backend.get_patch(s1)['diffs']['props']['birds'][f'1@{actor}']['edits'] == [
            {'action': 'multi-insert', 'index': 0, 'elemId': f'4@{actor}',
             'values': ['bullfinch', 'greenfinch']},
            {'action': 'multi-insert', 'index': 2, 'elemId': f'2@{actor}',
             'values': ['chaffinch', 'goldfinch']}]


class TestCausalGating:
    def test_pending_changes(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)],
                   'ops': [set_op('_root', 'bird', 'jay', pred=[f'1@{actor}'])]}
        s0 = Backend.init()
        # Apply change2 before change1: it must be queued
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change2)])
        assert patch1['pendingChanges'] == 1
        assert patch1['diffs'] == {'objectId': '_root', 'type': 'map', 'props': {}}
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change1)])
        assert patch2['pendingChanges'] == 0
        assert patch2['clock'] == {actor: 2}
        assert patch2['diffs']['props']['bird'] == {
            f'2@{actor}': {'type': 'value', 'value': 'jay'}}
        assert Backend.get_missing_deps(s2) == []

    def test_missing_deps_reported(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(change1)],
                   'ops': [set_op('_root', 'bird', 'jay', pred=[f'1@{actor}'])]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change2)])
        assert Backend.get_missing_deps(s1) == [hash_of(change1)]

    def test_duplicate_changes_ignored(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change1)])
        assert patch2['clock'] == {actor: 1}
        assert len(Backend.get_all_changes(s2)) == 1

    def test_seq_gap_throws(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        change3 = {'actor': actor, 'seq': 3, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)],
                   'ops': [set_op('_root', 'bird', 'jay')]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        with pytest.raises(ValueError, match='Skipped sequence number'):
            Backend.apply_changes(s1, [encode_change(change3)])


class TestFrozenHandles:
    def test_stale_handle_raises(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
                   'ops': [set_op('_root', 'bird', 'magpie')]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        with pytest.raises(ValueError, match='outdated Automerge document'):
            Backend.apply_changes(s0, [encode_change(change1)])


class TestIncrementalDiffsMore:
    """Remaining incremental-diff cases (ref backend_test.js:452-719)."""

    def test_timestamp_in_a_list(self):
        actor = 'aaaa11'
        now_ms = 1589032171000
        change = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': now_ms, 'datatype': 'timestamp',
             'pred': []}]}
        s0 = Backend.init()
        s1, patch = Backend.apply_changes(s0, [encode_change(change)])
        assert patch == {
            'clock': {actor: 1}, 'deps': [hash_of(change)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'list': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': now_ms,
                               'datatype': 'timestamp'}}]}}}}}

    def test_updates_to_deleted_map_object(self):
        actor1, actor2 = 'aaaa11', 'bbbb22'
        change1 = {'actor': actor1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{actor1}', 'blackbirds', 2)]}
        change2 = {'actor': actor2, 'seq': 1, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': '_root', 'key': 'birds',
             'pred': [f'1@{actor1}']}]}
        change3 = {'actor': actor1, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            set_op(f'1@{actor1}', 'blackbirds', 2)]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(change1)])
        s2, _ = Backend.apply_changes(s1, [encode_change(change2)])
        s3, patch3 = Backend.apply_changes(s2, [encode_change(change3)])
        assert patch3 == {
            'clock': {actor1: 2, actor2: 1}, 'maxOp': 3, 'pendingChanges': 0,
            'deps': sorted([hash_of(change2), hash_of(change3)]),
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {}}}

    def test_updates_to_deleted_list_element(self):
        actor1, actor2 = 'aaaa11', 'bbbb22'
        change1 = {'actor': actor1, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor1}', 'elemId': '_head',
             'insert': True, 'pred': []},
            set_op(f'2@{actor1}', 'title', 'buy milk'),
            set_op(f'2@{actor1}', 'done', False)]}
        change2 = {'actor': actor2, 'seq': 1, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor1}', 'elemId': f'2@{actor1}',
             'pred': [f'2@{actor1}']}]}
        change3 = {'actor': actor1, 'seq': 2, 'startOp': 5, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            set_op(f'2@{actor1}', 'done', True, [f'4@{actor1}'])]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(
            s0, [encode_change(change1), encode_change(change2)])
        s2, patch2 = Backend.apply_changes(s1, [encode_change(change3)])
        assert patch1 == {
            'clock': {actor1: 1, actor2: 1}, 'deps': [hash_of(change2)],
            'maxOp': 5, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor1}': {'objectId': f'1@{actor1}', 'type': 'list',
                                'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor1}',
                     'opId': f'2@{actor1}', 'value': {
                        'objectId': f'2@{actor1}', 'type': 'map', 'props': {
                            'title': {f'3@{actor1}': {'type': 'value',
                                                      'value': 'buy milk'}},
                            'done': {f'4@{actor1}': {'type': 'value',
                                                     'value': False}}}}},
                    {'action': 'remove', 'index': 0, 'count': 1}]}}}}}
        assert patch2 == {
            'clock': {actor1: 2, actor2: 1},
            'deps': sorted([hash_of(change2), hash_of(change3)]),
            'maxOp': 5, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {}}}

    def test_nested_maps_in_lists_diff(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'value': 'first'},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': f'2@{actor}',
             'insert': True, 'pred': []},
            set_op(f'3@{actor}', 'title', 'water plants'),
            set_op(f'3@{actor}', 'done', False)]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 5,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 'first'}},
                    {'action': 'insert', 'index': 1, 'elemId': f'3@{actor}',
                     'opId': f'3@{actor}', 'value': {
                        'type': 'map', 'objectId': f'3@{actor}', 'props': {
                            'title': {f'4@{actor}': {
                                'type': 'value', 'value': 'water plants'}},
                            'done': {f'5@{actor}': {
                                'type': 'value', 'value': False}}}}}]}}}}}

    def _multi_insert_case(self, datatype, values):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'datatype': datatype,
             'values': values}]}
        s0 = Backend.init()
        s1, patch1 = Backend.apply_changes(s0, [encode_change(change1)])
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)],
            'maxOp': 1 + len(values), 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'multi-insert', 'index': 0,
                     'elemId': f'2@{actor}', 'datatype': datatype,
                     'values': values}]}}}}}

    def test_multi_insert_uint(self):
        self._multi_insert_case('uint', [1, 2, 3, 4, 5])

    def test_multi_insert_float64(self):
        self._multi_insert_case('float64', [1.0, 2.0, 3.3, 4.0, 5.0])

    def test_multi_insert_timestamp(self):
        self._multi_insert_case('timestamp', [1, 2, 3, 4, 5])

    def test_multi_insert_counter(self):
        self._multi_insert_case('counter', [1, 2, 3, 4, 5])

    def test_multi_insert_datatype_mismatch_throws(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'datatype': 'int',
             'values': [1, True, 'hello']}]}
        s0 = Backend.init()
        with pytest.raises(Exception):
            Backend.apply_local_change(s0, change1)


class TestApplyLocalChangeMore:
    """Remaining applyLocalChange cases (ref backend_test.js:788-1007)."""

    def test_detects_conflicts_based_on_frontend_version(self):
        local1 = {'requestType': 'change', 'actor': '111111', 'seq': 1,
                  'time': 0, 'startOp': 1, 'deps': [], 'ops': [
            set_op('_root', 'bird', 'goldfinch')]}
        s0 = Backend.init()
        s1, patch1, _bin = Backend.apply_local_change(s0, local1)
        first_hash = decode_change(Backend.get_all_changes(s1)[0])['hash']
        remote1 = {'actor': '222222', 'seq': 1, 'startOp': 2, 'time': 0,
                   'deps': [first_hash], 'ops': [
            set_op('_root', 'bird', 'magpie', ['1@111111'])]}
        local2 = {'requestType': 'change', 'actor': '111111', 'seq': 2,
                  'time': 0, 'startOp': 2, 'deps': [], 'ops': [
            set_op('_root', 'bird', 'jay', ['1@111111'])]}
        s2, patch2 = Backend.apply_changes(s1, [encode_change(remote1)])
        s3, patch3, _bin = Backend.apply_local_change(s2, local2)
        changes = [decode_change(c) for c in Backend.get_all_changes(s3)]
        assert patch3 == {
            'actor': '111111', 'seq': 2, 'clock': {'111111': 2, '222222': 1},
            'deps': [hash_of(remote1)], 'maxOp': 2, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'bird': {
                '2@222222': {'type': 'value', 'value': 'magpie'},
                '2@111111': {'type': 'value', 'value': 'jay'}}}}}
        assert changes[2]['hash'] == \
            '7a00e28d7fbf179708a1b0045c7f9bad93366c0e69f9af15e830dae9970a9d19'
        assert changes[2]['ops'] == [
            {'action': 'set', 'obj': '_root', 'key': 'bird', 'insert': False,
             'value': 'jay', 'pred': ['1@111111']}]

    def test_transforms_list_indexes_into_element_ids(self):
        remote1 = {'actor': '222222', 'seq': 1, 'startOp': 1, 'time': 0,
                   'deps': [], 'ops': [
            {'obj': '_root', 'action': 'makeList', 'key': 'birds', 'pred': []}]}
        remote2 = {'actor': '222222', 'seq': 2, 'startOp': 2, 'time': 0,
                   'deps': [hash_of(remote1)], 'ops': [
            {'obj': '1@222222', 'action': 'set', 'elemId': '_head',
             'insert': True, 'value': 'magpie', 'pred': []}]}
        local1 = {'actor': '111111', 'seq': 1, 'startOp': 2, 'time': 0,
                  'deps': [hash_of(remote1)], 'ops': [
            {'obj': '1@222222', 'action': 'set', 'elemId': '_head',
             'insert': True, 'value': 'goldfinch', 'pred': []}]}
        local2 = {'actor': '111111', 'seq': 2, 'startOp': 3, 'time': 0,
                  'deps': [], 'ops': [
            {'obj': '1@222222', 'action': 'set', 'elemId': '2@111111',
             'insert': True, 'value': 'wagtail', 'pred': []}]}
        local3 = {'actor': '111111', 'seq': 3, 'startOp': 4, 'time': 0,
                  'deps': [hash_of(remote2)], 'ops': [
            {'obj': '1@222222', 'action': 'set', 'elemId': '2@222222',
             'value': 'Magpie', 'pred': ['2@222222']},
            {'obj': '1@222222', 'action': 'set', 'elemId': '2@111111',
             'value': 'Goldfinch', 'pred': ['2@111111']}]}
        s0 = Backend.init()
        s1, _ = Backend.apply_changes(s0, [encode_change(remote1)])
        s2, _, _bin = Backend.apply_local_change(s1, local1)
        s3, _ = Backend.apply_changes(s2, [encode_change(remote2)])
        s4, _, _bin = Backend.apply_local_change(s3, local2)
        s5, _, _bin = Backend.apply_local_change(s4, local3)
        changes = [decode_change(c) for c in Backend.get_all_changes(s5)]
        assert changes[1]['hash'] == \
            '06392148c4a0dfff8b346ad58a3261cc15187cbf8a58779f78d54251126d4ccc'
        assert changes[3]['hash'] == \
            '2801c386ec2a140376f3bef285a6e6d294a2d8fb7a180da4fbb6e2bc4f550dd9'
        assert changes[4]['hash'] == \
            '734f1dad5fb2f10970bae2baa6ce100c3b85b43072b3799d8f2e15bcd21297fc'
        assert changes[4]['deps'] == \
            sorted([hash_of(remote2), changes[3]['hash']])
        assert changes[4]['ops'] == [
            {'obj': '1@222222', 'action': 'set', 'elemId': '2@222222',
             'insert': False, 'value': 'Magpie', 'pred': ['2@222222']},
            {'obj': '1@222222', 'action': 'set', 'elemId': '2@111111',
             'insert': False, 'value': 'Goldfinch', 'pred': ['2@111111']}]

    def test_local_multi_insert_int(self):
        actor = 'aaaa11'
        local = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'datatype': 'int',
             'values': [1, 2, 3, 4, 5]}]}
        s0 = Backend.init()
        s1, patch1, _bin = Backend.apply_local_change(s0, local)
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [], 'maxOp': 6, 'actor': actor,
            'seq': 1, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'multi-insert', 'index': 0,
                     'elemId': f'2@{actor}', 'datatype': 'int',
                     'values': [1, 2, 3, 4, 5]}]}}}}}

    def test_local_multi_insert_float64(self):
        actor = 'aaaa11'
        local = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'datatype': 'float64',
             'values': [1.0, 2.0, 3.3, 4.0, 5.0]}]}
        s0 = Backend.init()
        s1, patch1, _bin = Backend.apply_local_change(s0, local)
        assert patch1 == {
            'clock': {actor: 1}, 'deps': [], 'maxOp': 6, 'actor': actor,
            'seq': 1, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'multi-insert', 'index': 0,
                     'elemId': f'2@{actor}', 'datatype': 'float64',
                     'values': [1.0, 2.0, 3.3, 4.0, 5.0]}]}}}}}

    def test_local_multi_delete(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'insert': True,
             'elemId': '_head', 'pred': [], 'datatype': 'int',
             'values': [1, 2, 3, 4, 5]}]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 7, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'elemId': f'3@{actor}',
             'multiOp': 3, 'pred': [f'3@{actor}']}]}
        s0 = Backend.init()
        s1, _, _bin = Backend.apply_local_change(s0, change1)
        s2, patch2, _bin = Backend.apply_local_change(s1, change2)
        assert patch2 == {
            'clock': {actor: 2}, 'deps': [], 'maxOp': 9, 'actor': actor,
            'seq': 2, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'remove', 'index': 1, 'count': 3}]}}}}}


class TestSaveLoadMore:
    """Remaining save/load cases (ref backend_test.js:1043-1058)."""

    def test_loads_floats_correctly(self):
        # Document bytes generated by the reference's companion Rust backend
        # (ref backend_test.js:1043-1058): { birds: 3.0 } with float64 kept
        # as a float through the document container.
        data = bytes([
            133, 111, 74, 131, 233, 181, 157, 86, 0, 144, 1, 1, 16, 228, 91,
            238, 197, 233, 52, 66, 187, 138, 75, 115, 104, 190, 195, 159, 200,
            1, 221, 158, 172, 238, 121, 38, 160, 123, 25, 33, 97, 124, 142,
            27, 86, 224, 238, 83, 14, 157, 207, 233, 8, 110, 91, 151, 172, 38,
            120, 221, 38, 162, 7, 1, 2, 3, 2, 19, 2, 35, 7, 53, 16, 64, 2, 86,
            2, 8, 21, 7, 33, 2, 35, 2, 52, 1, 66, 2, 86, 3, 87, 8, 128, 1, 2,
            127, 0, 127, 1, 127, 1, 127, 243, 145, 234, 194, 149, 47, 127, 14,
            73, 110, 105, 116, 105, 97, 108, 105, 122, 97, 116, 105, 111, 110,
            127, 0, 127, 7, 127, 5, 98, 105, 114, 100, 115, 127, 0, 127, 1, 1,
            127, 1, 127, 133, 1, 0, 0, 0, 0, 0, 0, 8, 64, 127, 0])
        import automerge_tpu as A
        doc = A.load(data)
        assert dict(doc) == {'birds': 3.0}
        assert isinstance(doc['birds'], float)


class TestGetPatchMore:
    """Remaining getPatch cases (ref backend_test.js:1130-1276)."""

    def test_get_patch_creates_nested_maps(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeMap', 'obj': '_root', 'key': 'birds', 'pred': []},
            set_op(f'1@{actor}', 'wrens', 3)]}
        change2 = {'actor': actor, 'seq': 2, 'startOp': 3, 'time': 0,
                   'deps': [hash_of(change1)], 'ops': [
            {'action': 'del', 'obj': f'1@{actor}', 'key': 'wrens',
             'pred': [f'2@{actor}']},
            set_op(f'1@{actor}', 'sparrows', 15)]}
        s1 = Backend.load_changes(
            Backend.init(), [encode_change(change1), encode_change(change2)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 2}, 'deps': [hash_of(change2)], 'maxOp': 4,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'map',
                               'props': {'sparrows': {f'4@{actor}': {
                                   'type': 'value', 'value': 15,
                                   'datatype': 'int'}}}}}}}}

    def test_get_patch_creates_lists(self):
        actor = 'aaaa11'
        change1 = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'birds', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': 'chaffinch', 'pred': []}]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change1)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 1}, 'deps': [hash_of(change1)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': 'chaffinch'}}]}}}}}

    def test_get_patch_nested_maps_in_lists(self):
        actor = 'aaaa11'
        change = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos', 'pred': []},
            {'action': 'makeMap', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'pred': []},
            set_op(f'2@{actor}', 'title', 'water plants'),
            set_op(f'2@{actor}', 'done', False)]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 1}, 'deps': [hash_of(change)], 'maxOp': 4,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'todos': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}', 'value': {
                        'type': 'map', 'objectId': f'2@{actor}', 'props': {
                            'title': {f'3@{actor}': {
                                'type': 'value', 'value': 'water plants'}},
                            'done': {f'4@{actor}': {
                                'type': 'value', 'value': False}}}}}]}}}}}

    def test_get_patch_timestamp_at_root(self):
        actor = 'aaaa11'
        now_ms = 1589032171000
        change = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            set_op('_root', 'now', now_ms, datatype='timestamp')]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 1}, 'deps': [hash_of(change)], 'maxOp': 1,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'now': {
                f'1@{actor}': {'type': 'value', 'value': now_ms,
                               'datatype': 'timestamp'}}}}}

    def test_get_patch_timestamp_in_list(self):
        actor = 'aaaa11'
        now_ms = 1589032171000
        change = {'actor': actor, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list', 'pred': []},
            {'action': 'set', 'obj': f'1@{actor}', 'elemId': '_head',
             'insert': True, 'value': now_ms, 'datatype': 'timestamp',
             'pred': []}]}
        s1 = Backend.load_changes(Backend.init(), [encode_change(change)])
        assert Backend.get_patch(s1) == {
            'clock': {actor: 1}, 'deps': [hash_of(change)], 'maxOp': 2,
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {'list': {
                f'1@{actor}': {'objectId': f'1@{actor}', 'type': 'list',
                               'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}',
                     'value': {'type': 'value', 'value': now_ms,
                               'datatype': 'timestamp'}}]}}}}}
