"""uuid helper tests (ported semantics of reference test/uuid_test.js)."""

import re

import automerge_tpu as am
from automerge_tpu.common import uuid, set_uuid_factory


class TestUuid:
    def test_generates_unique_values(self):
        a, b = uuid(), uuid()
        assert a != b
        assert re.fullmatch(r'[0-9a-f]{32}', a)

    def test_custom_factory(self):
        seq = iter(range(100))
        set_uuid_factory(lambda: f'custom-{next(seq)}')
        try:
            assert uuid() == 'custom-0'
            assert uuid() == 'custom-1'
        finally:
            set_uuid_factory(None)
        assert re.fullmatch(r'[0-9a-f]{32}', uuid())

    def test_factory_drives_actor_ids(self):
        set_uuid_factory(lambda: 'feedface')
        try:
            doc = am.init()
            assert am.get_actor_id(doc) == 'feedface'
        finally:
            set_uuid_factory(None)
