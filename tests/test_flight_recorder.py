"""Forensic flight-recorder dumps at the fault seams, phase attribution
of the hot paths, and the sync-round dispatch-count regression.

The contract under test: "quarantined_docs moved by 1" must come with a
forensic record naming WHICH doc (slot + durable id), WHAT phase, and
WHAT typed error, with the surrounding events — for hostile bytes on the
wire (batched apply, sync receive) and on disk (recovery)."""

import os

import pytest

from automerge_tpu import native, observability
from automerge_tpu.backend import init_sync_state
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, init_docs
from automerge_tpu.fleet.durability import DurableFleet
from automerge_tpu.fleet.sync_driver import (generate_sync_messages_docs,
                                             receive_sync_messages_docs)
from automerge_tpu.observability import recorder

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


@pytest.fixture(autouse=True)
def _clean_recorder():
    recorder.clear_events()
    yield
    recorder.clear_events()
    observability.disable()


def _change(actor, key, value, seq=1, deps=()):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': value, 'datatype': 'int', 'pred': []}]})


def _flip(buf, pos=10):
    out = bytearray(buf)
    out[pos] ^= 0xFF
    return bytes(out)


def test_quarantine_produces_forensic_dump():
    """A quarantining batch apply that rejects a doc must dump a flight
    record naming the doc's slot, phase ('decode'), and typed error."""
    n = 5
    fleet = DocFleet(doc_capacity=8, key_capacity=16)
    handles = init_docs(n, fleet)
    per_doc = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in range(n)]
    per_doc[2] = [_flip(per_doc[2][0])]
    dumps_before = recorder.flight_stats()['flight_dumps']

    from automerge_tpu.observability import hist as obs_hist
    obs_hist.reset()
    observability.enable()
    try:
        _h, _p, errors = fleet_backend.apply_changes_docs(
            handles, per_doc, mirror=False, on_error='quarantine')
    finally:
        observability.disable()
    assert errors[2] is not None
    # the quarantine retry loop re-parses survivors; their byte sizes
    # must still be recorded exactly ONCE (on the committing attempt)
    assert observability.histogram_snapshot()['doc_change_bytes'][
        'count'] == n - 1
    obs_hist.reset()

    assert recorder.flight_stats()['flight_dumps'] == dumps_before + 1
    report = observability.last_flight_record()
    assert report['trigger'] == 'quarantine'
    (err,) = report['detail']['errors']
    assert err['doc'] == 2
    assert err['stage'] == 'decode'
    assert err['error'] == 'MalformedChange'
    # the event ring carries the same rejection with a bytes digest
    ev = [e for e in report['events'] if e['kind'] == 'quarantine'][-1]
    assert ev['doc'] == 2 and ev['error'] == 'MalformedChange'
    assert ev['change_bytes'] > 0 and len(ev['digest']) == 16


def test_quarantine_dump_names_durable_id(tmp_path):
    """Journaled fleets: the forensic dump carries the document's durable
    journal id (the id recovery and the on-disk journal speak), not just
    the batch slot."""
    n = 4
    mgr = DurableFleet(str(tmp_path / 'fleet'))
    handles = mgr.init_docs(n)
    # one clean round assigns durable ids to every doc
    clean = [[_change(f'{i:02x}' * 16, 'k', i)] for i in range(n)]
    handles, _p, errs = mgr.apply_changes(handles, clean)
    assert not any(errs)
    dur_ids = [h['state']._dur_id for h in handles]

    poisoned = [[_change(f'{i:02x}' * 16, 'k2', i, seq=2,
                         deps=fleet_backend.get_heads(handles[i]))]
                for i in range(n)]
    poisoned[1] = [_flip(poisoned[1][0])]
    handles, _p, errors = mgr.apply_changes(handles, poisoned)
    assert errors[1] is not None
    report = observability.last_flight_record()
    (err,) = report['detail']['errors']
    assert err['doc'] == 1
    assert err['durable_id'] == dur_ids[1]
    assert err['error'] == 'MalformedChange'
    mgr.close()


def test_recovery_rot_produces_forensic_dump(tmp_path):
    """Mid-journal rot: recovery quarantines exactly the victim doc and
    dumps a flight record naming its durable id, the 'replay' stage, and
    the typed journal error."""
    path = str(tmp_path / 'fleet')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(3)
    handles, _p, errs = mgr.apply_changes(
        handles, [[_change(f'{i:02x}' * 16, 'k', i)] for i in range(3)])
    assert not any(errs)
    victim_id = handles[1]['state']._dur_id
    mgr.journal.sync()
    journal_path = mgr.journal.path
    mgr.journal.close()

    # rot one byte inside the victim's journal payload (scan for a frame
    # byte whose flip recovery reports as rot for doc 1)
    data = bytearray(open(journal_path, 'rb').read())
    data[len(data) // 2] ^= 0xFF
    open(journal_path, 'wb').write(bytes(data))

    mgr2, rec_handles, report = DurableFleet.recover(path)
    assert report.rotted_records >= 1 or report.quarantined
    flight = observability.last_flight_record()
    assert flight['trigger'] == 'recovery'
    detail = flight['detail']
    assert detail['rotted_records'] == report.rotted_records
    if report.quarantined:
        assert any(e['durable_id'] in report.quarantined
                   for e in detail['errors'])
        assert all(e['error'] for e in detail['errors'])
    # rot events in the ring name the damaged byte offset
    rots = [e for e in flight['events'] if e['kind'] == 'journal_rot']
    assert rots, flight['events']
    del victim_id
    mgr2.close()


def test_sync_receive_decode_quarantine_dumps():
    n = 3
    fleet = DocFleet(doc_capacity=2 * n, key_capacity=16)
    src = init_docs(n, fleet)
    src, _ = fleet_backend.apply_changes_docs(
        src, [[_change(f'{i:02x}' * 16, 'k', i)] for i in range(n)],
        mirror=False)
    dst = init_docs(n, fleet)
    sa = [init_sync_state() for _ in range(n)]
    sb = [init_sync_state() for _ in range(n)]
    sa, msgs = generate_sync_messages_docs(src, sa)
    msgs = list(msgs)
    msgs[0] = b'\xff\x00garbage'
    dst, sb, _p, errors = receive_sync_messages_docs(
        dst, sb, msgs, mirror=False, on_error='quarantine')
    assert errors[0] is not None and errors[0].stage == 'decode'
    report = observability.last_flight_record()
    assert report['trigger'] == 'quarantine'
    assert report['detail']['errors'][0]['error'] == 'MalformedSyncMessage'


def test_doc_materialization_attributed():
    """Satellite: the parked-history revive (~700µs/doc; ROADMAP native
    change-list extraction) must show up as a span, accumulated
    metrics.seconds, and a doc_materialize_s histogram sample."""
    fleet = DocFleet(doc_capacity=4, key_capacity=8)
    handles = init_docs(2, fleet)
    handles, _ = fleet_backend.apply_changes_docs(
        handles, [[_change(f'{i:02x}' * 16, 'k', i)] for i in range(2)],
        mirror=False)
    assert fleet_backend.park_docs(handles) == 2
    assert handles[0]['state']._impl._doc_pending is not None
    observability.enable()
    try:
        handles[0]['state']._impl.changes      # property get revives
    finally:
        observability.disable()
    assert fleet.metrics.doc_materializations >= 1
    assert fleet.metrics.seconds['doc_materializations'] > 0
    spans = [s for s in observability.iter_spans()
             if s['name'] == 'doc_materialize']
    assert spans and spans[-1]['attrs']['chunk_bytes'] > 0
    hist = observability.histogram_snapshot()['doc_materialize_s']
    assert hist['count'] == 1 and hist['p50'] > 0


def test_sync_round_dispatches_flat_across_fleet_sizes():
    """Tier-1 regression for the round-6 O(1)-dispatch sync contract,
    measured through a FULL round (generate -> receive -> reply ->
    receive, fleet backends on both ends): 4x the docs must cost exactly
    the same device dispatches per round. Prep for the on-chip BENCH_r06
    re-capture (ROADMAP) — on the chip this is the difference between a
    flat tunnel cost and one that grows with fleet size."""
    per_round = {}
    for n in (6, 24):
        fleet = DocFleet(doc_capacity=2 * n, key_capacity=16)
        src = init_docs(n, fleet)
        src, _ = fleet_backend.apply_changes_docs(
            src, [[_change(f'{i:02x}' * 16, 'k', i)] for i in range(n)],
            mirror=False)
        dst = init_docs(n, fleet)
        sa = [init_sync_state() for _ in range(n)]
        sb = [init_sync_state() for _ in range(n)]
        rounds = []
        for _round in range(3):
            before = observability.dispatch_counts([fleet])
            sa, msgs = generate_sync_messages_docs(src, sa)
            dst, sb, _p = receive_sync_messages_docs(dst, sb, msgs,
                                                     mirror=False)
            sb, replies = generate_sync_messages_docs(dst, sb)
            src, sa, _p = receive_sync_messages_docs(src, sa, replies,
                                                     mirror=False)
            after = observability.dispatch_counts([fleet])
            rounds.append(after['total'] - before['total'])
        # the content must actually have moved (the count means something)
        assert fleet_backend.get_heads(dst[0]) == \
            fleet_backend.get_heads(src[0])
        per_round[n] = tuple(rounds)
    assert per_round[6] == per_round[24], per_round
