"""Contract-linter tests (ISSUE-19): per-rule positive/negative
fixtures (violation detected at the right file:line; idiomatic code
passes), the suppression-baseline round-trip, the real-tree tier-1
gate, and pinning tests for the violations the linter surfaced and
this round fixed."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from automerge_tpu import analysis
from automerge_tpu.analysis import scopes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(src, path, rule_ids=None):
    return analysis.lint_source(textwrap.dedent(src), path,
                                analysis.get_rules(rule_ids))


def violations(src, path, rule_ids=None):
    return [f for f in lint(src, path, rule_ids) if not f.suppressed]


# ---------------------------------------------------------------------------
# rule: typed-errors
# ---------------------------------------------------------------------------

class TestTypedErrors:
    def test_decode_surface_bare_raise_detected(self):
        src = '''\
        from automerge_tpu.errors import MalformedChange


        def decode_frame(buf):
            if not buf:
                raise ValueError('empty frame')
            return buf
        '''
        found = violations(src, 'automerge_tpu/backend/wire.py',
                           ['typed-errors'])
        assert len(found) == 1
        assert found[0].line == 6
        assert found[0].path == 'automerge_tpu/backend/wire.py'
        assert 'decode_frame' in found[0].message

    def test_guarded_boundary_and_typed_raise_pass(self):
        src = '''\
        from automerge_tpu.errors import MalformedChange, as_wire_error


        def decode_frame(buf):
            try:
                if not buf:
                    raise ValueError('empty frame')
                if buf[0] != 7:
                    raise MalformedChange('bad magic')
                return buf
            except Exception as exc:
                raise as_wire_error(exc, MalformedChange, 'decode_frame')
        '''
        assert violations(src, 'automerge_tpu/backend/wire.py',
                          ['typed-errors']) == []

    def test_funnel_modules_exempt(self):
        src = '''\
        def decode_column(buf):
            raise ValueError('internal funnel style')
        '''
        assert violations(src, 'automerge_tpu/columnar.py',
                          ['typed-errors']) == []

    def test_except_pass_detected(self):
        src = '''\
        def f():
            try:
                g()
            except Exception:
                pass
        '''
        found = violations(src, 'tools/anything.py', ['typed-errors'])
        assert len(found) == 1 and found[0].line == 4

    def test_narrowed_except_pass_ok(self):
        src = '''\
        def f():
            try:
                g()
            except (OSError, KeyError):
                pass
        '''
        assert violations(src, 'tools/anything.py', ['typed-errors']) == []

    def test_message_string_match_detected(self):
        src = '''\
        def f():
            try:
                g()
            except ValueError as exc:
                if 'session closed' in str(exc):
                    return None
                raise
        '''
        found = violations(src, 'automerge_tpu/shard/router.py',
                           ['typed-errors'])
        assert len(found) == 1 and found[0].line == 5
        assert 'typed class' in found[0].message

    def test_isinstance_dispatch_ok(self):
        src = '''\
        from automerge_tpu.errors import SessionClosed


        def f():
            try:
                g()
            except ValueError as exc:
                if isinstance(exc, SessionClosed):
                    return None
                raise
        '''
        assert violations(src, 'automerge_tpu/shard/router.py',
                          ['typed-errors']) == []


# ---------------------------------------------------------------------------
# rule: counter-discipline
# ---------------------------------------------------------------------------

class TestCounterDiscipline:
    def test_raw_dict_stats_detected(self):
        src = '''\
        _stats = {'decoded': 0, 'rejected': 0}
        '''
        found = violations(src, 'automerge_tpu/fleet/newmod.py',
                           ['counter-discipline'])
        assert len(found) == 1 and found[0].line == 1
        assert 'Counters' in found[0].message

    def test_reserved_source_name_detected(self):
        src = '''\
        from automerge_tpu.observability import register_health_source

        register_health_source('fleet3', lambda: 0)
        '''
        found = violations(src, 'automerge_tpu/fleet/newmod.py',
                           ['counter-discipline'])
        assert len(found) == 1 and found[0].line == 3
        assert 'reserved' in found[0].message

    def test_counters_and_local_dicts_pass(self):
        src = '''\
        from automerge_tpu.observability.metrics import Counters

        _stats = Counters({'decoded': 0})


        def summarize():
            link_stats = {}
            link_stats['x'] = 1
            return link_stats
        '''
        assert violations(src, 'automerge_tpu/fleet/newmod.py',
                          ['counter-discipline']) == []


# ---------------------------------------------------------------------------
# rule: kernel-ledger
# ---------------------------------------------------------------------------

class TestKernelLedger:
    def test_unwrapped_jits_detected(self):
        src = '''\
        import functools

        import jax


        @jax.jit
        def f(x):
            return x


        g = jax.jit(lambda x: x)


        @functools.partial(jax.jit, static_argnums=(0,))
        def h(x):
            return x
        '''
        found = violations(src, 'automerge_tpu/fleet/newkern.py',
                           ['kernel-ledger'])
        assert [f.line for f in found] == [6, 11, 14]

    def test_instrumented_jit_passes(self):
        src = '''\
        import jax

        from automerge_tpu.observability.perf import instrument_kernel


        def _impl(x):
            return x


        k = instrument_kernel('k', jax.jit(_impl, donate_argnums=(0,)))
        '''
        assert violations(src, 'automerge_tpu/fleet/newkern.py',
                          ['kernel-ledger']) == []

    def test_per_doc_jnp_loop_detected(self):
        src = '''\
        import jax.numpy as jnp


        def pump(docs):
            out = []
            for d in docs:
                out.append(jnp.asarray(d))
            return out
        '''
        found = violations(src, 'automerge_tpu/service/pump.py',
                           ['kernel-ledger'])
        assert len(found) == 1 and found[0].line == 7
        assert 'per-doc loop' in found[0].message

    def test_per_class_pool_loop_passes(self):
        src = '''\
        import jax.numpy as jnp


        def grow(pools):
            for cls, st in pools.items():
                pools[cls] = jnp.zeros(st)
        '''
        assert violations(src, 'automerge_tpu/fleet/loader2.py',
                          ['kernel-ledger']) == []


# ---------------------------------------------------------------------------
# rule: determinism
# ---------------------------------------------------------------------------

class TestDeterminism:
    def test_wall_clock_and_unseeded_random_detected(self):
        src = '''\
        import random
        import time


        def tick():
            return time.time()


        def jitter():
            return random.random()
        '''
        found = violations(src, 'automerge_tpu/fleet/clock.py',
                           ['determinism'])
        assert [f.line for f in found] == [6, 10]

    def test_seeded_rng_and_out_of_scope_clock_pass(self):
        src = '''\
        import random
        import time


        def jitter(seed):
            return random.Random(seed).random()


        def stamp():
            return time.time()
        '''
        # seeded instance passes even in scope; the wall clock still
        # flags there, and nothing flags OUT of the deterministic scope
        # (observability legitimately timestamps real time)
        in_scope = violations(src, 'automerge_tpu/fleet/clock.py',
                              ['determinism'])
        assert [f.line for f in in_scope] == [10]
        assert violations(src, 'automerge_tpu/observability/x.py',
                          ['determinism']) == []

    def test_unsorted_encode_iteration_detected(self):
        src = '''\
        def encode_row(d, out):
            for k, v in d.items():
                out.append(k)
        '''
        found = violations(src, 'automerge_tpu/backend/enc.py',
                           ['determinism'])
        assert len(found) == 1 and found[0].line == 2
        assert 'sorted' in found[0].message

    def test_sorted_encode_iteration_passes(self):
        src = '''\
        def encode_row(d, out):
            for k, v in sorted(d.items()):
                out.append(k)
            all_ids = set()
            for inner in d.values():
                all_ids |= inner
        '''
        assert violations(src, 'automerge_tpu/backend/enc.py',
                          ['determinism']) == []


# ---------------------------------------------------------------------------
# rule: lock-discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    def test_unlocked_module_state_mutation_detected(self):
        src = '''\
        _tbl = {}


        def put(k, v):
            _tbl[k] = v
        '''
        found = violations(src, 'automerge_tpu/observability/export.py',
                           ['lock-discipline'])
        assert len(found) == 1 and found[0].line == 5
        assert 'race candidate' in found[0].message

    def test_locked_mutation_and_counters_pass(self):
        src = '''\
        import threading

        from automerge_tpu.observability.metrics import Counters

        _tbl = {}
        _LOCK = threading.Lock()
        _stats = Counters({'hits': 0})


        def put(k, v):
            with _LOCK:
                _tbl[k] = v
            _stats.inc('hits')
        '''
        assert violations(src, 'automerge_tpu/observability/export.py',
                          ['lock-discipline']) == []

    def test_rule_scoped_to_threaded_modules(self):
        src = '''\
        _tbl = {}


        def put(k, v):
            _tbl[k] = v
        '''
        assert violations(src, 'automerge_tpu/frontend/views2.py',
                          ['lock-discipline']) == []


# ---------------------------------------------------------------------------
# suppression + baseline round-trip
# ---------------------------------------------------------------------------

VIOLATING = '''_tbl = {}


def put(k, v):
    # archlint: ok[lock-discipline] fixture: registration is import-time only
    _tbl[k] = v
'''


class TestSuppressionBaseline:
    def _write(self, root, body):
        mod = os.path.join(root, 'automerge_tpu', 'observability')
        os.makedirs(mod, exist_ok=True)
        path = os.path.join(mod, 'export.py')
        with open(path, 'w') as fh:
            fh.write(body)
        return path

    def test_round_trip(self, tmp_path):
        root = str(tmp_path)
        self._write(root, VIOLATING)
        bl = os.path.join(root, 'baseline.json')
        rules = analysis.get_rules(['lock-discipline'])

        # suppressed inline, but NOT yet in the baseline -> check fails
        findings, _, _ = analysis.lint_paths(['automerge_tpu'], rules,
                                             root=root)
        assert len(findings) == 1 and findings[0].suppressed
        checked = analysis.check_findings(findings,
                                          analysis.load_baseline(bl))
        assert not checked['violations'] and len(checked['unlisted']) == 1

        # record it -> check passes and the justification is on record
        entries = analysis.write_baseline(bl, findings)
        assert entries[0]['justification'].startswith('fixture:')
        checked = analysis.check_findings(findings,
                                          analysis.load_baseline(bl))
        assert not (checked['violations'] or checked['unlisted'] or
                    checked['stale'])

        # remove the inline comment -> violation AND stale entry
        self._write(root, VIOLATING.replace(
            '    # archlint: ok[lock-discipline] fixture: registration '
            'is import-time only\n', ''))
        findings, _, _ = analysis.lint_paths(['automerge_tpu'], rules,
                                             root=root)
        checked = analysis.check_findings(findings,
                                          analysis.load_baseline(bl))
        assert len(checked['violations']) == 1
        assert len(checked['stale']) == 1

    def test_unjustified_marker_does_not_suppress(self, tmp_path):
        root = str(tmp_path)
        self._write(root, VIOLATING.replace(
            'fixture: registration is import-time only', ''))
        findings, _, _ = analysis.lint_paths(
            ['automerge_tpu'], analysis.get_rules(['lock-discipline']),
            root=root)
        assert len(findings) == 1 and not findings[0].suppressed
        assert 'no justification' in findings[0].message

    def test_wrong_rule_marker_does_not_suppress(self, tmp_path):
        root = str(tmp_path)
        self._write(root, VIOLATING.replace('ok[lock-discipline]',
                                            'ok[determinism]'))
        findings, _, _ = analysis.lint_paths(
            ['automerge_tpu'], analysis.get_rules(['lock-discipline']),
            root=root)
        assert len(findings) == 1 and not findings[0].suppressed


# ---------------------------------------------------------------------------
# the tier-1 gate: the REAL tree is clean under the checked-in baseline
# ---------------------------------------------------------------------------

def test_real_tree_is_clean_under_checked_in_baseline():
    proc = subprocess.run(
        [sys.executable, os.path.join('tools', 'archlint.py'),
         '--check', '--json', '-'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # with --json -, stdout is the pure payload (the human report goes
    # to stderr so the output pipes into `obs_report --archlint -`)
    payload = json.loads(proc.stdout)
    assert payload['violations'] == 0
    assert payload['unlisted'] == 0 and payload['stale'] == []
    assert len(payload['rules']) == 5
    # acceptance: the suppression baseline stays small and justified
    assert payload['baseline_size'] <= 10
    assert all(f['justification'] for f in payload['findings']
               if f['suppressed'])


def test_scope_tables_name_real_files():
    # a scope table pointing at renamed/deleted modules checks nothing
    for rel in sorted(scopes.FUNNEL_MODULES | scopes.THREADED_MODULES):
        assert os.path.exists(os.path.join(REPO, rel)), rel


# ---------------------------------------------------------------------------
# pinning tests for the real violations this round fixed
# ---------------------------------------------------------------------------

class TestFixedViolations:
    def test_zero_width_bloom_header_raises_typed(self):
        from automerge_tpu.backend.sync import read_filter_header
        from automerge_tpu.encoding import Decoder, Encoder
        from automerge_tpu.errors import MalformedSyncMessage
        enc = Encoder()
        enc.append_uint32(4)     # num_entries > 0
        enc.append_uint32(0)     # bits_per_entry == 0 -> zero-width
        enc.append_uint32(0)
        with pytest.raises(MalformedSyncMessage):
            read_filter_header(Decoder(enc.buffer))

    def test_native_inflate_garbage_raises_typed(self):
        from automerge_tpu import native
        from automerge_tpu.errors import MalformedChange
        if not native.available():
            pytest.skip('native toolchain unavailable')
        with pytest.raises(MalformedChange):
            native.inflate_raw(b'\xffgarbage-not-deflate\xff',
                               max_size=1 << 16)

    def test_fixed_jit_entry_points_are_in_the_ledger(self):
        from automerge_tpu.fleet import pallas_merge, registers, sharding
        from automerge_tpu.observability import perf
        assert registers.visible_registers.kernel_kind == \
            'visible_registers'
        assert pallas_merge.pallas_apply_op_batch.kernel_kind == \
            'pallas_apply_op_batch'
        mesh = sharding.fleet_mesh()
        for factory, kind in (
                (sharding.sharded_seq_apply, 'sharded_seq_apply'),
                (sharding.sharded_long_seq_apply,
                 'sharded_long_seq_apply'),
                (sharding.sharded_long_seq_materialize,
                 'sharded_long_seq_materialize'),
                (sharding.sharded_apply, 'sharded_apply')):
            assert factory(mesh).kernel_kind == kind
        # wrap-time registration makes them visible to the ledger even
        # before the first dispatch (kernel_snapshot shows them once
        # dispatched; kernel_kinds lists every wired kind)
        kinds = set(perf.kernel_kinds())
        assert {'visible_registers', 'pallas_apply_op_batch',
                'sharded_seq_apply', 'sharded_apply'} <= kinds

    def test_register_source_registration_is_locked(self):
        # the round-13 registries now take the counters lock; pin the
        # behavioral contract (register + read back) rather than the
        # lock itself — archlint pins the lock statically
        from automerge_tpu.observability import metrics
        metrics.register_health_source('archlint_pin', lambda: 41)
        try:
            assert metrics.health_counts()['archlint_pin'] == 41
        finally:
            with metrics._COUNTERS_LOCK:
                metrics._health_sources.pop('archlint_pin', None)
