"""Worker for test_multihost: one controller process of a 2-process CPU
mesh (2 local virtual devices each -> 4 global shards). Each shard edits
its own key in a fleet-resident document, then every pair converges with
the payload matrix riding the mesh collective (ICI within a host, DCN
across — jax.distributed + Gloo here stands in for the cross-host wire).
Run: python multihost_worker.py <pid> <nproc> <port>."""

import json
import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '') + \
    ' --xla_force_host_platform_device_count=2'

import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
jax.distributed.initialize(coordinator_address=f'127.0.0.1:{port}',
                           num_processes=nproc, process_id=pid)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import automerge_tpu as A
from automerge_tpu import frontend as F
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend
from automerge_tpu.fleet.exchange import (local_shard_ids,
                                          drive_pairwise_sync_multihost)
from jax.sharding import Mesh

mesh = Mesh(np.asarray(jax.devices()), ('hosts',))
mine = local_shard_ids(mesh, 'hosts')
n = mesh.shape['hosts']

fb = FleetBackend(DocFleet(doc_capacity=8, key_capacity=32))
local_docs = {}
prev = A.Backend()
A.set_default_backend(fb)
try:
    for s in mine:
        actor = f'{s:02x}' * 16
        doc = A.change(A.init(actor), {'time': 0},
                       lambda r, s=s: r.update({f'k{s}': s}))
        local_docs[s] = F.get_backend_state(doc, 'multihost')
finally:
    A.set_default_backend(prev)

drive_pairwise_sync_multihost(mesh, 'hosts', local_docs, fleet_backend)

reads = fleet_backend.materialize_docs([local_docs[s] for s in mine])
heads = [fleet_backend.get_heads(local_docs[s]) for s in mine]
print('RESULT ' + json.dumps({
    'process': pid, 'shards': mine,
    'reads': reads, 'heads': heads,
}), flush=True)
