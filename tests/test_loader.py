"""Bulk native document load (fleet/loader.py): saved containers straight to
device state with no replay and no change-log materialization.

Differential harness (the wasm.js cross-implementation pattern): documents
built through the public API on the host backend, saved, bulk-loaded into a
fleet, then compared read-for-read and patch-for-patch against the host
engine on the same bytes."""

import numpy as np
import pytest

import automerge_tpu as A
from automerge_tpu import backend as host_backend
from automerge_tpu import native
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet
from automerge_tpu.fleet.loader import load_docs

A1, A2, A3 = '01' * 8, '89' * 8, 'fe' * 8

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


def _corpus():
    """Documents covering the loadable shapes: values of every datatype,
    counters with incs, nested maps/tables, text/list editing, concurrent
    merges with conflicts, deletes, multi-actor histories."""
    docs = []
    d = A.from_({'x': 1, 's': 'hello', 'c': A.Counter(10), 'f': 2.5,
                 'ok': True, 'n': None, 'u': A.Uint(3),
                 'when': A.Int(1589032171000)}, A1)
    d = A.change(d, lambda r: r['c'].increment(7))
    docs.append(d)

    d = A.from_({'cfg': {'a': {'deep': 'yes'}, 'b': 2}}, A1)
    d = A.change(d, lambda r: r['cfg'].__setitem__('b', 9))
    docs.append(d)

    d = A.from_({'t': A.Text('hello world')}, A1)
    d = A.change(d, lambda r: r['t'].delete_at(0))
    d = A.change(d, lambda r: r['t'].insert_at(0, 'H'))
    docs.append(d)

    d = A.from_({'l': [1, 2, 3, 'four']}, A1)
    d = A.change(d, lambda r: r['l'].__setitem__(1, 20))
    d = A.change(d, lambda r: r['l'].delete_at(0))
    docs.append(d)

    # concurrent conflicting writes (multi-value register shape)
    b1 = A.from_({'k': 'one', 'shared': 0}, A1)
    b2 = A.merge(A.init(A2), b1)
    b1 = A.change(b1, lambda r: r.__setitem__('k', 'from-a'))
    b2 = A.change(b2, lambda r: r.__setitem__('k', 'from-b'))
    docs.append(A.merge(b1, b2))

    # concurrent text editing (3 actors)
    t1 = A.from_({'t': A.Text('base')}, A1)
    t2 = A.merge(A.init(A2), t1)
    t3 = A.merge(A.init(A3), t1)
    t1 = A.change(t1, lambda r: r['t'].insert_at(0, 'X'))
    t2 = A.change(t2, lambda r: r['t'].set(1, 'A'))
    t3 = A.change(t3, lambda r: r['t'].delete_at(2))
    docs.append(A.merge(A.merge(t1, t2), t3))

    # deleted keys + re-set
    d = A.from_({'gone': 1, 'kept': 2}, A1)
    d = A.change(d, lambda r: r.__delitem__('gone'))
    d = A.change(d, lambda r: r.__setitem__('kept', 3))
    docs.append(d)

    # empty document
    docs.append(A.init(A1))

    # table rows
    d = A.from_({'tbl': A.Table()}, A1)

    def add_row(r):
        r['tbl'].add({'name': 'wren', 'n': 1})
    d = A.change(d, add_row)
    docs.append(d)
    return docs


def _host_view(buf):
    hb = host_backend.load(buf)
    patch = host_backend.get_patch(hb)
    return patch


class TestBulkLoad:
    @pytest.mark.parametrize('exact', [False, True])
    def test_differential_reads_and_patches(self, exact):
        docs = _corpus()
        bufs = [A.save(d) for d in docs]
        fleet = DocFleet(doc_capacity=4, key_capacity=8, exact_device=exact)
        handles = load_docs(bufs, fleet)
        assert fleet.metrics.docs_bulk_loaded == len(bufs)
        # reads match the ordinary (host-OpSet-replay) load path on the
        # same bytes, with NO change-log materialization on the bulk side
        oracle_fleet = DocFleet(doc_capacity=4, key_capacity=8)
        oracle = [fleet_backend.load(bytes(b), oracle_fleet) for b in bufs]
        expect = fleet_backend.materialize_docs(oracle)
        mats = fleet_backend.materialize_docs(handles)
        for i, (m, e) in enumerate(zip(mats, expect)):
            assert m == e, f'doc {i} mismatch'
        assert fleet.metrics.doc_materializations == 0
        # patches match the host backend exactly; in exact mode nested and
        # sequence docs are ALSO device-served (no chunk materialization —
        # only counter-in-list style inexact rows fall back)
        for i, (h, buf) in enumerate(zip(handles, bufs)):
            assert fleet_backend.get_patch(h) == _host_view(buf), \
                f'doc {i} patch mismatch'
        if exact:
            assert fleet.metrics.mirror_rebuilds == 0
            assert fleet.metrics.doc_materializations == 0

    def test_save_verbatim_until_edit(self):
        docs = _corpus()
        bufs = [A.save(d) for d in docs]
        fleet = DocFleet(doc_capacity=4, key_capacity=8, exact_device=True)
        handles = load_docs(bufs, fleet)
        for h, buf in zip(handles, bufs):
            assert bytes(fleet_backend.save(h)) == bytes(buf)
        assert fleet.metrics.doc_materializations == 0

    def test_edit_after_load(self):
        """Further changes apply on top of bulk-loaded state and reads stay
        correct; save after edit re-encodes canonically (not verbatim)."""
        d = A.from_({'x': 1, 'c': A.Counter(5)}, A1)
        buf = A.save(d)
        fleet = DocFleet(doc_capacity=2, key_capacity=8, exact_device=True)
        handle = load_docs([buf], fleet)[0]
        # build the same follow-up change with the host frontend
        d2 = A.load(buf)
        d2 = A.change(d2, lambda r: (r.__setitem__('x', 2),
                                     r['c'].increment(3)))
        new_change = A.get_last_local_change(d2)
        handle, _patch = fleet_backend.apply_changes(handle, [new_change])
        mat = fleet_backend.materialize_docs([handle])[0]
        assert mat == {'x': 2, 'c': 8}
        # canonical save after edit equals the host engine's canonical save
        hb = host_backend.load(buf)
        hb, _ = host_backend.apply_changes(hb, [new_change])
        assert bytes(fleet_backend.save(handle)) == \
            bytes(host_backend.save(hb))

    def test_sync_after_load_materializes_lazily(self):
        """Sync needs real change history: the parked chunk materializes on
        demand and the sync round converges against a host peer."""
        d = A.from_({'x': 1, 't': A.Text('ab')}, A1)
        buf = A.save(d)
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        handle = load_docs([buf], fleet)[0]
        peer = host_backend.init()
        s1 = A.init_sync_state()
        s2 = A.init_sync_state()
        for _ in range(10):
            s1, msg = fleet_backend.generate_sync_message(handle, s1)
            if msg is not None:
                peer, s2, _ = host_backend.receive_sync_message(peer, s2, msg)
            s2, msg2 = host_backend.generate_sync_message(peer, s2)
            if msg2 is not None:
                handle, s1, _ = fleet_backend.receive_sync_message(
                    handle, s1, msg2)
            if msg is None and msg2 is None:
                break
        assert host_backend.get_heads(peer) == fleet_backend.get_heads(handle)
        assert fleet.metrics.doc_materializations == 1

    def test_counter_in_list_falls_back_to_mirror(self):
        """Counters inside sequences are host-mirror-only: the loaded row
        flags inexact and reads still come out right (via materialization)."""
        d = A.from_({'l': [A.Counter(10)]}, A1)
        d = A.change(d, lambda r: r['l'][0].increment(5))
        buf = A.save(d)
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        handle = load_docs([buf], fleet)[0]
        assert fleet_backend.materialize_docs([handle]) == [{'l': [15]}]

    def test_fallback_paths_still_load(self):
        """Buffers the native path can't take (concatenated chunks, raw
        change chunks, objects inside sequences) load via the ordinary
        path and produce identical reads."""
        d = A.from_({'l': [{'obj': 'in-list'}]}, A1)   # object inside a seq
        buf_nested = A.save(d)
        d2 = A.from_({'x': 1}, A1)
        raw_changes = b''.join(A.get_all_changes(d2))  # change chunks
        fleet = DocFleet(doc_capacity=4, key_capacity=8)
        handles = load_docs([buf_nested, raw_changes], fleet)
        mats = fleet_backend.materialize_docs(handles)
        assert mats[0] == {'l': [{'obj': 'in-list'}]}
        assert mats[1] == {'x': 1}

    def test_heads_clock_graph_match_host(self):
        docs = _corpus()
        bufs = [A.save(d) for d in docs]
        fleet = DocFleet(doc_capacity=4, key_capacity=8)
        handles = load_docs(bufs, fleet)
        for h, buf in zip(handles, bufs):
            hb = host_backend.load(buf)
            assert fleet_backend.get_heads(h) == host_backend.get_heads(hb)
            assert h['state'].clock == hb['state'].clock
            assert h['state'].max_op == hb['state'].max_op
            # hash-graph queries resolve lazily and agree with the host
            assert sorted(x.hex() if isinstance(x, bytes) else x
                          for x in fleet_backend.get_missing_deps(h)) == \
                sorted(x.hex() if isinstance(x, bytes) else x
                       for x in host_backend.get_missing_deps(hb))
            assert [bytes(c) for c in fleet_backend.get_all_changes(h)] == \
                [bytes(c) for c in host_backend.get_all_changes(hb)]

    def test_empty_sequence_stays_device_resident(self):
        """An empty Text/list gets its device row at load (the ordinary
        path allocates at make time): reads must not fall back to the
        mirror via an unresolved link."""
        d = A.from_({'t': A.Text(), 'l': [], 'x': 1}, A1)
        buf = A.save(d)
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        handle = load_docs([buf], fleet)[0]
        assert fleet_backend.materialize_docs([handle]) == \
            [{'t': '', 'l': [], 'x': 1}]
        assert fleet.metrics.doc_materializations == 0

    def test_objects_inside_lists_bulk_load(self):
        """Documents holding rows-in-lists (maps, nested lists, Text as
        list elements) take the native bulk path, not the per-doc
        fallback: the make element rows install as links, child objects
        install like any registered object, and the loaded docs
        materialize from device state and save back verbatim."""
        d = A.init(A1)
        d = A.change(d, lambda r: r.update(
            {'todo': [{'t': 'wash', 'n': 1}, [1, 2], A.Text('hi')],
             'k': 9}))
        d = A.change(d, lambda r: r['todo'][0].update({'n': 2}))
        d = A.change(d, lambda r: r['todo'][1].append(3))
        d = A.change(d, lambda r: r['todo'].delete_at(2))
        buf = bytes(A.save(d))
        want = {'todo': [{'t': 'wash', 'n': 2}, [1, 2, 3]], 'k': 9}
        for exact in (False, True):
            fleet = DocFleet(doc_capacity=4, key_capacity=16,
                             exact_device=exact)
            handles = load_docs([buf, buf], fleet)
            assert fleet.metrics.docs_bulk_loaded == 2, exact
            assert fleet_backend.materialize_docs(handles) == [want, want]
            assert bytes(fleet_backend.save(handles[0])) == buf
            assert fleet.metrics.doc_materializations == 0

    def test_get_patch_stays_lazy_in_exact_mode(self):
        """get_patch on a flat bulk-loaded doc serves from the device
        registers without materializing the parked chunk."""
        d = A.from_({'x': 1, 'c': A.Counter(2)}, A1)
        d = A.change(d, lambda r: r['c'].increment(3))
        buf = A.save(d)
        fleet = DocFleet(doc_capacity=2, key_capacity=8, exact_device=True)
        handle = load_docs([buf], fleet)[0]
        patch = fleet_backend.get_patch(handle)
        assert patch == _host_view(buf)
        assert fleet.metrics.doc_materializations == 0
        assert fleet.metrics.mirror_rebuilds == 0

    def test_overflow_doc_does_not_corrupt_batch_peers(self):
        """A fallback-bound doc whose op counters exceed the packing window
        must not alias into a good doc's keyspace (the inc/succ lookup
        tables take good-doc rows only)."""
        from automerge_tpu.columnar import encode_change, decode_change_meta
        from automerge_tpu.backend.op_set import OpSet
        BIG = 1 << 24
        # doc 0: huge op counters (startOp pushed past 2^23) -> fallback
        ops_a = OpSet()
        c1 = encode_change({'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0,
                            'message': '', 'deps': [], 'ops': [
                                {'action': 'set', 'obj': '_root', 'key': 'k',
                                 'value': 1, 'datatype': 'counter',
                                 'pred': []}]})
        h1 = decode_change_meta(c1, True)['hash']
        c2 = encode_change({'actor': A1, 'seq': 2, 'startOp': BIG + 5,
                            'time': 0, 'message': '', 'deps': [h1], 'ops': [
                                {'action': 'inc', 'obj': '_root', 'key': 'k',
                                 'value': 99, 'pred': [f'1@{A1}']}]})
        ops_a.apply_changes([c1, c2])
        buf_big = ops_a.save()
        # doc 1 (same actor, so packed keys can alias): a deleted key whose
        # del opId counter collides with doc 0's inc counter under the
        # doc-scoped key packing
        ops_b = OpSet()
        d1 = encode_change({'actor': A1, 'seq': 1, 'startOp': 1, 'time': 0,
                            'message': '', 'deps': [], 'ops': [
                                {'action': 'set', 'obj': '_root', 'key': 'x',
                                 'value': 7, 'datatype': 'int', 'pred': []}]})
        g1 = decode_change_meta(d1, True)['hash']
        d2 = encode_change({'actor': A1, 'seq': 2, 'startOp': 5, 'time': 0,
                            'message': '', 'deps': [g1], 'ops': [
                                {'action': 'del', 'obj': '_root', 'key': 'x',
                                 'pred': [f'1@{A1}']}]})
        ops_b.apply_changes([d1, d2])
        buf_del = ops_b.save()
        fleet = DocFleet(doc_capacity=4, key_capacity=8)
        handles = load_docs([buf_big, buf_del], fleet)
        mats = fleet_backend.materialize_docs(handles)
        assert mats[0] == {'k': 100}      # fallback path, still correct
        assert mats[1] == {}              # deleted key must stay deleted
        # prove the repro shape: without the good-doc filter the del op's
        # succ key aliases doc 0's inc rid and the key resurrects

    def test_fuzz_differential(self):
        """Randomized multi-actor editing histories: save on host, bulk
        load, compare whole-doc reads in both device modes."""
        import random
        rng = random.Random(7)
        alphabet = 'abcdefghij'
        bufs, expects = [], []
        for trial in range(6):
            actors = [A1, A2]
            base = A.from_({'t': A.Text('seed'), 'm': {}, 'k': 0}, actors[0])
            replicas = [base, A.merge(A.init(actors[1]), base)]
            for step in range(12):
                i = rng.randrange(2)

                def edit(r, rng=rng):
                    roll = rng.random()
                    t = r['t']
                    if roll < 0.3 and len(t):
                        t.delete_at(rng.randrange(len(t)))
                    elif roll < 0.5:
                        t.insert_at(rng.randrange(len(t) + 1),
                                    rng.choice(alphabet))
                    elif roll < 0.7 and len(t):
                        t.set(rng.randrange(len(t)),
                              rng.choice(alphabet).upper())
                    elif roll < 0.85:
                        r['m'][rng.choice(alphabet)] = rng.randrange(100)
                    else:
                        r['k'] = rng.randrange(1000)
                replicas[i] = A.change(replicas[i], edit)
                if rng.random() < 0.3:
                    a, b = rng.sample(range(2), 2)
                    replicas[a] = A.merge(replicas[a], replicas[b])
            final = A.merge(A.clone(replicas[0]), replicas[1])
            bufs.append(A.save(final))
            expects.append(dict(final))
        for exact in (False, True):
            fleet = DocFleet(doc_capacity=8, key_capacity=16,
                             exact_device=exact)
            handles = load_docs(bufs, fleet)
            assert fleet.metrics.docs_bulk_loaded == len(bufs)
            mats = fleet_backend.materialize_docs(handles)
            for i, (m, e) in enumerate(zip(mats, expects)):
                assert m == e, f'trial {i} exact={exact}'
            assert fleet.metrics.doc_materializations == 0
