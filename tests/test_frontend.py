"""Frontend-only conformance tests: the backend is mocked by construction —
change requests are inspected directly and patches injected by hand (ported
semantics of reference test/frontend_test.js, incl. the request-queue
async-mode reconciliation at frontend/index.js:288-327)."""

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu import backend as Backend
from automerge_tpu.columnar import decode_change
from automerge_tpu.common import uuid
from automerge_tpu.frontend import Counter, Text


def get_requests(doc):
    return [{'actor': r['actor'], 'seq': r['seq']}
            for r in doc._state['requests']]


class TestInitializing:
    def test_empty_by_default(self):
        doc = Frontend.init()
        assert Frontend.get_object_id(doc) == '_root'
        assert dict(doc) == {}

    def test_defer_actor_id(self):
        doc0 = Frontend.init({'deferActorId': True})
        assert Frontend.get_actor_id(doc0) is None
        doc1 = Frontend.set_actor_id(doc0, uuid())
        doc2, _req = Frontend.change(doc1, lambda d: d.update({'wrens': 3}))
        assert dict(doc2) == {'wrens': 3}

    def test_change_requires_actor_id(self):
        doc = Frontend.init({'deferActorId': True})
        with pytest.raises(ValueError):
            Frontend.change(doc, lambda d: d.update({'wrens': 3}))

    def test_from_initial_state(self):
        doc = Frontend.from_({'birds': {'wrens': 3}})
        assert doc == {'birds': {'wrens': 3}}

    def test_from_empty_object(self):
        doc = Frontend.from_({})
        assert dict(doc) == {}


class TestPerformingChanges:
    def test_unmodified_doc_if_no_change(self):
        doc0 = Frontend.init()
        doc1, req = Frontend.change(doc0, lambda d: None)
        assert doc1 is doc0
        assert req is None

    def test_set_root_property_request(self):
        actor = uuid()
        doc, change = Frontend.change(Frontend.init(actor),
                                      lambda d: d.update({'bird': 'magpie'}))
        assert dict(doc) == {'bird': 'magpie'}
        assert change == {
            'actor': actor, 'seq': 1, 'startOp': 1, 'deps': [],
            'time': change['time'], 'message': '',
            'ops': [{'obj': '_root', 'action': 'set', 'key': 'bird',
                     'insert': False, 'value': 'magpie', 'pred': []}]}
        assert get_requests(doc) == [{'actor': actor, 'seq': 1}]

    def test_create_nested_maps_request(self):
        doc, change = Frontend.change(Frontend.init(),
                                      lambda d: d.update({'birds': {'wrens': 3}}))
        actor = Frontend.get_actor_id(doc)
        birds = Frontend.get_object_id(doc['birds'])
        assert doc == {'birds': {'wrens': 3}}
        assert birds == f'1@{actor}'
        assert change['ops'] == [
            {'obj': '_root', 'action': 'makeMap', 'key': 'birds',
             'insert': False, 'pred': []},
            {'obj': birds, 'action': 'set', 'key': 'wrens', 'insert': False,
             'value': 3, 'datatype': 'int', 'pred': []}]

    def test_updates_inside_nested_maps(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda d: d.update({'birds': {'wrens': 3}}))
        doc2, change2 = Frontend.change(
            doc1, lambda d: d['birds'].update({'sparrows': 15}))
        birds = Frontend.get_object_id(doc2['birds'])
        actor = Frontend.get_actor_id(doc1)
        assert doc1 == {'birds': {'wrens': 3}}
        assert doc2 == {'birds': {'wrens': 3, 'sparrows': 15}}
        assert change2['ops'] == [
            {'obj': birds, 'action': 'set', 'key': 'sparrows', 'insert': False,
             'value': 15, 'datatype': 'int', 'pred': []}]
        assert change2['startOp'] == 3
        assert change2['actor'] == actor

    def test_delete_keys(self):
        actor = uuid()
        doc1, _ = Frontend.change(
            Frontend.init(actor),
            lambda d: d.update({'magpies': 2, 'sparrows': 15}))
        doc2, change2 = Frontend.change(
            doc1, lambda d: d.__delitem__('magpies'))
        assert dict(doc2) == {'sparrows': 15}
        assert change2['ops'] == [
            {'obj': '_root', 'action': 'del', 'key': 'magpies',
             'insert': False, 'pred': [f'1@{actor}']}]

    def test_create_lists(self):
        doc, change = Frontend.change(Frontend.init(),
                                      lambda d: d.update({'birds': ['chaffinch']}))
        actor = Frontend.get_actor_id(doc)
        birds = Frontend.get_object_id(doc['birds'])
        assert doc == {'birds': ['chaffinch']}
        assert change['ops'] == [
            {'obj': '_root', 'action': 'makeList', 'key': 'birds',
             'insert': False, 'pred': []},
            {'obj': birds, 'action': 'set', 'elemId': '_head', 'insert': True,
             'value': 'chaffinch', 'pred': []}]

    def test_updates_inside_lists(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda d: d.update({'birds': ['chaffinch']}))
        doc2, change2 = Frontend.change(
            doc1, lambda d: d['birds'].__setitem__(0, 'greenfinch'))
        birds = Frontend.get_object_id(doc2['birds'])
        actor = Frontend.get_actor_id(doc1)
        assert doc2 == {'birds': ['greenfinch']}
        assert change2['ops'] == [
            {'obj': birds, 'action': 'set', 'elemId': f'2@{actor}',
             'insert': False, 'value': 'greenfinch', 'pred': [f'2@{actor}']}]

    def test_assign_past_end_inserts_nulls(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda d: d.update({'birds': ['chaffinch']}))
        doc2, _ = Frontend.change(
            doc1, lambda d: d['birds'].__setitem__(2, 'greenfinch'))
        assert doc2 == {'birds': ['chaffinch', None, 'greenfinch']}

    def test_delete_list_elements(self):
        actor = uuid()
        doc1, _ = Frontend.change(
            Frontend.init(actor),
            lambda d: d.update({'birds': ['chaffinch', 'goldfinch']}))
        doc2, change2 = Frontend.change(doc1, lambda d: d['birds'].delete_at(0))
        birds = Frontend.get_object_id(doc2['birds'])
        assert doc2 == {'birds': ['goldfinch']}
        assert change2['ops'] == [
            {'obj': birds, 'action': 'del', 'elemId': f'2@{actor}',
             'insert': False, 'pred': [f'2@{actor}']}]

    def test_date_stored_as_timestamp(self):
        import datetime
        now = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        doc, change = Frontend.change(Frontend.init(),
                                      lambda d: d.update({'now': now}))
        assert change['ops'][0]['datatype'] == 'timestamp'
        assert isinstance(doc['now'], datetime.datetime)
        assert doc['now'] == now


class TestCounters:
    def test_counter_in_map(self):
        actor = uuid()
        doc1, change1 = Frontend.change(
            Frontend.init(actor), lambda d: d.update({'wrens': Counter(0)}))
        assert doc1['wrens'] == Counter(0)
        doc2, change2 = Frontend.change(
            doc1, lambda d: d['wrens'].increment())
        assert doc2['wrens'] == Counter(1)
        assert change1['ops'] == [
            {'obj': '_root', 'action': 'set', 'key': 'wrens', 'insert': False,
             'value': 0, 'datatype': 'counter', 'pred': []}]
        assert change2['ops'] == [
            {'obj': '_root', 'action': 'inc', 'key': 'wrens', 'insert': False,
             'value': 1, 'pred': [f'1@{actor}']}]

    def test_counter_in_list(self):
        actor = uuid()
        doc1, _ = Frontend.change(
            Frontend.init(actor), lambda d: d.update({'counts': [Counter(1)]}))
        doc2, change2 = Frontend.change(
            doc1, lambda d: d['counts'][0].increment(2))
        assert doc2['counts'][0] == Counter(3)
        assert change2['ops'] == [
            {'obj': f'1@{actor}', 'action': 'inc', 'elemId': f'2@{actor}',
             'insert': False, 'value': 2, 'pred': [f'2@{actor}']}]

    def test_refuse_overwriting_counter(self):
        doc1, _ = Frontend.change(
            Frontend.init(), lambda d: d.update({'counter': Counter(1)}))
        with pytest.raises(ValueError, match='Cannot overwrite a Counter'):
            Frontend.change(doc1, lambda d: d.update({'counter': 42}))

    def test_counter_behaves_like_number(self):
        doc, _ = Frontend.change(
            Frontend.init(), lambda d: d.update({'birds': Counter(3)}))
        c = doc['birds']
        assert c + 10 == 13
        assert c < 4 and c >= 3
        assert int(c) == 3
        assert str(c) == '3'

    def test_counter_json_serializable(self):
        import json
        doc, _ = Frontend.change(
            Frontend.init(), lambda d: d.update({'birds': Counter()}))
        assert json.dumps({'birds': doc['birds'].to_json()}) == '{"birds": 0}'


class TestBackendConcurrency:
    """Async request-queue mode: frontend and backend on separate threads."""

    def test_version_and_seq_from_backend(self):
        local, remote1, remote2 = uuid(), uuid(), uuid()
        patch1 = {
            'clock': {local: 4, remote1: 11, remote2: 41}, 'maxOp': 4,
            'deps': [],
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'blackbirds': {local: {'type': 'value', 'value': 24}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(local), patch1)
        doc2, change = Frontend.change(doc1,
                                       lambda d: d.update({'partridges': 1}))
        assert change == {
            'actor': local, 'seq': 5, 'deps': [], 'startOp': 5,
            'time': change['time'], 'message': '',
            'ops': [{'obj': '_root', 'action': 'set', 'key': 'partridges',
                     'insert': False, 'datatype': 'int', 'value': 1,
                     'pred': []}]}
        assert get_requests(doc2) == [{'actor': local, 'seq': 5}]

    def test_remove_pending_requests_once_handled(self):
        actor = uuid()
        doc1, change1 = Frontend.change(Frontend.init(actor),
                                        lambda d: d.update({'blackbirds': 24}))
        doc2, change2 = Frontend.change(doc1,
                                        lambda d: d.update({'partridges': 1}))
        assert change1['seq'] == 1 and change1['startOp'] == 1
        assert change2['seq'] == 2 and change2['startOp'] == 2
        assert get_requests(doc2) == [{'actor': actor, 'seq': 1},
                                      {'actor': actor, 'seq': 2}]

        doc2 = Frontend.apply_patch(doc2, {
            'actor': actor, 'seq': 1, 'clock': {actor: 1}, 'deps': [],
            'maxOp': 1,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'blackbirds': {actor: {'type': 'value', 'value': 24}}}}})
        assert get_requests(doc2) == [{'actor': actor, 'seq': 2}]
        assert doc2 == {'blackbirds': 24, 'partridges': 1}

        doc2 = Frontend.apply_patch(doc2, {
            'actor': actor, 'seq': 2, 'clock': {actor: 2}, 'deps': [],
            'maxOp': 2,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'partridges': {actor: {'type': 'value', 'value': 1}}}}})
        assert doc2 == {'blackbirds': 24, 'partridges': 1}
        assert get_requests(doc2) == []

    def test_remote_patches_leave_queue_unchanged(self):
        actor, other = uuid(), uuid()
        doc, req = Frontend.change(Frontend.init(actor),
                                   lambda d: d.update({'blackbirds': 24}))
        assert get_requests(doc) == [{'actor': actor, 'seq': 1}]

        doc = Frontend.apply_patch(doc, {
            'clock': {other: 1}, 'deps': [], 'maxOp': 1,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'pheasants': {other: {'type': 'value', 'value': 2}}}}})
        # Remote value not visible yet: the local request is still in flight
        assert doc == {'blackbirds': 24}
        assert get_requests(doc) == [{'actor': actor, 'seq': 1}]

        doc = Frontend.apply_patch(doc, {
            'actor': actor, 'seq': 1, 'clock': {actor: 1, other: 1},
            'deps': [], 'maxOp': 1,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'blackbirds': {actor: {'type': 'value', 'value': 24}}}}})
        assert doc == {'blackbirds': 24, 'pheasants': 2}
        assert get_requests(doc) == []

    def test_out_of_order_request_patches_rejected(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda d: d.update({'blackbirds': 24}))
        doc2, _ = Frontend.change(doc1, lambda d: d.update({'partridges': 1}))
        actor = Frontend.get_actor_id(doc2)
        diffs = {'objectId': '_root', 'type': 'map', 'props': {
            'partridges': {actor: {'type': 'value', 'value': 1}}}}
        with pytest.raises(ValueError, match='Mismatched sequence number'):
            Frontend.apply_patch(doc2, {'actor': actor, 'seq': 2,
                                        'clock': {actor: 2}, 'deps': [],
                                        'maxOp': 2, 'diffs': diffs})

    def test_concurrent_insertions_into_lists(self):
        doc1, _ = Frontend.change(Frontend.init(),
                                  lambda d: d.update({'birds': ['goldfinch']}))
        birds = Frontend.get_object_id(doc1['birds'])
        actor = Frontend.get_actor_id(doc1)
        doc1 = Frontend.apply_patch(doc1, {
            'actor': actor, 'seq': 1, 'clock': {actor: 1}, 'maxOp': 2,
            'deps': [],
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {actor: {'objectId': birds, 'type': 'list', 'edits': [
                    {'action': 'insert', 'elemId': f'2@{actor}',
                     'opId': f'2@{actor}', 'index': 0,
                     'value': {'type': 'value', 'value': 'goldfinch'}}]}}}}})
        assert doc1 == {'birds': ['goldfinch']}
        assert get_requests(doc1) == []

        def ins(d):
            d['birds'].insert_at(0, 'chaffinch')
            d['birds'].insert_at(2, 'greenfinch')
        doc2, _ = Frontend.change(doc1, ins)
        assert doc2 == {'birds': ['chaffinch', 'goldfinch', 'greenfinch']}

        remote_actor = uuid()
        doc3 = Frontend.apply_patch(doc2, {
            'clock': {actor: 1, remote_actor: 1}, 'maxOp': 4, 'deps': [],
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {actor: {'objectId': birds, 'type': 'list', 'edits': [
                    {'action': 'insert', 'elemId': f'1@{remote_actor}',
                     'opId': f'1@{remote_actor}', 'index': 1,
                     'value': {'type': 'value', 'value': 'bullfinch'}}]}}}}})
        # Remote insert does not take effect until our request round-trips
        assert doc3 == {'birds': ['chaffinch', 'goldfinch', 'greenfinch']}

        doc4 = Frontend.apply_patch(doc3, {
            'actor': actor, 'seq': 2, 'clock': {actor: 2, remote_actor: 1},
            'maxOp': 4, 'deps': [],
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {actor: {'objectId': birds, 'type': 'list', 'edits': [
                    {'action': 'insert', 'index': 0, 'elemId': f'3@{actor}',
                     'opId': f'3@{actor}',
                     'value': {'type': 'value', 'value': 'chaffinch'}},
                    {'action': 'insert', 'index': 2, 'elemId': f'4@{actor}',
                     'opId': f'4@{actor}',
                     'value': {'type': 'value', 'value': 'greenfinch'}}]}}}}})
        assert doc4 == {'birds': ['chaffinch', 'goldfinch', 'greenfinch',
                                  'bullfinch']}
        assert get_requests(doc4) == []

    def test_interleaving_patches_and_changes(self):
        actor = uuid()
        doc1, change1 = Frontend.change(Frontend.init(actor),
                                        lambda d: d.update({'number': 1}))
        doc2, change2 = Frontend.change(doc1, lambda d: d.update({'number': 2}))
        assert change2['ops'] == [
            {'obj': '_root', 'action': 'set', 'key': 'number', 'insert': False,
             'datatype': 'int', 'value': 2, 'pred': [f'1@{actor}']}]
        state0 = Backend.init()
        _state1, patch1, _bin1 = Backend.apply_local_change(state0, change1)
        doc2a = Frontend.apply_patch(doc2, patch1)
        _doc3, change3 = Frontend.change(doc2a, lambda d: d.update({'number': 3}))
        assert change3['seq'] == 3 and change3['startOp'] == 3
        assert change3['ops'] == [
            {'obj': '_root', 'action': 'set', 'key': 'number', 'insert': False,
             'datatype': 'int', 'value': 3, 'pred': [f'2@{actor}']}]

    def test_deps_filled_in_when_frontend_behind(self):
        actor1, actor2 = uuid(), uuid()
        _doc1, change1 = Frontend.change(Frontend.init(actor1),
                                         lambda d: d.update({'number': 1}))
        _s, _p, bin1 = Backend.apply_local_change(Backend.init(), change1)

        state1a, patch1a = Backend.apply_changes(Backend.init(), [bin1])
        doc1a = Frontend.apply_patch(Frontend.init(actor2), patch1a)
        doc2, change2 = Frontend.change(doc1a, lambda d: d.update({'number': 2}))
        doc3, change3 = Frontend.change(doc2, lambda d: d.update({'number': 3}))
        hash1 = decode_change(bin1)['hash']
        assert change2['deps'] == [hash1]
        assert change2['startOp'] == 2
        assert change2['ops'][0]['pred'] == [f'1@{actor1}']
        assert change3['deps'] == []
        assert change3['ops'][0]['pred'] == [f'2@{actor2}']

        state2, patch2, bin2 = Backend.apply_local_change(state1a, change2)
        state3, patch3, bin3 = Backend.apply_local_change(state2, change3)
        assert decode_change(bin2)['deps'] == [hash1]
        assert decode_change(bin3)['deps'] == [decode_change(bin2)['hash']]
        assert patch1a['deps'] == [hash1]
        assert patch2['deps'] == []

        doc2a = Frontend.apply_patch(doc3, patch2)
        doc3a = Frontend.apply_patch(doc2a, patch3)
        _doc4, change4 = Frontend.change(doc3a, lambda d: d.update({'number': 4}))
        assert change4['seq'] == 3 and change4['startOp'] == 4
        assert change4['deps'] == []
        _s4, _p4, bin4 = Backend.apply_local_change(state3, change4)
        assert decode_change(bin4)['deps'] == [decode_change(bin3)['hash']]


class TestApplyingPatches:
    def test_set_root_properties(self):
        actor = uuid()
        patch = {'clock': {actor: 1}, 'deps': [], 'maxOp': 1,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'bird': {f'1@{actor}': {'type': 'value',
                                             'value': 'magpie'}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        assert dict(doc) == {'bird': 'magpie'}

    def test_reveal_conflicts_on_root(self):
        actor1, actor2 = '02ef21', '2a1d37'
        patch = {'clock': {actor1: 1, actor2: 1}, 'deps': [], 'maxOp': 1,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'favoriteBird': {
                         f'1@{actor1}': {'type': 'value', 'value': 'robin'},
                         f'1@{actor2}': {'type': 'value', 'value': 'wagtail'}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        # Lamport: higher actorId wins at equal counter
        assert dict(doc) == {'favoriteBird': 'wagtail'}
        assert Frontend.get_conflicts(doc, 'favoriteBird') == {
            f'1@{actor1}': 'robin', f'1@{actor2}': 'wagtail'}

    def test_create_nested_maps_from_patch(self):
        actor = uuid()
        patch = {'clock': {actor: 1}, 'deps': [], 'maxOp': 2,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'birds': {f'1@{actor}': {
                         'objectId': f'1@{actor}', 'type': 'map', 'props': {
                             'wrens': {f'2@{actor}': {'type': 'value',
                                                      'value': 3,
                                                      'datatype': 'int'}}}}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        assert doc == {'birds': {'wrens': 3}}

    def test_create_lists_from_patch(self):
        actor = uuid()
        patch = {'clock': {actor: 1}, 'deps': [], 'maxOp': 2,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'birds': {f'1@{actor}': {
                         'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                             {'action': 'insert', 'index': 0,
                              'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                              'value': {'type': 'value',
                                        'value': 'chaffinch'}}]}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        assert doc == {'birds': ['chaffinch']}

    def test_multi_insert_patch(self):
        actor = uuid()
        patch = {'clock': {actor: 1}, 'deps': [], 'maxOp': 4,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'birds': {f'1@{actor}': {
                         'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                             {'action': 'multi-insert', 'index': 0,
                              'elemId': f'2@{actor}',
                              'values': ['a', 'b', 'c']}]}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        assert doc == {'birds': ['a', 'b', 'c']}
        assert Frontend.get_element_ids(doc['birds']) == \
            [f'2@{actor}', f'3@{actor}', f'4@{actor}']

    def test_text_patch(self):
        actor = uuid()
        patch = {'clock': {actor: 1}, 'deps': [], 'maxOp': 3,
                 'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                     'text': {f'1@{actor}': {
                         'objectId': f'1@{actor}', 'type': 'text', 'edits': [
                             {'action': 'multi-insert', 'index': 0,
                              'elemId': f'2@{actor}', 'values': ['h', 'i']}]}}}}}
        doc = Frontend.apply_patch(Frontend.init(), patch)
        assert isinstance(doc['text'], Text)
        assert str(doc['text']) == 'hi'


class TestApplyingPatchesMore:
    """Remaining patch-application cases (ref frontend_test.js:478-763)."""

    def test_updates_inside_nested_maps_from_patch(self):
        birds, actor = uuid(), uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 2,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'map', 'props': {
                              'wrens': {actor: {'type': 'value',
                                                'value': 3}}}}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'map', 'props': {
                              'sparrows': {actor: {'type': 'value',
                                                   'value': 15}}}}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'birds': {'wrens': 3}}
        assert doc2 == {'birds': {'wrens': 3, 'sparrows': 15}}

    def test_updates_inside_map_key_conflicts(self):
        birds1, birds2 = uuid(), uuid()
        patch1 = {'clock': {birds1: 1, birds2: 1}, 'deps': [], 'maxOp': 2,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'favoriteBirds': {
                          'actor1': {'objectId': birds1, 'type': 'map',
                                     'props': {'blackbirds': {
                                         'actor1': {'type': 'value',
                                                    'value': 1}}}},
                          'actor2': {'objectId': birds2, 'type': 'map',
                                     'props': {'wrens': {
                                         'actor2': {'type': 'value',
                                                    'value': 3}}}}}}}}
        patch2 = {'clock': {birds1: 2, birds2: 1}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'favoriteBirds': {
                          'actor1': {'objectId': birds1, 'type': 'map',
                                     'props': {'blackbirds': {
                                         'actor1': {'value': 2}}}},
                          'actor2': {'objectId': birds2, 'type': 'map'}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'favoriteBirds': {'wrens': 3}}
        assert doc2 == {'favoriteBirds': {'wrens': 3}}
        assert Frontend.get_conflicts(doc1, 'favoriteBirds') == {
            'actor1': {'blackbirds': 1}, 'actor2': {'wrens': 3}}
        assert Frontend.get_conflicts(doc2, 'favoriteBirds') == {
            'actor1': {'blackbirds': 2}, 'actor2': {'wrens': 3}}

    def test_structure_shares_unmodified_objects(self):
        birds, mammals, actor = uuid(), uuid(), uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 4,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'map', 'props': {
                              'wrens': {actor: {'value': 3}}}}},
                      'mammals': {actor: {
                          'objectId': mammals, 'type': 'map', 'props': {
                              'badgers': {actor: {'value': 1}}}}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 5,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'map', 'props': {
                              'sparrows': {actor: {'value': 15}}}}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'birds': {'wrens': 3}, 'mammals': {'badgers': 1}}
        assert doc2 == {'birds': {'wrens': 3, 'sparrows': 15},
                        'mammals': {'badgers': 1}}
        assert doc1['mammals'] is doc2['mammals']

    def test_delete_keys_in_maps_from_patch(self):
        actor = uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 2,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'magpies': {actor: {'value': 2}},
                      'sparrows': {actor: {'value': 15}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'magpies': {}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'magpies': 2, 'sparrows': 15}
        assert doc2 == {'sparrows': 15}

    def test_updates_inside_lists_from_patch(self):
        birds, actor = uuid(), uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 2,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'insert', 'index': 0,
                               'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                               'value': {'value': 'chaffinch'}}]}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {actor: {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'update', 'index': 0,
                               'opId': f'3@{actor}',
                               'value': {'value': 'greenfinch'}}]}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'birds': ['chaffinch']}
        assert doc2 == {'birds': ['greenfinch']}

    def test_updates_inside_list_element_conflicts(self):
        actor1, actor2 = '01234567', '89abcdef'
        birds = f'1@{actor1}'
        patch1 = {'clock': {actor1: 2, actor2: 1}, 'deps': [], 'maxOp': 4,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {birds: {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'insert', 'index': 0,
                               'elemId': f'2@{actor1}', 'opId': f'2@{actor1}',
                               'value': {
                                   'objectId': f'2@{actor1}', 'type': 'map',
                                   'props': {
                                       'species': {f'3@{actor1}': {
                                           'type': 'value',
                                           'value': 'woodpecker'}},
                                       'numSeen': {f'4@{actor1}': {
                                           'type': 'value', 'value': 1}}}}},
                              {'action': 'update', 'index': 0,
                               'opId': f'2@{actor2}', 'value': {
                                   'objectId': f'2@{actor2}', 'type': 'map',
                                   'props': {
                                       'species': {f'3@{actor2}': {
                                           'type': 'value',
                                           'value': 'lapwing'}},
                                       'numSeen': {f'4@{actor2}': {
                                           'type': 'value', 'value': 2}}}}}]}}}}}
        patch2 = {'clock': {actor1: 3, actor2: 1}, 'deps': [], 'maxOp': 5,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {birds: {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'update', 'index': 0,
                               'opId': f'2@{actor1}', 'value': {
                                   'objectId': f'2@{actor1}', 'type': 'map',
                                   'props': {'numSeen': {f'5@{actor1}': {
                                       'type': 'value', 'value': 2}}}}},
                              {'action': 'update', 'index': 0,
                               'opId': f'2@{actor2}', 'value': {
                                   'objectId': f'2@{actor2}', 'type': 'map',
                                   'props': {}}}]}}}}}
        patch3 = {'clock': {actor1: 3, actor2: 1}, 'deps': [], 'maxOp': 6,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {birds: {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'update', 'index': 0,
                               'opId': f'2@{actor1}', 'value': {
                                   'objectId': f'2@{actor1}', 'type': 'map',
                                   'props': {'numSeen': {f'6@{actor1}': {
                                       'type': 'value', 'value': 2}}}}}]}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        doc3 = Frontend.apply_patch(doc2, patch3)
        assert doc1 == {'birds': [{'species': 'lapwing', 'numSeen': 2}]}
        assert doc2 == {'birds': [{'species': 'lapwing', 'numSeen': 2}]}
        assert doc3 == {'birds': [{'species': 'woodpecker', 'numSeen': 2}]}
        assert doc1['birds'][0] is doc2['birds'][0]
        assert Frontend.get_conflicts(doc1['birds'], 0) == {
            f'2@{actor1}': {'species': 'woodpecker', 'numSeen': 1},
            f'2@{actor2}': {'species': 'lapwing', 'numSeen': 2}}
        assert Frontend.get_conflicts(doc2['birds'], 0) == {
            f'2@{actor1}': {'species': 'woodpecker', 'numSeen': 2},
            f'2@{actor2}': {'species': 'lapwing', 'numSeen': 2}}
        assert Frontend.get_conflicts(doc3['birds'], 0) is None

    def test_delete_list_elements_from_patch(self):
        birds, actor = uuid(), uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {f'1@{actor}': {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'insert', 'index': 0,
                               'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                               'value': {'value': 'chaffinch'}},
                              {'action': 'insert', 'index': 1,
                               'elemId': f'3@{actor}', 'opId': f'3@{actor}',
                               'value': {'value': 'goldfinch'}}]}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 4,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {f'1@{actor}': {
                          'objectId': birds, 'type': 'list', 'props': {},
                          'edits': [{'action': 'remove', 'index': 0,
                                     'count': 1}]}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'birds': ['chaffinch', 'goldfinch']}
        assert doc2 == {'birds': ['goldfinch']}

    def test_delete_multiple_list_elements_from_patch(self):
        birds, actor = uuid(), uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 3,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {f'1@{actor}': {
                          'objectId': birds, 'type': 'list', 'edits': [
                              {'action': 'insert', 'index': 0,
                               'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                               'value': {'value': 'chaffinch'}},
                              {'action': 'insert', 'index': 1,
                               'elemId': f'3@{actor}', 'opId': f'3@{actor}',
                               'value': {'value': 'goldfinch'}}]}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 4,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'birds': {f'1@{actor}': {
                          'objectId': birds, 'type': 'list', 'props': {},
                          'edits': [{'action': 'remove', 'index': 0,
                                     'count': 2}]}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'birds': ['chaffinch', 'goldfinch']}
        assert doc2 == {'birds': []}

    def test_updates_at_different_tree_levels(self):
        actor = uuid()
        patch1 = {'clock': {actor: 1}, 'deps': [], 'maxOp': 6,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'counts': {f'1@{actor}': {
                          'objectId': f'1@{actor}', 'type': 'map', 'props': {
                              'magpies': {f'2@{actor}': {'value': 2}}}}},
                      'details': {f'3@{actor}': {
                          'objectId': f'3@{actor}', 'type': 'list', 'edits': [
                              {'action': 'insert', 'index': 0,
                               'elemId': f'4@{actor}', 'opId': f'4@{actor}',
                               'value': {
                                   'objectId': f'4@{actor}', 'type': 'map',
                                   'props': {
                                       'species': {f'5@{actor}': {
                                           'type': 'value',
                                           'value': 'magpie'}},
                                       'family': {f'6@{actor}': {
                                           'type': 'value',
                                           'value': 'corvidae'}}}}}]}}}}}
        patch2 = {'clock': {actor: 2}, 'deps': [], 'maxOp': 8,
                  'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                      'counts': {f'1@{actor}': {
                          'objectId': f'1@{actor}', 'type': 'map', 'props': {
                              'magpies': {f'7@{actor}': {'type': 'value',
                                                         'value': 3}}}}},
                      'details': {f'3@{actor}': {
                          'objectId': f'3@{actor}', 'type': 'list', 'edits': [
                              {'action': 'update', 'index': 0,
                               'opId': f'4@{actor}', 'value': {
                                   'objectId': f'4@{actor}', 'type': 'map',
                                   'props': {'species': {f'8@{actor}': {
                                       'type': 'value',
                                       'value': 'Eurasian magpie'}}}}}]}}}}}
        doc1 = Frontend.apply_patch(Frontend.init(), patch1)
        doc2 = Frontend.apply_patch(doc1, patch2)
        assert doc1 == {'counts': {'magpies': 2},
                        'details': [{'species': 'magpie',
                                     'family': 'corvidae'}]}
        assert doc2 == {'counts': {'magpies': 3},
                        'details': [{'species': 'Eurasian magpie',
                                     'family': 'corvidae'}]}
