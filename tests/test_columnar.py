"""Columnar change-format tests, ported from reference test/columnar_test.js,
plus extra round-trip coverage."""

import pytest

from automerge_tpu.columnar import encode_change, decode_change


class TestChangeEncoding:
    def test_encode_text_edits_exact_bytes(self):
        change1 = {'actor': 'aaaa', 'seq': 1, 'startOp': 1, 'time': 9, 'message': '',
                   'deps': [], 'ops': [
            {'action': 'makeText', 'obj': '_root', 'key': 'text', 'insert': False, 'pred': []},
            {'action': 'set', 'obj': '1@aaaa', 'elemId': '_head', 'insert': True,
             'value': 'h', 'pred': []},
            {'action': 'del', 'obj': '1@aaaa', 'elemId': '2@aaaa', 'insert': False,
             'pred': ['2@aaaa']},
            {'action': 'set', 'obj': '1@aaaa', 'elemId': '_head', 'insert': True,
             'value': 'H', 'pred': []},
            {'action': 'set', 'obj': '1@aaaa', 'elemId': '4@aaaa', 'insert': True,
             'value': 'i', 'pred': []},
        ]}
        expected = bytes([
            0x85, 0x6f, 0x4a, 0x83,  # magic bytes
            0xe2, 0xbd, 0xfb, 0xf5,  # checksum
            1, 94, 0, 2, 0xaa, 0xaa,  # chunkType: change, length, deps, actor 'aaaa'
            1, 1, 9, 0, 0,  # seq, startOp, time, message, actor list
            12, 0x01, 4, 0x02, 4,  # column count, objActor, objCtr
            0x11, 8, 0x13, 7, 0x15, 8,  # keyActor, keyCtr, keyStr
            0x34, 4, 0x42, 6,  # insert, action
            0x56, 6, 0x57, 3,  # valLen, valRaw
            0x70, 6, 0x71, 2, 0x73, 2,  # predNum, predActor, predCtr
            0, 1, 4, 0,  # objActor column: null, 0, 0, 0, 0
            0, 1, 4, 1,  # objCtr column: null, 1, 1, 1, 1
            0, 2, 0x7f, 0, 0, 1, 0x7f, 0,  # keyActor column: null, null, 0, null, 0
            0, 1, 0x7c, 0, 2, 0x7e, 4,  # keyCtr column: null, 0, 2, 0, 4
            0x7f, 4, 0x74, 0x65, 0x78, 0x74, 0, 4,  # keyStr column: 'text', null x4
            1, 1, 1, 2,  # insert column: false, true, false, true, true
            0x7d, 4, 1, 3, 2, 1,  # action column: makeText, set, del, set, set
            0x7d, 0, 0x16, 0, 2, 0x16,  # valLen column
            0x68, 0x48, 0x69,  # valRaw column: 'h', 'H', 'i'
            2, 0, 0x7f, 1, 2, 0,  # predNum column: 0, 0, 1, 0, 0
            0x7f, 0,  # predActor column: 0
            0x7f, 2,  # predCtr column: 2
        ])
        assert encode_change(change1) == expected
        decoded = decode_change(encode_change(change1))
        expected_decoded = dict(change1, hash=decoded['hash'])
        assert decoded == expected_decoded

    def test_strict_pred_ordering(self):
        change = bytes([
            133, 111, 74, 131, 31, 229, 112, 44, 1, 105, 1, 58, 30, 190, 100, 253, 180,
            180, 66, 49, 126, 81, 142, 10, 3, 35, 140, 189, 231, 34, 145, 57, 66, 23,
            224, 149, 64, 97, 88, 140, 168, 194, 229, 4, 244, 209, 58, 138, 67, 140, 1,
            152, 236, 250, 2, 0, 1, 4, 55, 234, 66, 242, 8, 21, 11, 52, 1, 66, 2, 86, 3,
            87, 10, 112, 2, 113, 3, 115, 4, 127, 9, 99, 111, 109, 109, 111, 110, 86, 97,
            114, 1, 127, 1, 127, 166, 1, 52, 48, 57, 49, 52, 57, 52, 53, 56, 50, 127, 2,
            126, 0, 1, 126, 139, 1, 0,
        ])
        with pytest.raises(ValueError, match='operation IDs are not in ascending order'):
            decode_change(change)

    TRAILING_BYTES_CHANGE = bytes([
        0x85, 0x6f, 0x4a, 0x83,  # magic bytes
        0xb2, 0x98, 0x9e, 0xa9,  # checksum
        1, 61, 0, 2, 0x12, 0x34,  # chunkType: change, length, deps, actor '1234'
        1, 1, 252, 250, 220, 255, 5,  # seq, startOp, time
        14, 73, 110, 105, 116, 105, 97, 108, 105, 122, 97, 116, 105, 111, 110,  # message
        0, 6,  # actor list, column count
        0x15, 3, 0x34, 1, 0x42, 2,  # keyStr, insert, action
        0x56, 2, 0x57, 1, 0x70, 2,  # valLen, valRaw, predNum
        0x7f, 1, 0x78,  # keyStr: 'x'
        1,  # insert: false
        0x7f, 1,  # action: set
        0x7f, 19,  # valLen: 1 byte of type uint
        1,  # valRaw: 1
        0x7f, 0,  # predNum: 0
        0, 1, 2, 3, 4, 5, 6, 7, 8, 9,  # 10 trailing bytes
    ])

    def test_trailing_bytes_decode_reencode(self):
        assert encode_change(decode_change(self.TRAILING_BYTES_CHANGE)) == \
            self.TRAILING_BYTES_CHANGE


class TestRoundTrips:
    def test_map_ops_round_trip(self):
        change = {'actor': 'deadbeef', 'seq': 1, 'startOp': 1, 'time': 0,
                  'message': 'hi', 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'a', 'insert': False,
             'value': 'magpie', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'b', 'insert': False,
             'value': 42, 'datatype': 'int', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'c', 'insert': False,
             'value': 1.5, 'datatype': 'float64', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'd', 'insert': False,
             'value': True, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'e', 'insert': False,
             'value': None, 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'f', 'insert': False,
             'value': 3, 'datatype': 'counter', 'pred': []},
            {'action': 'set', 'obj': '_root', 'key': 'g', 'insert': False,
             'value': 1609459200000, 'datatype': 'timestamp', 'pred': []},
        ]}
        decoded = decode_change(encode_change(change))
        assert decoded['actor'] == 'deadbeef'
        assert decoded['message'] == 'hi'
        ops = decoded['ops']
        assert ops[0]['value'] == 'magpie'
        assert ops[1]['value'] == 42 and ops[1]['datatype'] == 'int'
        assert ops[2]['value'] == 1.5 and ops[2]['datatype'] == 'float64'
        assert ops[3]['value'] is True
        assert ops[4]['value'] is None
        assert ops[5]['value'] == 3 and ops[5]['datatype'] == 'counter'
        assert ops[6]['value'] == 1609459200000 and ops[6]['datatype'] == 'timestamp'

    def test_multi_actor_preds_round_trip(self):
        change = {'actor': 'aaaa', 'seq': 2, 'startOp': 5, 'time': 123,
                  'message': '', 'deps': [], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'k', 'insert': False,
             'value': 1, 'datatype': 'int', 'pred': ['3@bbbb', '4@aaaa']},
        ]}
        decoded = decode_change(encode_change(change))
        # preds are sorted into Lamport order on encode
        assert decoded['ops'][0]['pred'] == ['3@bbbb', '4@aaaa']

    def test_deps_round_trip(self):
        h1 = 'aa' * 32
        h2 = 'bb' * 32
        change = {'actor': 'abcd', 'seq': 3, 'startOp': 10, 'time': 1, 'message': 'm',
                  'deps': [h2, h1], 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'x', 'insert': False,
             'value': 1, 'datatype': 'uint', 'pred': []},
        ]}
        decoded = decode_change(encode_change(change))
        assert decoded['deps'] == [h1, h2]  # sorted

    def test_large_change_deflated(self):
        ops = [{'action': 'set', 'obj': '_root', 'key': f'key-{i:04d}', 'insert': False,
                'value': f'value-{i}', 'pred': []} for i in range(100)]
        change = {'actor': 'cafe', 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
                  'deps': [], 'ops': ops}
        encoded = encode_change(change)
        assert encoded[8] == 2  # CHUNK_TYPE_DEFLATE
        decoded = decode_change(encoded)
        assert len(decoded['ops']) == 100
        assert decoded['ops'][99]['value'] == 'value-99'

    def test_multi_insert_expansion(self):
        change = {'actor': 'aaaa', 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
                  'deps': [], 'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'list', 'insert': False,
             'pred': []},
            {'action': 'set', 'obj': '1@aaaa', 'elemId': '_head', 'insert': True,
             'values': [1, 2, 3], 'datatype': 'int', 'pred': []},
        ]}
        decoded = decode_change(encode_change(change))
        assert len(decoded['ops']) == 4
        assert [op.get('value') for op in decoded['ops'][1:]] == [1, 2, 3]
        assert decoded['ops'][1]['elemId'] == '_head'
        assert decoded['ops'][2]['elemId'] == '2@aaaa'
        assert decoded['ops'][3]['elemId'] == '3@aaaa'
