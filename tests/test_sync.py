"""Sync protocol tests, ported from reference test/sync_test.js: 2-peer
in-memory reconciliation driver, divergence scenarios, crash recovery, and
Bloom-filter false positives."""

import pytest

import automerge_tpu as A
from automerge_tpu import backend as Backend
from automerge_tpu.backend.sync import BloomFilter
from automerge_tpu.backend import (
    decode_sync_message, encode_sync_state, decode_sync_state, init_sync_state,
)
from automerge_tpu.columnar import decode_change_meta


def get_heads(doc):
    return Backend.get_heads(A.Frontend.get_backend_state(doc))


def sync(a, b, a_sync_state=None, b_sync_state=None):
    """In-memory 2-peer convergence loop (ref sync_test.js:15-35)."""
    a_sync_state = a_sync_state or init_sync_state()
    b_sync_state = b_sync_state or init_sync_state()
    max_iter = 10
    i = 0
    while True:
        a_sync_state, a_to_b = A.generate_sync_message(a, a_sync_state)
        b_sync_state, b_to_a = A.generate_sync_message(b, b_sync_state)
        if a_to_b:
            b, b_sync_state, _ = A.receive_sync_message(b, b_sync_state, a_to_b)
        if b_to_a:
            a, a_sync_state, _ = A.receive_sync_message(a, a_sync_state, b_to_a)
        i += 1
        if i > max_iter:
            raise AssertionError(f'Did not synchronize within {max_iter} iterations')
        if not a_to_b and not b_to_a:
            break
    return a, b, a_sync_state, b_sync_state


class TestInSync:
    def test_empty_local_doc_message(self):
        n1 = A.init()
        s1, m1 = A.generate_sync_message(n1, init_sync_state())
        message = decode_sync_message(m1)
        assert message['heads'] == []
        assert message['need'] == []
        assert len(message['have']) == 1
        assert message['have'][0]['lastSync'] == []
        assert len(message['have'][0]['bloom']) == 0
        assert message['changes'] == []

    def test_no_reply_when_both_empty(self):
        n1, n2 = A.init(), A.init()
        s1, m1 = A.generate_sync_message(n1, init_sync_state())
        n2, s2, _ = A.receive_sync_message(n2, init_sync_state(), m1)
        s2, m2 = A.generate_sync_message(n2, s2)
        assert m2 is None

    def test_equal_heads_no_reply(self):
        n1, n2 = A.init(), A.init()
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'n': []}))
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d['n'].append(i))
        n2, _ = A.apply_changes(n2, A.get_all_changes(n1))
        assert A.equals(n1, n2)
        s1, m1 = A.generate_sync_message(n1, init_sync_state())
        assert s1['lastSentHeads'] == get_heads(n1)
        n2, s2, _ = A.receive_sync_message(n2, init_sync_state(), m1)
        s2, m2 = A.generate_sync_message(n2, s2)
        assert m2 is None

    def test_offer_all_changes_from_nothing(self):
        n1, n2 = A.init(), A.init()
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'n': []}))
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d['n'].append(i))
        assert not A.equals(n1, n2)
        n1, n2, _, _ = sync(n1, n2)
        assert A.equals(n1, n2)

    def test_sync_with_prior_state(self):
        n1, n2 = A.init(), A.init()
        s1 = s2 = None
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        for i in range(5, 10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        assert not A.equals(n1, n2)
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert A.equals(n1, n2)

    def test_incremental_single_change_messages(self):
        n1, n2 = A.init(), A.init()
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'items': []}))
        n1, n2, s1, s2 = sync(n1, n2)
        for item in ('x', 'y', 'z'):
            n1 = A.change(n1, {'time': 0},
                          lambda d, item=item: d['items'].append(item))
            s1, message = A.generate_sync_message(n1, s1)
            assert len(decode_sync_message(message)['changes']) == 1


class TestDiverged:
    def test_diverged_no_prior_state(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, _, _ = sync(n1, n2)
        for i in range(10, 15):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        for i in range(15, 18):
            n2 = A.change(n2, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        assert not A.equals(n1, n2)
        n1, n2, _, _ = sync(n1, n2)
        assert get_heads(n1) == get_heads(n2)
        assert A.equals(n1, n2)

    def test_diverged_with_prior_state_round_tripped(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        for i in range(10, 15):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        for i in range(15, 18):
            n2 = A.change(n2, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        assert not A.equals(n1, n2)
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == get_heads(n2)
        assert A.equals(n1, n2)

    def test_nonempty_state_after_sync(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        assert s1['sharedHeads'] == get_heads(n1)
        assert s2['sharedHeads'] == get_heads(n1)

    def test_resync_after_crash_with_data_loss(self):
        """(ref sync_test.js crash-recovery scenario)"""
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)

        # Save a copy of n2 as "r" to simulate crash recovery from stale state
        r, r_sync_state = A.clone(n2), s2
        for i in range(3, 6):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == get_heads(n2)

        for i in range(6, 9):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        s1 = decode_sync_state(encode_sync_state(s1))
        r_sync_state = decode_sync_state(encode_sync_state(r_sync_state))

        assert get_heads(n1) != get_heads(r)
        assert A.equals(n1, {'x': 8})
        assert A.equals(r, {'x': 2})
        n1, r, s1, r_sync_state = sync(n1, r, s1, r_sync_state)
        assert get_heads(n1) == get_heads(r)
        assert A.equals(n1, r)

    def test_data_loss_without_disconnect(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        assert get_heads(n1) == get_heads(n2)

        n2_after_loss = A.init('89abcdef')
        n1, n2, s1, s2 = sync(n1, n2_after_loss, s1, init_sync_state())
        assert get_heads(n1) == get_heads(n2)
        assert A.equals(n1, n2)

    def test_changes_concurrent_to_last_sync_heads(self):
        n1, n2, n3 = A.init('01234567'), A.init('89abcdef'), A.init('fedcba98')
        s12, s21 = init_sync_state(), init_sync_state()
        s23, s32 = init_sync_state(), init_sync_state()

        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 1}))
        n1, n2, s12, s21 = sync(n1, n2, s12, s21)
        n2, n3, s23, s32 = sync(n2, n3, s23, s32)

        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 2}))
        n1, n2, s12, s21 = sync(n1, n2, s12, s21)

        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 3}))
        n2 = A.change(n2, {'time': 0}, lambda d: d.update({'x': 4}))
        n3 = A.change(n3, {'time': 0}, lambda d: d.update({'x': 5}))

        change = A.get_last_local_change(n3)
        n2, _ = A.apply_changes(n2, [change])
        n1, n2, s12, s21 = sync(n1, n2, s12, s21)
        assert get_heads(n1) == get_heads(n2)
        assert A.equals(n1, n2)

    def test_branching_and_merging_histories(self):
        n1, n2, n3 = A.init('01234567'), A.init('89abcdef'), A.init('fedcba98')
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 0}))
        n2, _ = A.apply_changes(n2, [A.get_last_local_change(n1)])
        n3, _ = A.apply_changes(n3, [A.get_last_local_change(n1)])
        n3 = A.change(n3, {'time': 0}, lambda d: d.update({'x': 1}))

        for i in range(1, 20):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'n1': i}))
            n2 = A.change(n2, {'time': 0}, lambda d, i=i: d.update({'n2': i}))
            change1 = A.get_last_local_change(n1)
            change2 = A.get_last_local_change(n2)
            n1, _ = A.apply_changes(n1, [change2])
            n2, _ = A.apply_changes(n2, [change1])

        n1, n2, s1, s2 = sync(n1, n2)
        # n3's change is concurrent to the last sync heads: slow code path
        n2, _ = A.apply_changes(n2, [A.get_last_local_change(n3)])
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'n1': 'final'}))
        n2 = A.change(n2, {'time': 0}, lambda d: d.update({'n2': 'final'}))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == get_heads(n2)
        assert A.equals(n1, n2)


class TestFalsePositives:
    def test_false_positive_head(self):
        """Brute-force search for a Bloom-filter false positive; deterministic
        hashes via fixed actorIds and {time: 0} (ref sync_test.js:453-486)."""
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, _, _ = sync(n1, n2)

        # Search for a false positive: n2's new change must collide with the
        # Bloom filter built over n1's new change
        false_positive = None
        for i in range(1000):
            n1up = A.change(A.clone(n1, '01234567'), {'time': 0},
                            lambda d, i=i: d.update({'x': f'final @ n1, attempt {i}'}))
            n2up = A.change(A.clone(n2, '89abcdef'), {'time': 0},
                            lambda d, i=i: d.update({'x': f'final @ n2, attempt {i}'}))
            n1hash = get_heads(n1up)[0]
            n2hash = get_heads(n2up)[0]
            if BloomFilter([n1hash]).contains_hash(n2hash):
                false_positive = (n1up, n2up)
                break
        assert false_positive is not None, 'no false positive found in 1000 attempts'
        n1up, n2up = false_positive
        # Sync must still converge despite the false positive (the missing
        # change is requested explicitly via `need`)
        n1f, n2f, _, _ = sync(n1up, n2up)
        assert get_heads(n1f) == get_heads(n2f)
        assert A.equals(n1f, n2f)


class TestBloomFilter:
    def test_round_trip(self):
        hashes = [decode_change_meta(c, True)['hash'] for c in
                  A.get_all_changes(A.from_({'a': 1}, 'abcdef'))]
        bloom = BloomFilter(hashes)
        decoded = BloomFilter(bloom.bytes)
        assert decoded.num_entries == len(hashes)
        assert decoded.num_bits_per_entry == 10
        assert decoded.num_probes == 7
        for h in hashes:
            assert decoded.contains_hash(h)

    def test_empty_filter(self):
        bloom = BloomFilter([])
        assert bloom.bytes == b''
        assert not bloom.contains_hash('00' * 32)

    def test_false_positive_rate_sane(self):
        import hashlib
        member = [hashlib.sha256(f'm{i}'.encode()).hexdigest() for i in range(100)]
        others = [hashlib.sha256(f'o{i}'.encode()).hexdigest() for i in range(1000)]
        bloom = BloomFilter(member)
        assert all(bloom.contains_hash(h) for h in member)
        fp = sum(1 for h in others if bloom.contains_hash(h))
        assert fp < 50  # ~1% expected; allow generous margin


class TestSyncStateEncoding:
    def test_sync_state_round_trip(self):
        doc = A.from_({'a': 1}, 'abcdef')
        state = init_sync_state()
        state['sharedHeads'] = get_heads(doc)
        state['lastSentHeads'] = get_heads(doc)
        decoded = decode_sync_state(encode_sync_state(state))
        assert decoded['sharedHeads'] == get_heads(doc)
        assert decoded['lastSentHeads'] == []  # ephemeral parts not persisted

    def test_peer_state_type_check(self):
        with pytest.raises(ValueError, match='Unexpected record type'):
            decode_sync_state(bytes([0x42, 0]))
        with pytest.raises(ValueError, match='Unexpected message type'):
            decode_sync_message(bytes([0x43, 0]))


class TestSyncExchangeDetails:
    """Message-level exchange assertions (ref sync_test.js:127-273)."""

    def test_no_messages_once_synced(self):
        n1, n2 = A.init('abc123'), A.init('def456')
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        for i in range(5):
            n2 = A.change(n2, {'time': 0}, lambda d, i=i: d.update({'y': i}))

        s1, message = A.generate_sync_message(n1, s1)
        n2, s2, patch = A.receive_sync_message(n2, s2, message)
        s2, message = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(message)['changes']) == 5
        assert patch is None

        n1, s1, patch = A.receive_sync_message(n1, s1, message)
        s1, message = A.generate_sync_message(n1, s1)
        assert len(decode_sync_message(message)['changes']) == 5
        assert patch['diffs']['props'] == {
            'y': {'5@def456': {'type': 'value', 'value': 4,
                               'datatype': 'int'}}}

        n2, s2, patch = A.receive_sync_message(n2, s2, message)
        s2, message = A.generate_sync_message(n2, s2)
        assert patch['diffs']['props'] == {
            'x': {'5@abc123': {'type': 'value', 'value': 4,
                               'datatype': 'int'}}}

        n1, s1, patch = A.receive_sync_message(n1, s1, message)
        s1, message = A.generate_sync_message(n1, s1)
        assert message is None
        assert patch is None
        s2, message = A.generate_sync_message(n2, s2)
        assert message is None

    def test_simultaneous_messages_during_synchronization(self):
        n1, n2 = A.init('abc123'), A.init('def456')
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        for i in range(5):
            n2 = A.change(n2, {'time': 0}, lambda d, i=i: d.update({'y': i}))
        head1, head2 = get_heads(n1)[0], get_heads(n2)[0]

        s1, msg1to2 = A.generate_sync_message(n1, s1)
        s2, msg2to1 = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(msg1to2)['changes']) == 0
        assert len(decode_sync_message(msg1to2)['have'][0]['lastSync']) == 0
        assert len(decode_sync_message(msg2to1)['changes']) == 0
        assert len(decode_sync_message(msg2to1)['have'][0]['lastSync']) == 0

        n1, s1, patch1 = A.receive_sync_message(n1, s1, msg2to1)
        assert patch1 is None
        n2, s2, patch2 = A.receive_sync_message(n2, s2, msg1to2)
        assert patch2 is None

        s1, msg1to2 = A.generate_sync_message(n1, s1)
        assert len(decode_sync_message(msg1to2)['changes']) == 5
        s2, msg2to1 = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(msg2to1)['changes']) == 5

        n1, s1, patch1 = A.receive_sync_message(n1, s1, msg2to1)
        assert Backend.get_missing_deps(
            A.Frontend.get_backend_state(n1)) == []
        assert patch1 is not None
        assert dict(n1) == {'x': 4, 'y': 4}

        n2, s2, patch2 = A.receive_sync_message(n2, s2, msg1to2)
        assert Backend.get_missing_deps(
            A.Frontend.get_backend_state(n2)) == []
        assert patch2 is not None
        assert dict(n2) == {'x': 4, 'y': 4}

        s1, msg1to2 = A.generate_sync_message(n1, s1)
        assert len(decode_sync_message(msg1to2)['changes']) == 0
        s2, msg2to1 = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(msg2to1)['changes']) == 0

        n1, s1, patch1 = A.receive_sync_message(n1, s1, msg2to1)
        n2, s2, patch2 = A.receive_sync_message(n2, s2, msg1to2)
        assert s1['sharedHeads'] == sorted([head1, head2])
        assert s2['sharedHeads'] == sorted([head1, head2])
        assert patch1 is None
        assert patch2 is None

        s1, msg1to2 = A.generate_sync_message(n1, s1)
        s2, msg2to1 = A.generate_sync_message(n2, s2)
        assert msg1to2 is None
        assert msg2to1 is None

        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 5}))
        s1, msg1to2 = A.generate_sync_message(n1, s1)
        assert decode_sync_message(msg1to2)['have'][0]['lastSync'] == \
            sorted([head1, head2])

    def test_assumes_sent_changes_received_until_heard_otherwise(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        s1 = init_sync_state()
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'items': []}))
        n1, n2, s1, _s2 = sync(n1, n2, s1)

        for item in ('x', 'y', 'z'):
            n1 = A.change(n1, {'time': 0},
                          lambda d, item=item: d['items'].append(item))
            s1, message = A.generate_sync_message(n1, s1)
            assert len(decode_sync_message(message)['changes']) == 1

    def test_works_regardless_of_who_initiates(self):
        n1, n2 = A.init(), A.init()
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        for i in range(5, 10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        assert not A.equals(n1, n2)
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert A.equals(n1, n2)


class TestFalsePositiveDependency:
    """Bloom false positives on a dependency chain (ref sync_test.js:488-557).
    The brute-force search runs against OUR BloomFilter, which is bit-
    compatible with the reference's, so the same construction applies."""

    def _setup(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        i = 1
        while True:
            n1us1 = A.change(A.clone(n1, {'actorId': '01234567'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} @ n1'}))
            n2us1 = A.change(A.clone(n2, {'actorId': '89abcdef'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} @ n2'}))
            n1hash1 = get_heads(n1us1)[0]
            n2hash1 = get_heads(n2us1)[0]
            n1us2 = A.change(n1us1, {'time': 0},
                             lambda d: d.update({'x': 'final @ n1'}))
            n2us2 = A.change(n2us1, {'time': 0},
                             lambda d: d.update({'x': 'final @ n2'}))
            n1hash2 = get_heads(n1us2)[0]
            n2hash2 = get_heads(n2us2)[0]
            if BloomFilter([n1hash1, n1hash2]).contains_hash(n2hash1):
                return n1us2, n2us2, s1, s2, n1hash2, n2hash2
            i += 1

    def test_sync_two_nodes_without_connection_reset(self):
        n1, n2, s1, s2, n1hash2, n2hash2 = self._setup()
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == sorted([n1hash2, n2hash2])
        assert get_heads(n2) == sorted([n1hash2, n2hash2])

    def test_sync_two_nodes_with_connection_reset(self):
        n1, n2, s1, s2, n1hash2, n2hash2 = self._setup()
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == sorted([n1hash2, n2hash2])
        assert get_heads(n2) == sorted([n1hash2, n2hash2])

    def test_sync_three_nodes(self):
        n1, n2, s1, s2, n1hash2, n2hash2 = self._setup()
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))

        s1, m1 = A.generate_sync_message(n1, s1)
        s2, m2 = A.generate_sync_message(n2, s2)
        n1, s1, _ = A.receive_sync_message(n1, s1, m2)
        n2, s2, _ = A.receive_sync_message(n2, s2, m1)

        s1, m1 = A.generate_sync_message(n1, s1)
        s2, m2 = A.generate_sync_message(n2, s2)
        n1, s1, _ = A.receive_sync_message(n1, s1, m2)
        n2, s2, _ = A.receive_sync_message(n2, s2, m1)
        assert len(decode_sync_message(m1)['changes']) == 2
        assert len(decode_sync_message(m2)['changes']) == 1

        n3 = A.init('fedcba98')
        s13, s31 = init_sync_state(), init_sync_state()
        n1, n3, s13, s31 = sync(n1, n3, s13, s31)
        assert get_heads(n1) == [n1hash2]
        assert get_heads(n3) == [n1hash2]


class TestFalsePositiveChains:
    """ref sync_test.js:559-673"""

    def test_false_positive_depending_on_true_negative(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        i = 1
        while True:
            n1us1 = A.change(A.clone(n1, {'actorId': '01234567'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} @ n1'}))
            n2us1 = A.change(A.clone(n2, {'actorId': '89abcdef'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} @ n2'}))
            n1hash1 = get_heads(n1us1)[0]
            n1us2 = A.change(n1us1, {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i + 1} @ n1'}))
            n2us2 = A.change(n2us1, {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i + 1} @ n2'}))
            n1hash2 = get_heads(n1us2)[0]
            n2hash2 = get_heads(n2us2)[0]
            n1up3 = A.change(n1us2, {'time': 0},
                             lambda d: d.update({'x': 'final @ n1'}))
            n2up3 = A.change(n2us2, {'time': 0},
                             lambda d: d.update({'x': 'final @ n2'}))
            n1hash3 = get_heads(n1up3)[0]
            n2hash3 = get_heads(n2up3)[0]
            if BloomFilter([n1hash1, n1hash2, n1hash3]).contains_hash(n2hash2):
                n1, n2 = n1up3, n2up3
                break
            i += 1
        both_heads = sorted([n1hash3, n2hash3])
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == both_heads
        assert get_heads(n2) == both_heads

    def test_chains_of_false_positives(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(5):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 5}))
        i = 1
        while True:
            n2us1 = A.change(A.clone(n2, {'actorId': '89abcdef'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} @ n2'}))
            if BloomFilter(get_heads(n1)).contains_hash(get_heads(n2us1)[0]):
                n2 = n2us1
                break
            i += 1
        i = 1
        while True:
            n2us2 = A.change(A.clone(n2, {'actorId': '89abcdef'}),
                             {'time': 0},
                             lambda d, i=i: d.update({'x': f'{i} again'}))
            if BloomFilter(get_heads(n1)).contains_hash(get_heads(n2us2)[0]):
                n2 = n2us2
                break
            i += 1
        n2 = A.change(n2, {'time': 0}, lambda d: d.update({'x': 'final @ n2'}))
        all_heads = sorted(get_heads(n1) + get_heads(n2))
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        n1, n2, s1, s2 = sync(n1, n2, s1, s2)
        assert get_heads(n1) == all_heads
        assert get_heads(n2) == all_heads

    def test_false_positive_hash_explicitly_requested(self):
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        for i in range(10):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        i = 1
        while True:
            n1up = A.change(A.clone(n1, {'actorId': '01234567'}),
                            {'time': 0},
                            lambda d, i=i: d.update({'x': f'{i} @ n1'}))
            n2up = A.change(A.clone(n2, {'actorId': '89abcdef'}),
                            {'time': 0},
                            lambda d, i=i: d.update({'x': f'{i} @ n2'}))
            if BloomFilter(get_heads(n1up)).contains_hash(get_heads(n2up)[0]):
                n1, n2 = n1up, n2up
                break
            i += 1

        s1, message = A.generate_sync_message(n1, s1)
        assert len(decode_sync_message(message)['changes']) == 0

        n2, s2, _ = A.receive_sync_message(n2, s2, message)
        s2, message = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(message)['changes']) == 0

        n1, s1, _ = A.receive_sync_message(n1, s1, message)
        s1, message = A.generate_sync_message(n1, s1)
        assert decode_sync_message(message)['need'] == get_heads(n2)

        n2, s2, _ = A.receive_sync_message(n2, s2, message)
        s2, message = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(message)['changes']) == 1

        n1, s1, _ = A.receive_sync_message(n1, s1, message)
        assert get_heads(n1) == get_heads(n2)


class TestProtocolFeatures:
    """ref sync_test.js:676-830"""

    def test_multiple_bloom_filters(self):
        from automerge_tpu.backend import encode_sync_message
        n1, n2, n3 = A.init('01234567'), A.init('89abcdef'), A.init('76543210')
        s13, s31 = init_sync_state(), init_sync_state()
        s32, s23 = init_sync_state(), init_sync_state()
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, _, _ = sync(n1, n2)
        n1, n3, s13, s31 = sync(n1, n3)
        n3, n2, s32, s23 = sync(n3, n2)
        for i in range(2):
            n1 = A.change(n1, {'time': 0},
                          lambda d, i=i: d.update({'x': f'{i} @ n1'}))
        for i in range(2):
            n2 = A.change(n2, {'time': 0},
                          lambda d, i=i: d.update({'x': f'{i} @ n2'}))
        n1, _ = A.apply_changes(n1, A.get_all_changes(n2))
        n2, _ = A.apply_changes(n2, A.get_all_changes(n1))
        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': '3 @ n1'}))
        n2 = A.change(n2, {'time': 0}, lambda d: d.update({'x': '3 @ n2'}))
        for i in range(3):
            n3 = A.change(n3, {'time': 0},
                          lambda d, i=i: d.update({'x': f'{i} @ n3'}))
        n1c3, n2c3, n3c3 = get_heads(n1)[0], get_heads(n2)[0], get_heads(n3)[0]
        s13 = decode_sync_state(encode_sync_state(s13))
        s31 = decode_sync_state(encode_sync_state(s31))
        s23 = decode_sync_state(encode_sync_state(s23))
        s32 = decode_sync_state(encode_sync_state(s32))

        s13, message1 = A.generate_sync_message(n1, s13)
        assert len(decode_sync_message(message1)['changes']) == 0
        n3, s31, _ = A.receive_sync_message(n3, s31, message1)
        s31, message3 = A.generate_sync_message(n3, s31)
        assert len(decode_sync_message(message3)['changes']) == 3
        n1, s13, _ = A.receive_sync_message(n1, s13, message3)

        s32, message3 = A.generate_sync_message(n3, s32)
        modified = decode_sync_message(message3)
        modified['have'].append(decode_sync_message(message1)['have'][0])
        assert len(modified['changes']) == 0
        n2, s23, _ = A.receive_sync_message(
            n2, s23, encode_sync_message(modified))

        s23, message2 = A.generate_sync_message(n2, s23)
        assert len(decode_sync_message(message2)['changes']) == 1
        n3, s32, _ = A.receive_sync_message(n3, s32, message2)

        s13, message1 = A.generate_sync_message(n1, s13)
        assert len(decode_sync_message(message1)['changes']) == 5
        n3, s31, _ = A.receive_sync_message(n3, s31, message1)
        assert get_heads(n3) == sorted([n1c3, n2c3, n3c3])

    def test_any_change_can_be_requested(self):
        from automerge_tpu.backend import encode_sync_message
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        last_sync = get_heads(n1)
        for i in range(3, 6):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n1, n2, s1, s2 = sync(n1, n2)
        s1['lastSentHeads'] = []
        s1, message = A.generate_sync_message(n1, s1)
        mod = decode_sync_message(message)
        mod['need'] = last_sync
        n2, s2, _ = A.receive_sync_message(n2, s2, encode_sync_message(mod))
        s2, message = A.generate_sync_message(n2, s2)
        assert len(decode_sync_message(message)['changes']) == 1
        assert A.decode_change(
            decode_sync_message(message)['changes'][0])['hash'] == last_sync[0]

    def test_ignores_requests_for_nonexistent_change(self):
        from automerge_tpu.backend import encode_sync_message
        n1, n2 = A.init('01234567'), A.init('89abcdef')
        s1, s2 = init_sync_state(), init_sync_state()
        for i in range(3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        n2, _ = A.apply_changes(n2, A.get_all_changes(n1))
        s1, message = A.generate_sync_message(n1, s1)
        mod = decode_sync_message(message)
        mod['need'] = ['00' * 32]
        n2, s2, _ = A.receive_sync_message(n2, s2, encode_sync_message(mod))
        s2, message = A.generate_sync_message(n2, s2)
        assert message is None

    def test_subset_of_changes_can_be_sent(self):
        from automerge_tpu.backend import encode_sync_message
        n1, n2, n3 = A.init('01234567'), A.init('89abcdef'), A.init('76543210')
        s1, s2 = init_sync_state(), init_sync_state()

        n1 = A.change(n1, {'time': 0}, lambda d: d.update({'x': 0}))
        n3 = A.merge(n3, n1)
        for i in range(1, 3):
            n1 = A.change(n1, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        for i in range(3, 5):
            n3 = A.change(n3, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        c2, c4 = get_heads(n1)[0], get_heads(n3)[0]
        n2 = A.merge(n2, n3)

        n1, n2, s1, s2 = sync(n1, n2)
        s1 = decode_sync_state(encode_sync_state(s1))
        s2 = decode_sync_state(encode_sync_state(s2))
        assert s1['sharedHeads'] == sorted([c2, c4])
        assert s2['sharedHeads'] == sorted([c2, c4])

        n3 = A.change(n3, {'time': 0}, lambda d: d.update({'x': 5}))
        change5 = A.get_last_local_change(n3)
        n3 = A.change(n3, {'time': 0}, lambda d: d.update({'x': 6}))
        change6 = A.get_last_local_change(n3)
        c6 = get_heads(n3)[0]
        for i in range(7, 9):
            n3 = A.change(n3, {'time': 0}, lambda d, i=i: d.update({'x': i}))
        c8 = get_heads(n3)[0]
        n2 = A.merge(n2, n3)

        s1, msg = A.generate_sync_message(n1, s1)
        n2, s2, _ = A.receive_sync_message(n2, s2, msg)
        s2, msg = A.generate_sync_message(n2, s2)
        decoded = decode_sync_message(msg)
        decoded['changes'] = [change5, change6]
        msg = encode_sync_message(decoded)
        s2['sentHashes'] = {
            decode_change_meta(change5, True)['hash'],
            decode_change_meta(change6, True)['hash']}
        n1, s1, _ = A.receive_sync_message(n1, s1, msg)
        assert s1['sharedHeads'] == sorted([c2, c6])

        s1, msg = A.generate_sync_message(n1, s1)
        n2, s2, _ = A.receive_sync_message(n2, s2, msg)
        assert decode_sync_message(msg)['need'] == [c8]
        assert decode_sync_message(msg)['have'][0]['lastSync'] == \
            sorted([c2, c6])
        assert s1['sharedHeads'] == sorted([c2, c6])
        assert s2['sharedHeads'] == sorted([c2, c6])

        s2, msg = A.generate_sync_message(n2, s2)
        n1, s1, _ = A.receive_sync_message(n1, s1, msg)
        assert len(decode_sync_message(msg)['changes']) == 2
        assert s1['sharedHeads'] == sorted([c2, c8])
