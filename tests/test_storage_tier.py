"""Mmap-backed MainStore + cost-based tiering (ISSUE-15): the on-disk
segment arena (park -> discard-churn -> vacuum -> revive cycles, segment
swap under concurrently-held views, crash/kill recovery mid-vacuum), the
head-prefix probe short-circuit, the cost model replacing dead_fraction
triggers (brownout stage as pressure input), the clock auto-demote
policy, and mixed-batch sync routing through the frontier index.
"""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu import native                                  # noqa: E402
from automerge_tpu.columnar import DocChunkView, encode_change    # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend          # noqa: E402
from automerge_tpu.fleet.backend import DocFleet, init_docs       # noqa: E402
from automerge_tpu.fleet.segment import SegmentArena              # noqa: E402
from automerge_tpu.fleet.storage import MainStore, StorageEngine  # noqa: E402
from automerge_tpu.fleet.tiering import (                         # noqa: E402
    ClockDemote, CostModel, TieringController, tiering_stats)


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _workload(fleet, n, rounds=2):
    handles = init_docs(n, fleet)
    for r in range(rounds):
        per_doc = [[_change(f'{d:04x}' * 4, r + 1, r + 1,
                            fleet_backend.get_heads(handles[d]),
                            f'k{r}', d * 10 + r)]
                   for d in range(n)]
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
    return handles


class TestDiskArena:
    """The tentpole mechanics: chunk bytes on mmap'd segment files under
    the RAM-resident causal index."""

    def test_park_discard_vacuum_revive_park_cycles(self, tmp_path):
        fleet = DocFleet()
        eng = StorageEngine(fleet, path=str(tmp_path / 'arena'))
        handles = _workload(fleet, 12)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        assert all(i is not None for i in ids)
        for cycle in range(3):
            # churn: discard a third, vacuum underneath held ids
            eng.discard(ids[:4])
            assert eng.vacuums >= cycle  # dead_fraction policy may fire
            eng.vacuum_now()
            for i, save in zip(ids[4:], saves[4:]):
                assert bytes(eng.chunk(i)) == save
                assert eng.heads(i)
            # revive the rest, verify byte identity, re-park
            back = eng.revive(ids[4:])
            assert [bytes(h['state'].save()) for h in back] == saves[4:]
            assert len(eng.main) == 0
            new_ids = eng.park(back)
            assert all(i is not None for i in new_ids)
            # re-admit the first third for the next cycle
            front = eng.revive(new_ids[:0]) if False else None  # noqa
            restored = eng.ingest_chunks(saves[:4])
            ids = restored + new_ids
            saves = saves[:4] + saves[4:]

    def test_chunk_reads_are_zero_copy_views(self, tmp_path):
        fleet = DocFleet()
        eng = StorageEngine(fleet, path=str(tmp_path / 'arena'))
        handles = _workload(fleet, 3)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        view = eng.chunk(ids[0])
        assert isinstance(view, memoryview)
        assert bytes(view) == saves[0]
        # DocChunkView parses the view in place (no chunk copy)
        dcv = DocChunkView(view)
        assert sorted(dcv.heads) == eng.heads(ids[0])
        if native.available():
            got = native.extract_changes([view])
            want = native.extract_changes([saves[0]])
            assert got == want and got[0] is not None

    def test_held_view_survives_segment_swap(self, tmp_path):
        fleet = DocFleet()
        eng = StorageEngine(fleet, path=str(tmp_path / 'arena'),
                            vacuum_dead_fraction=None)
        handles = _workload(fleet, 10)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        held = eng.chunk(ids[7])
        held_want = saves[7]
        eng.discard(ids[:5])
        eng.vacuum_now()          # segment rewrite + atomic swap
        # the old epoch's files are unlinked, but the exported view pins
        # its mapping: reads through it stay byte-identical
        assert bytes(held) == held_want
        # and fresh reads address the NEW epoch correctly
        assert bytes(eng.chunk(ids[7])) == held_want
        del held
        eng.vacuum_now()

    def test_segment_rollover_and_reopen(self, tmp_path):
        fleet = DocFleet()
        root = str(tmp_path / 'arena')
        eng = StorageEngine(fleet, path=root, segment_bytes=1 << 10)
        handles = _workload(fleet, 16)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        assert len(eng.main._arena.segments) > 1   # rolled over
        for i, save in zip(ids, saves):
            assert bytes(eng.chunk(i)) == save
        eng.main.sync()
        eng2 = StorageEngine.open(root, segment_bytes=1 << 10)
        assert sorted(eng2._row_of) == sorted(ids)
        for i, save in zip(ids, saves):
            assert bytes(eng2.chunk(i)) == save
            assert eng2.heads(i) == eng.heads(i)
            assert eng2.clock(i) == eng.clock(i)

    @pytest.mark.parametrize('point', ['pre_commit', 'post_manifest'])
    def test_crash_mid_vacuum_recovers_byte_identical(self, tmp_path,
                                                      point):
        fleet = DocFleet()
        root = str(tmp_path / 'arena')
        eng = StorageEngine(fleet, path=root, vacuum_dead_fraction=None)
        handles = _workload(fleet, 10)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        eng.discard(ids[:4])
        eng.main.sync()
        eng.main._arena.fault_point = point
        with pytest.raises(RuntimeError, match='injected arena fault'):
            eng.vacuum_now()
        # pre_commit: the OLD epoch is authoritative; post_manifest: the
        # NEW one is. Either way recovery is byte-identical and complete.
        eng2 = StorageEngine.open(root)
        assert sorted(eng2._row_of) == ids[4:]
        for i in ids[4:]:
            assert bytes(eng2.chunk(i)) == saves[i]
            assert eng2.needs_sync(i, []) is True

    def test_kill_mid_vacuum_recovers(self, tmp_path):
        """Hard kill (os._exit inside the swap window) in a subprocess;
        the parent recovers the arena byte-identically."""
        root = str(tmp_path / 'arena')
        script = f'''
import sys; sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from tests.test_storage_tier import _workload
from automerge_tpu.fleet.backend import DocFleet
from automerge_tpu.fleet.storage import StorageEngine
fleet = DocFleet()
eng = StorageEngine(fleet, path={root!r}, vacuum_dead_fraction=None)
handles = _workload(fleet, 8)
saves = [bytes(h['state'].save()) for h in handles]
import json, pathlib
pathlib.Path({root!r} + '.expect').write_bytes(b''.join(saves[4:]))
ids = eng.park(handles)
eng.discard(ids[:4])
eng.main.sync()
eng.main._arena.fault_point = 'exit:post_manifest'
eng.vacuum_now()           # never returns
'''
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        proc = subprocess.run([sys.executable, '-c', script], env=env,
                              capture_output=True, timeout=300)
        assert proc.returncode == 71, proc.stderr.decode()[-2000:]
        eng2 = StorageEngine.open(root)
        assert len(eng2._row_of) == 4
        got = b''.join(bytes(eng2.chunk(i)) for i in sorted(eng2._row_of))
        with open(root + '.expect', 'rb') as f:
            assert got == f.read()

    def test_torn_append_tail_dropped(self, tmp_path):
        root = str(tmp_path / 'arena')
        arena = SegmentArena(root)
        addr = [arena.append(i, b'payload-%d' % i * 20) for i in range(6)]
        arena.sync()
        seg_path = arena.segments[-1].path
        size = os.path.getsize(seg_path)
        arena.close()
        with open(seg_path, 'r+b') as f:
            f.truncate(size - 5)            # torn mid-frame
        arena2, records = SegmentArena.open(root)
        assert sorted(records) == list(range(5))
        for i in range(5):
            seg, off, ln = records[i]
            assert bytes(arena2.view(seg, off, ln)) == b'payload-%d' % i * 20
        # and the arena appends cleanly past the truncated tail
        seg, off, ln = arena2.append(99, b'fresh')
        assert bytes(arena2.view(seg, off, ln)) == b'fresh'
        del addr

    def test_repark_preserves_ids_on_disk(self, tmp_path):
        fleet = DocFleet()
        root = str(tmp_path / 'arena')
        eng = StorageEngine(fleet, path=root)
        handles = _workload(fleet, 4)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        back = eng.revive(ids)
        eng.repark(back, ids)
        assert sorted(eng._row_of) == sorted(ids)
        eng.main.sync()
        # the arena frames carry the ORIGINAL ids: recovery agrees
        eng2 = StorageEngine.open(root)
        assert sorted(eng2._row_of) == sorted(ids)
        for i, save in zip(ids, saves):
            assert bytes(eng2.chunk(i)) == save

    def test_resident_vs_disk_split(self, tmp_path):
        fleet = DocFleet()
        eng = StorageEngine(fleet, path=str(tmp_path / 'arena'))
        handles = _workload(fleet, 32)
        eng.park(handles)
        stats = eng.memory_stats()
        assert stats['n_docs'] == 32
        assert stats['disk_bytes'] >= stats['chunk_bytes'] > 0
        # the chunk bytes are NOT resident: RSS pays the causal index
        assert stats['resident_bytes'] < stats['chunk_bytes'] + \
            stats['overhead_bytes']
        assert stats['resident_per_doc'] < 512, stats


class TestPrefixShortCircuit:
    """contains_head satellite: the 8-byte prefix set past the row
    threshold keeps miss probes O(1) and stays correct through discard
    churn and vacuum."""

    def test_probe_correct_above_threshold(self, monkeypatch):
        monkeypatch.setattr(MainStore, 'PREFIX_MIN_ROWS', 8)
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 12)
        heads = [list(h['state'].heads) for h in handles]
        ids = eng.park(handles)
        assert eng.main._head_prefixes is None
        # misses short-circuit through the set; hits still row-scan
        assert not eng.contains_head(ids[0], 'ee' * 32)
        assert eng.main._head_prefixes is not None
        for i, hs in zip(ids, heads):
            assert eng.contains_head(i, hs[0])
            assert not eng.contains_head(i, heads[(ids.index(i) + 1)
                                                  % len(ids)][0]) or \
                hs[0] == heads[(ids.index(i) + 1) % len(ids)][0]

    def test_prefixes_survive_churn_and_vacuum(self, monkeypatch):
        monkeypatch.setattr(MainStore, 'PREFIX_MIN_ROWS', 8)
        fleet = DocFleet()
        eng = StorageEngine(fleet, vacuum_dead_fraction=None)
        handles = _workload(fleet, 16)
        heads = [list(h['state'].heads) for h in handles]
        ids = eng.park(handles)
        assert not eng.contains_head(ids[-1], 'aa' * 32)   # build set
        eng.discard(ids[:8])
        # stale prefixes from discarded rows only fall through to the
        # exact scan — never a wrong answer
        for i, hs in zip(ids[8:], heads[8:]):
            assert eng.contains_head(i, hs[0])
        eng.vacuum_now()
        assert eng.main._head_prefixes is None             # rebuilt lazily
        for i, hs in zip(ids[8:], heads[8:]):
            assert eng.contains_head(i, hs[0])
        assert not eng.contains_head(ids[8], 'bb' * 32)

    def test_additions_maintain_built_set(self, monkeypatch):
        monkeypatch.setattr(MainStore, 'PREFIX_MIN_ROWS', 4)
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 6)
        ids = eng.park(handles)
        assert not eng.contains_head(ids[0], 'cc' * 32)    # build set
        more = _workload(fleet, 3)
        heads = [list(h['state'].heads) for h in more]
        more_ids = eng.park(more)
        for i, hs in zip(more_ids, heads):
            assert eng.contains_head(i, hs[0])


class _FakeDurable:
    def __init__(self):
        self.debt = {'bytes': 0, 'records': 0}
        self.compactions = 0

    def replay_debt(self):
        return dict(self.debt)

    def maybe_compact(self, force=False):
        self.compactions += 1
        self.debt = {'bytes': 0, 'records': 0}
        return True


class TestCostModel:
    """The dead_fraction byte trigger replaced by the write-amp vs
    read-latency vs replay-debt ledger, with brownout stage 2 as a
    pressure INPUT instead of a hard override."""

    def _churned_engine(self, n=16, discard=12):
        fleet = DocFleet()
        eng = StorageEngine(fleet, vacuum_dead_fraction=None)
        handles = _workload(fleet, n)
        ids = eng.ingest_chunks([bytes(h['state'].save())
                                 for h in handles])
        eng.discard(ids[:discard])
        return eng, ids

    def test_vacuum_fires_when_garbage_dominates(self):
        model = CostModel(min_garbage_bytes=1)
        eng, ids = self._churned_engine()
        assert eng.main.garbage_bytes > eng.main.chunk_bytes
        assert model.vacuum_due(eng.main, stage=0)
        eng.cost_model = model
        assert eng._maybe_vacuum()
        assert eng.vacuums == 1
        # post-vacuum: no garbage, model idles
        assert not model.vacuum_due(eng.main, stage=0)

    def test_vacuum_defers_under_brownout_stage2(self):
        model = CostModel(min_garbage_bytes=1, stage_write_penalty=1000.0)
        eng, _ids = self._churned_engine()
        before = tiering_stats()['tiering_deferred']
        assert model.vacuum_due(eng.main, stage=0)
        assert not model.vacuum_due(eng.main, stage=2)   # pressure defers
        assert tiering_stats()['tiering_deferred'] == before + 1

    def test_vacuum_still_fires_under_pressure_when_debt_overwhelms(self):
        # stage 2 raises the bar; it does not close the gate
        model = CostModel(min_garbage_bytes=1, stage_write_penalty=0.5)
        eng, _ids = self._churned_engine(n=16, discard=15)
        assert model.vacuum_due(eng.main, stage=2)

    def test_compact_decision_weighs_replay_debt(self):
        model = CostModel(min_replay_bytes=1024)
        dur = _FakeDurable()
        dur.debt = {'bytes': 512, 'records': 4}
        assert not model.compact_due(dur, stage=0)       # under floor
        dur.debt = {'bytes': 1 << 20, 'records': 5000}
        assert model.compact_due(dur, stage=0)
        # pressure defers the same debt...
        model2 = CostModel(min_replay_bytes=1024, stage_write_penalty=50.0,
                           replay_record_cost=0.0)
        assert not model2.compact_due(dur, stage=2)
        # ...until the record term overwhelms it
        dur.debt = {'bytes': 1 << 20, 'records': 10_000_000}
        model3 = CostModel(min_replay_bytes=1024, stage_write_penalty=50.0)
        assert model3.compact_due(dur, stage=2)


class TestClockDemote:
    """Auto-demote: the clock hand feeds StorageEngine.park with zero
    manual park calls; touched docs get their second chance."""

    def test_demotes_cold_docs_under_pressure(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 12)
        resident = {'docs': 12}
        # synthetic watermark source: pressure until <= 4 docs live
        policy = ClockDemote(eng, budget_bytes=4,
                             source=lambda: resident['docs'], batch=4)
        policy.register(handles)
        hot = handles[:3]
        parked_total = []
        for _tick in range(8):
            policy.touch(hot)          # the request path keeps 3 docs hot
            parked = policy.tick()
            parked_total.extend(parked)
            resident['docs'] = 12 - len(parked_total)
            if resident['docs'] <= 4:
                break
        assert len(parked_total) >= 8
        assert len(eng.main) == len(parked_total)
        # the hot docs survived the sweeps
        assert all(not h.get('frozen') for h in hot)
        assert tiering_stats()['tiering_demoted_docs'] >= 8

    def test_no_pressure_no_demotion(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 4)
        policy = ClockDemote(eng, budget_bytes=100, source=lambda: 1)
        policy.register(handles)
        assert policy.tick() == []
        assert len(eng.main) == 0


class TestTieringController:
    def test_controller_replaces_threshold_and_drives_all_planes(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)           # default dead_fraction 0.5
        dur = _FakeDurable()
        dur.debt = {'bytes': 4 << 20, 'records': 10_000}
        ctrl = TieringController(
            engine=eng, durable=dur,
            model=CostModel(min_garbage_bytes=1, min_replay_bytes=1024))
        assert eng.vacuum_dead_fraction is None          # model owns it
        assert eng.cost_model is ctrl.model
        handles = _workload(fleet, 16)
        ids = eng.ingest_chunks([bytes(h['state'].save())
                                 for h in handles])
        # discard churn between ticks: the engine's own discard hook now
        # consults the model instead of dead_fraction
        eng.discard(ids[:12])
        out = ctrl.tick(stage=0)
        assert out['compacted'] and dur.compactions == 1
        assert eng.vacuums >= 1                          # model fired

    def test_service_pump_routes_through_controller(self):
        from automerge_tpu.service import DocService
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        ctrl = TieringController(engine=eng,
                                 model=CostModel(min_garbage_bytes=1))
        svc = DocService(fleet=fleet, tiering=ctrl)
        handles = _workload(fleet, 16)
        ids = eng.ingest_chunks([bytes(h['state'].save())
                                 for h in handles])
        for i in ids[:12]:
            eng.main.discard(eng._row_of.pop(i))
        assert eng.main.dead_fraction > 0.5
        svc.pump()
        assert eng.vacuums >= 1          # the pump's tick fired the model


class TestMixedBatchRouting:
    """Sync-driver satellite: one promoted host doc in a batch no longer
    reverts the round to dict probes — the fleet subset rides the
    hashindex, stragglers route classic, outputs byte-identical."""

    def _mixed_batch(self, fleet, n=4):
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        handles = _workload(fleet, n, rounds=2)
        # promote doc 0 to the host engine via a fleet-unsupported op
        big = encode_change({
            'actor': 'dd' * 16, 'seq': 1, 'startOp': CTR_LIMIT + 10,
            'time': 0, 'message': '', 'deps': list(handles[0]['heads']),
            'ops': [{'action': 'makeText', 'obj': '_root', 'key': 'deep',
                     'pred': []}]})
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [[big]] + [[] for _ in handles[1:]], mirror=False)
        assert not handles[0]['state'].is_fleet
        assert all(h['state'].is_fleet for h in handles[1:])
        return handles

    def test_generate_byte_identical_with_straggler(self):
        from automerge_tpu.backend import init_sync_state
        from automerge_tpu.fleet.hashindex import set_frontier_enabled
        from automerge_tpu.fleet.sync_driver import (
            _stats as sync_stats, generate_sync_messages_docs)
        fleet = DocFleet()
        handles = self._mixed_batch(fleet)
        fleet.frontier_index()
        states = [init_sync_state() for _ in handles]
        for h, s in zip(handles, states):
            s['theirHeads'] = list(h['heads'])
            s['theirHave'] = [{'lastSync': list(h['heads']), 'bloom': b''}]
            s['theirNeed'] = []
        members0 = sync_stats['sync_frontier_member_docs']
        strag0 = sync_stats['sync_frontier_straggler_docs']
        new_states, messages = generate_sync_messages_docs(
            handles, [dict(s) for s in states])
        # the fleet subset rode the index; the promoted doc went classic
        assert sync_stats['sync_frontier_member_docs'] == members0 + 3
        assert sync_stats['sync_frontier_straggler_docs'] == strag0 + 1
        prev = set_frontier_enabled(False)
        try:
            classic_states, classic_msgs = generate_sync_messages_docs(
                handles, [dict(s) for s in states])
        finally:
            set_frontier_enabled(prev)
        assert [None if m is None else bytes(m) for m in messages] == \
            [None if m is None else bytes(m) for m in classic_msgs]
        assert new_states == classic_states

    def test_receive_mixed_batch_advances_all_docs(self):
        from automerge_tpu.backend import init_sync_state
        from automerge_tpu.backend.sync import encode_sync_message
        from automerge_tpu.columnar import decode_change_meta
        from automerge_tpu.fleet.sync_driver import (
            receive_sync_messages_docs)
        fleet = DocFleet()
        handles = self._mixed_batch(fleet)
        fleet.frontier_index()
        bufs = [_change('ee' * 16, 1, 60 + i, list(h['heads']), 'new', i)
                for i, h in enumerate(handles)]
        msgs = [encode_sync_message({
                    'heads': [decode_change_meta(b, True)['hash']],
                    'need': [], 'have': [], 'changes': [b]})
                for b in bufs]
        states = [init_sync_state() for _ in handles]
        new_handles, new_states, _p, errors = receive_sync_messages_docs(
            handles, states, msgs, on_error='quarantine')
        assert errors == [None, None, None, None]
        for i, b in enumerate(bufs):
            want = [decode_change_meta(b, True)['hash']]
            assert new_states[i]['sharedHeads'] == want


@pytest.mark.slow
def test_disk_tier_million_docs_resident_budget(tmp_path):
    """1M parked docs on the DISK arena: the RSS cost is the causal
    index (~100-130 B/doc reserved), the chunk bytes are a disk number.
    Distinct causal rows per doc, shared chunk payloads (the arena
    appends each one, so disk grows per doc — the honest part — while
    the header decode is precomputed once per distinct chunk)."""
    import resource
    n = 1_000_000
    distinct = 2048
    fleet = DocFleet()
    eng = StorageEngine(fleet, path=str(tmp_path / 'arena'))
    handles = init_docs(distinct, fleet)
    per_doc = [[_change(f'{d % 128:04x}' * 4, 1, 1, [], f'k{d}', d)]
               for d in range(distinct)]
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    chunks = [bytes(h['state'].save()) for h in handles]
    views = [DocChunkView(c) for c in chunks]
    rows = [(v.heads, v.clock, v.max_op, v.n_changes) for v in views]
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KiB
    eng.main.reserve(n)
    for i in range(0, n, distinct):
        k = min(distinct, n - i)
        eng.ingest_chunks(chunks[:k], rows=rows[:k])
    assert len(eng.main) == n
    stats = eng.memory_stats()
    assert stats['resident_per_doc'] < 256, stats
    assert stats['disk_bytes'] > 100 << 20          # chunks went to disk
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    grew_kib = rss1 - rss0
    # the ceiling the 10M bench extrapolates from: resident lanes only
    assert grew_kib < 300 << 10, f'RSS grew {grew_kib} KiB'
    # spot-check far-end reads and a revive round trip off the map
    assert eng.n_changes(n - 1) == 1
    back = eng.revive([n - 1])
    assert bytes(back[0]['state'].save()) == chunks[(n % distinct or
                                                     distinct) - 1]
