"""Query engine (automerge_tpu/query/): time-travel reads at historical
frontiers and incremental patch subscriptions.

The load-bearing contracts:

- `materialize_at` at EVERY prefix frontier of a merge-heavy doc is
  byte-identical to replaying that prefix from scratch — for live,
  parked (MainStore), and delta-tail-parked docs, across both device
  modes (satellite 3 of ISSUE 9).
- Batched reads cost O(1) fused dispatches regardless of N; a
  subscription tick costs ZERO device dispatches (pure hash-graph work).
- Cursor hygiene is typed: hostile cursor bytes fail `InvalidCursor`,
  unknown frontiers fail `UnknownHeads` (or resync, in the hub) — a
  subscriber is never sent a wrong patch.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import automerge_tpu.backend as host_backend                     # noqa: E402
from automerge_tpu.columnar import (                             # noqa: E402
    decode_change_meta, encode_change)
from automerge_tpu.errors import (                               # noqa: E402
    InvalidCursor, UnknownHeads)
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet.backend import (                        # noqa: E402
    DocFleet, init_docs, park_docs)
from automerge_tpu.fleet.storage import StorageEngine            # noqa: E402
from automerge_tpu.query import (                                # noqa: E402
    SubscriptionHub, decode_cursor, diff_since, encode_cursor,
    materialize_at, materialize_at_docs)


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _merge_heavy_history(n_rounds=3):
    """A branching/merging two-actor history in causal order: each round
    both actors edit concurrently off the current frontier, then actor a
    merges — so every third prefix frontier is multi-head. Returns the
    change buffers; `_fix_frontiers` recomputes the per-prefix heads."""
    a, b = 'aa' * 16, 'bb' * 16
    changes = []
    heads = []
    seq = {a: 0, b: 0}
    op = {a: 1, b: 1}

    def emit(actor, deps):
        seq[actor] += 1
        buf = _change(actor, seq[actor], op[actor], deps,
                      f'k{len(changes)}', len(changes))
        op[actor] += 1
        changes.append(buf)
        return decode_change_meta(buf, True)['hash']

    for _r in range(n_rounds):
        ha = emit(a, heads)
        hb = emit(b, heads)
        heads = [emit(a, sorted([ha, hb]))]
    return changes


def _fix_frontiers(changes):
    """Recompute frontiers[k] (heads after the first k changes) from the
    change headers — the ground truth the builder above must match."""
    frontiers = [[]]
    heads = set()
    for buf in changes:
        meta = decode_change_meta(buf, True)
        heads -= set(meta['deps'])
        heads.add(meta['hash'])
        frontiers.append(sorted(heads))
    return frontiers


def _control_save(changes):
    """Replay-from-scratch control: the canonical save bytes of a host
    doc holding exactly `changes`."""
    doc = host_backend.init()
    if changes:
        doc, _ = host_backend.apply_changes(doc, list(changes))
    return bytes(host_backend.save(doc))


@pytest.fixture(params=['lww', 'exact'])
def fleet(request):
    return DocFleet(exact_device=(request.param == 'exact'))


class TestMaterializeAt:
    def _loaded_doc(self, fleet, changes):
        handles = init_docs(1, fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [list(changes)], mirror=False)
        return handles[0]

    def _assert_every_prefix(self, fleet, source, changes):
        """All prefix frontiers in ONE batched read (the audit-read
        shape: N frontiers, one fused dispatch), each byte-identical to
        a from-scratch replay of its prefix."""
        frontiers = _fix_frontiers(changes)
        outs = materialize_at_docs([source] * len(frontiers), frontiers,
                                   fleet=fleet)
        for k, (frontier, out) in enumerate(zip(frontiers, outs)):
            assert sorted(out['state'].heads) == frontier
            assert bytes(out['state'].save()) == \
                _control_save(changes[:k]), f'frontier {k}'
        fleet_backend.free_docs(outs)

    def test_every_prefix_frontier_byte_identical_live(self, fleet):
        changes = _merge_heavy_history()
        handle = self._loaded_doc(fleet, changes)
        self._assert_every_prefix(fleet, handle, changes)
        # the singular form agrees (one frontier, spot-check)
        frontiers = _fix_frontiers(changes)
        out = materialize_at(handle, frontiers[4], fleet=fleet)
        assert bytes(out['state'].save()) == _control_save(changes[:4])
        fleet_backend.free_docs([out])

    def test_every_prefix_frontier_byte_identical_parked(self, fleet):
        changes = _merge_heavy_history()
        handle = self._loaded_doc(fleet, changes)
        eng = StorageEngine(fleet)
        ids = eng.park([handle])
        assert ids[0] is not None
        self._assert_every_prefix(fleet, (eng, ids[0]), changes)
        # the audit reads never revived the parked doc
        assert len(eng.main) == 1

    def test_every_prefix_frontier_delta_tail_parked(self, fleet):
        # in-fleet parked prefix + turbo delta tail: history spans the
        # parked chunk AND the tail; selection must cover both
        changes = _merge_heavy_history()
        split = len(changes) // 2
        handle = self._loaded_doc(fleet, changes[:split])
        assert park_docs([handle]) == 1
        handle, _ = fleet_backend.apply_changes_docs(
            [handle], [list(changes[split:])], mirror=False)
        handle = handle[0]
        impl = handle['state']._impl
        assert impl._doc_pending is not None or impl._changes, \
            'expected a parked/tail engine'
        self._assert_every_prefix(fleet, handle, changes)

    def test_batched_reads_one_fused_dispatch(self, fleet):
        changes = _merge_heavy_history()
        frontiers = _fix_frontiers(changes)
        handle = self._loaded_doc(fleet, changes)
        deltas = {}
        for n in (3, 9):
            before = fleet.metrics.dispatches
            outs = materialize_at_docs(
                [handle] * n,
                [frontiers[1 + i % (len(frontiers) - 1)]
                 for i in range(n)], fleet=fleet)
            deltas[n] = fleet.metrics.dispatches - before
            fleet_backend.free_docs(outs)
        assert deltas[3] == deltas[9], deltas

    def test_unknown_heads_typed(self, fleet):
        changes = _merge_heavy_history(1)
        handle = self._loaded_doc(fleet, changes)
        with pytest.raises(UnknownHeads) as exc_info:
            materialize_at(handle, ['ee' * 32], fleet=fleet)
        assert exc_info.value.missing == ['ee' * 32]
        # parked form rejects identically
        eng = StorageEngine(fleet)
        ids = eng.park([handle])
        with pytest.raises(UnknownHeads):
            materialize_at((eng, ids[0]), ['ee' * 32], fleet=fleet)

    def test_quarantine_mode_contains_bad_frontier(self, fleet):
        changes = _merge_heavy_history(1)
        frontiers = _fix_frontiers(changes)
        handle = self._loaded_doc(fleet, changes)
        handles, errors = materialize_at_docs(
            [handle, handle], [['ee' * 32], frontiers[-1]],
            fleet=fleet, on_error='quarantine')
        assert handles[0] is None
        assert isinstance(errors[0].error, UnknownHeads)
        assert errors[1] is None
        assert bytes(handles[1]['state'].save()) == _control_save(changes)
        fleet_backend.free_docs([handles[1]])

    def test_redundant_frontier_normalizes(self, fleet):
        # a frontier naming a change AND its ancestor materializes at
        # the maximal elements
        changes = _merge_heavy_history(1)
        frontiers = _fix_frontiers(changes)
        handle = self._loaded_doc(fleet, changes)
        redundant = frontiers[-1] + frontiers[1]
        out = materialize_at(handle, redundant, fleet=fleet)
        assert sorted(out['state'].heads) == frontiers[-1]
        fleet_backend.free_docs([out])


class TestCursorCodec:
    def test_round_trip(self):
        heads = ['ab' * 32, 'cd' * 32]
        assert decode_cursor(encode_cursor(heads)) == sorted(heads)
        assert decode_cursor(encode_cursor([])) == []
        # dedupe + sort on encode
        assert decode_cursor(encode_cursor(heads[::-1] + heads)) == \
            sorted(heads)

    def test_hostile_bytes_fail_typed(self):
        good = encode_cursor(['ab' * 32])
        hostile = [b'', b'\x00', b'garbage', good[:-5], good + b'x',
                   bytes([0x52]) + good[1:],
                   bytes([0x51, 0xff, 0xff, 0xff, 0xff, 0x7f])]
        for mutant in hostile:
            with pytest.raises(InvalidCursor):
                decode_cursor(mutant)

    def test_unsorted_wire_cursor_rejected(self):
        # hand-built cursor with unsorted hashes: reject (canonical form
        # keeps equivalence classes honest)
        from automerge_tpu.encoding import Encoder
        out = Encoder()
        out.append_byte(0x51)
        out.append_uint53(2)
        out.append_raw_bytes(bytes.fromhex('cd' * 32))
        out.append_raw_bytes(bytes.fromhex('ab' * 32))
        with pytest.raises(InvalidCursor):
            decode_cursor(out.buffer)


class TestSubscriptionHub:
    def _serve(self, fleet, changes):
        handles = init_docs(1, fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [list(changes)], mirror=False)
        return handles[0]

    def test_patch_folds_byte_identical(self, fleet):
        changes = _merge_heavy_history()
        handle = self._serve(fleet, changes)
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d')
        ev = hub.tick()[sub.id]
        assert ev['kind'] == 'patch'
        shadow = host_backend.init()
        shadow, _ = host_backend.apply_changes(shadow, ev['changes'])
        assert host_backend.get_heads(shadow) == ev['heads']
        assert bytes(host_backend.save(shadow)) == \
            bytes(handle['state'].save())
        # cursor advanced: next tick is quiet
        assert hub.tick() == {}

    def test_incremental_diff_only(self, fleet):
        changes = _merge_heavy_history()
        split = len(changes) - 3
        handle = self._serve(fleet, changes[:split])
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d')
        first = hub.tick()[sub.id]
        assert len(first['changes']) == split
        handle, _ = fleet_backend.apply_changes_docs(
            [handle], [list(changes[split:])], mirror=False)
        hub.update_source('d', handle[0])
        second = hub.tick()[sub.id]
        assert len(second['changes']) == 3       # ONLY the delta
        shadow = host_backend.init()
        shadow, _ = host_backend.apply_changes(shadow, first['changes'])
        shadow, _ = host_backend.apply_changes(shadow, second['changes'])
        assert bytes(host_backend.save(shadow)) == \
            bytes(handle[0]['state'].save())

    def test_equivalence_class_reuse(self, fleet):
        changes = _merge_heavy_history()
        handle = self._serve(fleet, changes)
        hub = SubscriptionHub()
        hub.register('d', handle)
        subs = [hub.subscribe('d') for _ in range(10)]
        events = hub.tick()
        assert len(events) == 10
        assert hub.stats['diffs_computed'] == 1
        assert hub.stats['diffs_reused'] == 9
        assert all(events[s.id]['heads'] == sorted(handle['state'].heads)
                   for s in subs)

    def test_tick_costs_zero_dispatches(self, fleet):
        changes = _merge_heavy_history()
        handles = init_docs(8, fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [list(changes)] * 8, mirror=False)
        hub = SubscriptionHub()
        for i, handle in enumerate(handles):
            hub.register(i, handle)
            for _ in range(5):
                hub.subscribe(i)
        before = fleet.metrics.dispatches
        events = hub.tick()
        assert len(events) == 40
        assert fleet.metrics.dispatches == before, \
            'a subscription tick must be pure host graph work'

    def test_bogus_cursor_resyncs_typed_never_wrong(self, fleet):
        changes = _merge_heavy_history()
        handle = self._serve(fleet, changes)
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d', cursor=['99' * 32])
        ev = hub.tick()[sub.id]
        assert ev['kind'] == 'resync'
        assert ev['error'] == 'UnknownHeads'
        shadow = host_backend.init()
        shadow, _ = host_backend.apply_changes(shadow, ev['changes'])
        assert bytes(host_backend.save(shadow)) == \
            bytes(handle['state'].save())
        assert hub.stats['resyncs'] == 1

    def test_replayed_cursor_idempotent(self, fleet):
        changes = _merge_heavy_history()
        frontiers = _fix_frontiers(changes)
        handle = self._serve(fleet, changes)
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d')
        first = hub.tick()[sub.id]
        # the client lost the push: replay from an old (valid) frontier
        hub.resubscribe(sub, frontiers[2])
        again = hub.tick()[sub.id]
        assert again['kind'] == 'patch'
        shadow = host_backend.init()
        shadow, _ = host_backend.apply_changes(shadow, first['changes'][:2])
        assert host_backend.get_heads(shadow) == frontiers[2]
        shadow, _ = host_backend.apply_changes(shadow, again['changes'])
        assert bytes(host_backend.save(shadow)) == \
            bytes(handle['state'].save())

    def test_park_revive_churn_mid_subscription(self, fleet):
        changes = _merge_heavy_history()
        split = len(changes) - 3
        handle = self._serve(fleet, changes[:split])
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d')
        hub.tick()
        # park: the source becomes a (store, id) pair — cursors survive
        eng = StorageEngine(fleet)
        ids = eng.park([handle])
        hub.update_source('d', (eng, ids[0]))
        assert hub.tick() == {}                   # quiet, served parked
        # revive, extend, rebind: the diff picks up from the cursor
        back = eng.revive(ids)
        back, _ = fleet_backend.apply_changes_docs(
            back, [list(changes[split:])], mirror=False)
        hub.update_source('d', back[0])
        ev = hub.tick()[sub.id]
        assert len(ev['changes']) == 3
        assert ev['heads'] == sorted(back[0]['state'].heads)

    def test_unregister_closes(self, fleet):
        changes = _merge_heavy_history(1)
        handle = self._serve(fleet, changes)
        hub = SubscriptionHub()
        hub.register('d', handle)
        sub = hub.subscribe('d')
        hub.unregister('d')
        assert hub.tick()[sub.id] == {'kind': 'closed'}
        assert len(hub) == 0


class TestDiffSince:
    def test_live_and_parked_agree(self, fleet):
        changes = _merge_heavy_history()
        frontiers = _fix_frontiers(changes)
        handles = init_docs(1, fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [list(changes)], mirror=False)
        handle = handles[0]
        chunk = bytes(handle['state'].save())
        eng = StorageEngine(fleet)
        ids = eng.ingest_chunks([chunk])
        for frontier in frontiers:
            live_changes, live_heads = diff_since(handle, frontier)
            parked_changes, parked_heads = diff_since((eng, ids[0]),
                                                      frontier)
            assert live_heads == parked_heads
            # the live log keeps application order, the chunk its
            # canonical order — same change SET, both causally valid
            assert sorted(bytes(c) for c in live_changes) == \
                sorted(parked_changes)

    def test_quiet_class_computes_once(self, fleet, monkeypatch):
        # regression: a QUIET equivalence class (cursor == heads) must
        # memoize its answer too — 5 at-frontier subscribers cost one
        # diff_since call per tick, not five
        import automerge_tpu.query.subscriptions as subs_mod
        changes = _merge_heavy_history(1)
        handles = init_docs(1, fleet)
        handles, _ = fleet_backend.apply_changes_docs(
            handles, [list(changes)], mirror=False)
        handle = handles[0]
        hub = SubscriptionHub()
        hub.register('d', handle)
        subs = [hub.subscribe('d') for _ in range(5)]
        assert len(hub.tick()) == 5           # first tick: full patches
        calls = []
        orig = subs_mod.diff_since
        monkeypatch.setattr(
            subs_mod, 'diff_since',
            lambda *a, **k: (calls.append(1), orig(*a, **k))[1])
        assert hub.tick() == {}               # all quiet now
        # the batched frontier compare proves the tick quiet with ZERO
        # diff_since calls (round 18); the memoized slow path must still
        # cost exactly one per class — both pinned
        assert len(calls) == 0, f'{len(calls)} diffs for a batched tick'
        assert hub.stats['quiet'] >= 5
        hub.batch_quiet = False
        calls.clear()
        assert hub.tick() == {}
        assert len(calls) == 1, f'{len(calls)} diffs for one quiet class'

    def test_non_canonical_count_rejected(self):
        # non-minimal LEB128 count (80 00 = padded zero): decodes to []
        # upstream but is NOT the canonical frame for [] — reject, or
        # equivalent cursors split equivalence classes
        with pytest.raises(InvalidCursor):
            decode_cursor(bytes([0x51, 0x80, 0x00]))
        assert decode_cursor(bytes([0x51, 0x00])) == []


class _StubHistory:
    """A selection-capable history whose buffers the apply gate will
    reject (poisoned mid-log) — the rotted-parked-chunk shape."""

    def __init__(self, changes):
        import automerge_tpu.columnar as columnar
        self.changes = [bytes(c) for c in changes]
        metas = [columnar.decode_change_meta(c, True) for c in changes]
        self.change_index_by_hash = {m['hash']: i
                                     for i, m in enumerate(metas)}
        self.dependencies_by_hash = {m['hash']: list(m['deps'])
                                     for m in metas}
        self.heads = [metas[-1]['hash']]
        # poison the FIRST buffer after hashing: selection still works
        # off the metadata, the fused apply rejects the bytes
        bad = bytearray(self.changes[0])
        bad[10] ^= 0x40
        self.changes[0] = bytes(bad)


class TestApplyStageQuarantine:
    def test_poisoned_history_costs_only_its_slot(self, fleet):
        from automerge_tpu.errors import WireCorruption
        changes = _merge_heavy_history(1)
        frontiers = _fix_frontiers(changes)
        good = init_docs(1, fleet)
        good, _ = fleet_backend.apply_changes_docs(
            good, [list(changes)], mirror=False)
        stub = _StubHistory(changes)
        handles, errors = materialize_at_docs(
            [stub, good[0]], [stub.heads, frontiers[-1]],
            fleet=fleet, on_error='quarantine')
        assert handles[0] is None
        assert isinstance(errors[0].error, WireCorruption)
        assert errors[1] is None
        assert bytes(handles[1]['state'].save()) == _control_save(changes)
        fleet_backend.free_docs([handles[1]])

    def test_rotted_chunk_source_costs_only_its_slot(self, fleet):
        from automerge_tpu.errors import MalformedDocument
        changes = _merge_heavy_history(1)
        frontiers = _fix_frontiers(changes)
        good = init_docs(1, fleet)
        good, _ = fleet_backend.apply_changes_docs(
            good, [list(changes)], mirror=False)
        rotted = bytearray(bytes(good[0]['state'].save()))
        rotted[6] ^= 0x08                      # checksum no longer holds
        handles, errors = materialize_at_docs(
            [bytes(rotted), good[0]], [frontiers[-1], frontiers[-1]],
            fleet=fleet, on_error='quarantine')
        assert handles[0] is None
        assert isinstance(errors[0].error, MalformedDocument)
        assert errors[1] is None
        assert bytes(handles[1]['state'].save()) == _control_save(changes)
        fleet_backend.free_docs([handles[1]])
        # raise mode still aborts typed
        with pytest.raises(MalformedDocument):
            materialize_at(bytes(rotted), frontiers[-1], fleet=fleet)
