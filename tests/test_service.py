"""Unit coverage for the overload-safe serving core (ISSUE-7).

Policy objects (token bucket, backoff, retry budget, deadline, brownout
ladder) are tested on injected clocks — no wall-clock sleeps — and the
service-level contracts are pinned end to end: typed admission
rejections, all-or-nothing deadlines at the fused-dispatch seam,
transient-fault retries under budget, brownout transitions moving
health counters and the journal's fsync batching, and the two
containment holes the chaos client flushed out (corrupt magic bytes,
unknown-type chunks with bad checksums) staying typed quarantines.
"""

import os

import pytest

import automerge_tpu.backend as host_backend
from automerge_tpu import native
from automerge_tpu.columnar import encode_change
from automerge_tpu.errors import (AutomergeError, DeadlineExceeded,
                                  MalformedChange, Overloaded,
                                  RetriesExhausted, SyncStalled,
                                  TenantThrottled, WireCorruption)
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet
from automerge_tpu.service import (AdmissionController, Backoff,
                                   BrownoutController, Deadline, DocService,
                                   RetryBudget, TokenBucket, service_stats)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


def change_bytes(actor, seq, val=1, key='k'):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': [],
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


# ---------------------------------------------------------------------------
# policy objects (no fleet, no clocks but the injected one)
# ---------------------------------------------------------------------------


def test_token_bucket_refill_and_deny():
    b = TokenBucket(rate=10.0, burst=2.0)
    assert b.take(0.0) is None
    assert b.take(0.0) is None
    wait = b.take(0.0)
    assert wait is not None and wait > 0
    # after the advertised wait, a token is back
    assert b.take(0.0 + wait) is None


def test_backoff_schedule_bounded_and_deterministic():
    a = Backoff(base=0.1, factor=2.0, cap=1.0, retries=4, jitter=0.5,
                seed=7)
    b = Backoff(base=0.1, factor=2.0, cap=1.0, retries=4, jitter=0.5,
                seed=7)
    da = [a.delay(k) for k in range(8)]
    db = [b.delay(k) for k in range(8)]
    assert da == db                       # seeded => replayable
    for k, d in enumerate(da):
        assert 0 < d <= 1.0               # jitter only shrinks, cap holds
        assert d <= min(1.0, 0.1 * 2.0 ** k)
    assert not a.exhausted(3)
    assert a.exhausted(4)


def test_retry_budget_refills_at_rate():
    rb = RetryBudget(rate=1.0, burst=2.0)
    assert rb.spend(0.0) and rb.spend(0.0)
    assert not rb.spend(0.0)              # dry
    assert rb.denied == 1
    assert rb.spend(1.5)                  # refilled


def test_deadline_typed_check():
    clock = [0.0]
    d = Deadline.after(1.0, clock=lambda: clock[0])
    d.check()                             # fine
    clock[0] = 2.0
    assert d.expired()
    with pytest.raises(DeadlineExceeded) as exc:
        d.check(what='unit')
    assert exc.value.late_by == pytest.approx(1.0)
    assert isinstance(exc.value, ValueError)   # taxonomy contract


def test_admission_typed_rejections_and_fair_drain():
    adm = AdmissionController(rate=1000.0, burst=1000.0, queue_limit=4,
                              max_queued=6)
    for i in range(4):
        adm.admit('a', f'a{i}', now=0.0)
    with pytest.raises(TenantThrottled) as exc:
        adm.admit('a', 'a4', now=0.0)     # tenant queue full
    assert exc.value.tenant == 'a'
    adm.admit('b', 'b0', now=0.0)
    adm.admit('b', 'b1', now=0.0)
    with pytest.raises(Overloaded):
        adm.admit('c', 'c0', now=0.0)     # global ceiling
    # round-robin drain: b is not starved behind a's queue
    order = adm.drain(4)
    assert 'b0' in order[:3]
    # rate limiting is typed too, with a retry hint
    adm2 = AdmissionController(rate=1.0, burst=1.0, queue_limit=10,
                               max_queued=10)
    adm2.admit('t', 'r0', now=0.0)
    with pytest.raises(TenantThrottled) as exc:
        adm2.admit('t', 'r1', now=0.0)
    assert exc.value.retry_after > 0


def test_brownout_ladder_hysteresis_and_counters():
    from automerge_tpu.service import brownout_stats
    bo = BrownoutController(high=0.8, low=0.2, up_ticks=2, down_ticks=3)
    before = brownout_stats()
    assert bo.observe(0.9) == 0           # one tick is not sustained
    assert bo.observe(0.9) == 1           # two ticks climb one stage
    assert bo.observe(0.5) == 1           # middle band holds
    bo.observe(0.9)
    assert bo.observe(0.9) == 2
    bo.observe(0.9), bo.observe(0.9)
    assert bo.stage == 3
    assert bo.shed_below() == bo.shed_priority
    assert bo.defer_compaction
    for _ in range(3):
        bo.observe(0.1)
    assert bo.stage == 2                  # one stage per transition
    after = brownout_stats()
    assert after['brownout_escalations'] - before['brownout_escalations'] == 3
    assert after['brownout_deescalations'] - \
        before['brownout_deescalations'] == 1
    assert len(bo.transitions) == 4


# ---------------------------------------------------------------------------
# service-level contracts (one shared small fleet; compile cost paid once)
# ---------------------------------------------------------------------------


def make_service(**kw):
    kw.setdefault('fleet', DocFleet(doc_capacity=8, key_capacity=64))
    kw.setdefault('tenant_rate', 10_000.0)
    kw.setdefault('tenant_burst', 1000.0)
    return DocService(**kw)


def test_service_apply_and_sync_roundtrip():
    svc = make_service()
    s_edit, s_sync = svc.open_sessions(['t0', 't1'])
    t1 = svc.submit(s_edit, 'apply', [change_bytes('aa' * 16, 1, 7)])
    # client replica for the sync session
    import automerge_tpu as A
    doc = A.frontend.get_backend_state(A.init('bb' * 16), 'svc-unit')
    doc, _ = host_backend.apply_changes(doc, [change_bytes('bb' * 16, 1, 9)])
    state, msg = host_backend.generate_sync_message(
        doc, host_backend.init_sync_state())
    t2 = svc.submit(s_sync, 'sync', msg)
    svc.pump()
    assert t1.status == 'ok' and t1.latency is not None
    assert t2.status == 'ok'
    # handshake to quiet: the client replica and the service doc converge
    for _ in range(12):
        doc, state, _ = host_backend.receive_sync_message(
            doc, state, t2.result) if t2.result is not None \
            else (doc, state, None)
        state, msg = host_backend.generate_sync_message(doc, state)
        if msg is None and t2.result is None:
            break
        t2 = svc.submit(s_sync, 'sync', msg)
        svc.pump()
    assert host_backend.get_heads(s_sync.handle) == \
        host_backend.get_heads(doc)


def test_expired_deadline_is_typed_and_never_partially_commits():
    svc = make_service()
    session = svc.open_session('t0')
    ok = svc.submit(session, 'apply', [change_bytes('cc' * 16, 1)])
    svc.pump()
    assert ok.status == 'ok'
    late = svc.submit(session, 'apply', [change_bytes('cc' * 16, 2)],
                      timeout=-0.001)
    svc.pump()
    assert late.status == 'error'
    assert isinstance(late.error, DeadlineExceeded)
    # all-or-nothing: the doc holds exactly the committed prefix
    assert len(host_backend.get_all_changes(session.handle)) == 1
    assert service_stats()['deadline_exceeded'] >= 1


def test_seam_deadline_checks_are_typed_and_pre_dispatch():
    fleet = DocFleet(doc_capacity=4, key_capacity=64)
    handles = fleet_backend.init_docs(2, fleet)
    clock = [0.0]
    expired = Deadline(-1.0, clock=lambda: clock[0])
    with pytest.raises(DeadlineExceeded):
        fleet_backend.apply_changes_docs(
            handles, [[change_bytes('aa' * 16, 1)], []], mirror=False,
            deadline=expired)
    # nothing mutated: the docs still apply cleanly afterwards
    out, _ = fleet_backend.apply_changes_docs(
        handles, [[change_bytes('aa' * 16, 1)], []], mirror=False)
    assert len(host_backend.get_all_changes(out[0])) == 1
    from automerge_tpu.fleet.sync_driver import (
        generate_sync_messages_docs, receive_sync_messages_docs)
    with pytest.raises(DeadlineExceeded):
        generate_sync_messages_docs(
            out, [host_backend.init_sync_state() for _ in out],
            deadline=expired)
    with pytest.raises(DeadlineExceeded):
        receive_sync_messages_docs(
            out, [host_backend.init_sync_state() for _ in out],
            [None, None], mirror=False, deadline=expired)


def test_quarantine_failure_is_typed_and_contained():
    svc = make_service()
    good, bad = svc.open_sessions(['t0', 't0'])
    ok = svc.submit(good, 'apply', [change_bytes('aa' * 16, 1, 5)])
    buf = bytearray(change_bytes('bb' * 16, 1))
    buf[20] ^= 0xFF
    poisoned = svc.submit(bad, 'apply', [bytes(buf)])
    svc.pump()
    assert ok.status == 'ok'
    assert poisoned.status == 'error'
    assert isinstance(poisoned.error, AutomergeError)
    assert len(host_backend.get_all_changes(bad.handle)) == 0


def test_corrupt_magic_is_quarantined_not_stored():
    """Pin for the native codec fix: a change whose MAGIC bytes are
    corrupt must be a typed quarantine — before the fix the native
    parser skipped the magic check, the ops landed on the device, and
    the garbage bytes entered the change log where save() exploded."""
    fleet = DocFleet(doc_capacity=4, key_capacity=64)
    base = change_bytes('dd' * 16, 1)
    for pos in range(4):
        corrupt = bytearray(base)
        corrupt[pos] ^= 0x40
        handles = fleet_backend.init_docs(1, fleet)
        out, _, errs = fleet_backend.apply_changes_docs(
            handles, [[bytes(corrupt)]], mirror=False,
            on_error='quarantine')
        assert errs[0] is not None, f'magic flip at byte {pos} accepted'
        assert isinstance(errs[0].error, WireCorruption)
        assert len(host_backend.get_all_changes(out[0])) == 0
        host_backend.save(out[0])          # and the doc still saves
        fleet_backend.free_docs(out)


def test_unknown_chunk_type_with_bad_checksum_is_quarantined():
    """Pin for the screen fix: a bit flip IN the chunk-type byte makes
    the container an 'unknown type' whose checksum no longer validates —
    it must quarantine typed, not slide through as nothing-to-apply
    (which resolved the request ok without applying anything)."""
    fleet = DocFleet(doc_capacity=4, key_capacity=64)
    base = bytearray(change_bytes('ee' * 16, 1))
    base[8] ^= 0x20                        # type 0x01 -> 0x21
    handles = fleet_backend.init_docs(1, fleet)
    out, _, errs = fleet_backend.apply_changes_docs(
        handles, [[bytes(base)]], mirror=False, on_error='quarantine')
    assert errs[0] is not None
    assert isinstance(errs[0].error, WireCorruption)
    fleet_backend.free_docs(out)


def test_transient_fault_retries_then_succeeds():
    clock = [0.0]
    svc = make_service(clock=lambda: clock[0],
                       backoff=Backoff(base=0.01, cap=0.1, retries=5,
                                       seed=3))
    session = svc.open_session('t0')
    clean = [change_bytes('aa' * 16, 1, 3)]
    corrupt = bytearray(clean[0])
    corrupt[20] ^= 0xFF
    draws = [bytes(corrupt), bytes(corrupt), clean[0]]   # 2 faults, then ok

    def payload_fn():
        return [draws.pop(0)] if draws else clean

    before = service_stats()['service_retries']
    ticket = svc.submit(session, 'apply', payload_fn=payload_fn)
    for _ in range(20):
        if ticket.done:
            break
        svc.pump(now=clock[0])
        clock[0] += 0.05                   # ripen the backoff parking
    assert ticket.status == 'ok'
    assert service_stats()['service_retries'] - before == 2
    assert len(host_backend.get_all_changes(session.handle)) == 1


def test_retry_budget_exhaustion_is_typed():
    clock = [0.0]
    svc = make_service(clock=lambda: clock[0],
                       backoff=Backoff(base=0.01, cap=0.02, retries=3,
                                       seed=0),
                       retry_rate=100.0, retry_burst=100.0)
    session = svc.open_session('t0')
    corrupt = bytearray(change_bytes('aa' * 16, 1))
    corrupt[20] ^= 0xFF

    ticket = svc.submit(session, 'apply',
                        payload_fn=lambda: [bytes(corrupt)])
    for _ in range(30):
        if ticket.done:
            break
        svc.pump(now=clock[0])
        clock[0] += 0.05
    assert ticket.status == 'error'
    assert isinstance(ticket.error, RetriesExhausted)
    assert isinstance(ticket.error.__cause__, WireCorruption)
    assert ticket.error.attempts == 3
    assert len(host_backend.get_all_changes(session.handle)) == 0


def test_tenant_fairness_under_flood():
    """An aggressive tenant floods its queue; a light tenant's request
    still completes on the next pump (round-robin drain + per-tenant
    queues = the flood cannot age other tenants)."""
    svc = make_service(tenant_queue=512, max_queued=10_000,
                       batch_limit=64)
    heavy = [svc.open_session('whale') for _ in range(4)]
    light = svc.open_session('minnow')
    seqs = {id(s): 0 for s in heavy}
    flood = []
    for i in range(256):
        s = heavy[i % 4]
        seqs[id(s)] += 1
        flood.append(svc.submit(
            s, 'apply', [change_bytes(f'{i % 4:02x}' * 16, seqs[id(s)])]))
    t_light = svc.submit(light, 'apply', [change_bytes('ff' * 16, 1)])
    svc.pump()
    assert t_light.status == 'ok'          # served in the FIRST tick
    assert sum(1 for t in flood if t.done) < len(flood)  # whale still queued


def test_brownout_widen_fsync_and_restore(tmp_path):
    from automerge_tpu.fleet.durability import DurableFleet
    durable = DurableFleet(str(tmp_path / 'dur'), fsync_bytes=64,
                           doc_capacity=8, key_capacity=64)
    svc = DocService(durable=durable, tenant_rate=10_000.0,
                     brownout=BrownoutController(high=0.5, low=0.1,
                                                 up_ticks=1, down_ticks=3,
                                                 fsync_widen_bytes=1 << 20))
    session = svc.open_session('t0')
    journal = durable.journal
    assert journal.fsync_bytes == 64
    svc.brownout.observe(0.9)              # stage 1: widen
    assert journal.fsync_bytes == 1 << 20
    # the widened loss window is visible through the health counter
    t = svc.submit(session, 'apply', [change_bytes('aa' * 16, 1)])
    svc.pump()
    assert t.status == 'ok'
    from automerge_tpu.observability import health_counts
    assert health_counts()['pending_fsync_bytes'] > 0
    for _ in range(3):                     # de-escalate: restore + close
        svc.brownout.observe(0.0)
    assert journal.fsync_bytes == 64
    assert journal.pending_fsync_bytes == 0
    durable.close()


def test_brownout_stage3_sheds_low_priority_sync_typed():
    svc = make_service()
    session = svc.open_session('t0')
    svc.brownout.stage = 3                 # force the top of the ladder
    shed = svc.submit(session, 'sync', None, priority=0)
    kept = svc.submit(session, 'sync', None, priority=2)
    svc.pump()
    assert shed.status == 'error'
    assert isinstance(shed.error, Overloaded)
    assert shed.error.shed is True and shed.error.stage == 3
    assert kept.status == 'ok'
    from automerge_tpu.service import brownout_stats
    assert brownout_stats()['shed_sync_rounds'] >= 1


def test_sync_reconnect_reset_converges_against_poisoned_server():
    """Regression for the reconnect livelock: a client that lost its
    sync state mid-handshake re-handshakes with reset=True; the service
    must answer from fresh state (simultaneous-handshake rule) instead
    of staying silent behind its stale sentHashes."""
    import automerge_tpu as A
    svc = make_service()
    session = svc.open_session('t0')
    doc = A.frontend.get_backend_state(A.init('ab' * 16), 'reset-unit')
    doc, _ = host_backend.apply_changes(doc, [change_bytes('ab' * 16, 1)])
    state = host_backend.init_sync_state()
    # half a handshake, then the client loses its state (crash)
    state, msg = host_backend.generate_sync_message(doc, state)
    t = svc.submit(session, 'sync', msg)
    svc.pump()
    state = host_backend.init_sync_state()     # client-side reconnect
    converged = False
    fresh = True
    for _ in range(12):
        state, msg = host_backend.generate_sync_message(doc, state)
        t = svc.submit(session, 'sync', msg, reset=fresh)
        fresh = False
        svc.pump()
        assert t.status == 'ok'
        if t.result is not None:
            doc, state, _ = host_backend.receive_sync_message(
                doc, state, t.result)
        if msg is None and t.result is None:
            converged = True
            break
    assert converged
    assert host_backend.get_heads(session.handle) == \
        host_backend.get_heads(doc)


def test_async_facade_roundtrip():
    import asyncio
    from automerge_tpu.service import AsyncDocService

    svc = make_service()
    session = svc.open_session('t0')
    facade = AsyncDocService(svc, idle_sleep=0.001)

    async def go():
        pump = asyncio.create_task(facade.run())
        ticket = await facade.submit(session, 'apply',
                                     [change_bytes('aa' * 16, 1)])
        with pytest.raises(DeadlineExceeded):
            await facade.submit(session, 'apply',
                                [change_bytes('aa' * 16, 2)],
                                timeout=-0.01)
        facade.stop()
        await pump
        return ticket

    ticket = asyncio.run(go())
    assert ticket.status == 'ok'


# ---------------------------------------------------------------------------
# satellites: flight-dump rate limit, stall give-up, loss-window counter
# ---------------------------------------------------------------------------


def test_flight_dump_rate_limit(tmp_path):
    from automerge_tpu.observability import recorder
    try:
        recorder.configure(dump_dir=str(tmp_path), dump_limit=3,
                           dump_window_s=3600.0)
        before = recorder.flight_stats()
        for _ in range(8):
            recorder.dump_flight_record('unit_rate_limit')
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith('flight-unit_rate_limit')]
        assert len(files) == 3             # the cap held on disk
        after = recorder.flight_stats()
        assert after['flight_dumps'] - before['flight_dumps'] == 8
        assert after['dumps_suppressed'] - before['dumps_suppressed'] == 5
        # suppressed dumps still assemble in memory, flagged
        assert recorder.last_flight_record()['suppressed'] is True
        # an explicit path bypasses the limit (operator override)
        report = recorder.dump_flight_record(
            'unit_rate_limit', path=str(tmp_path / 'explicit.json'))
        assert os.path.exists(report['path'])
    finally:
        recorder.configure(dump_dir=None, dump_limit=16,
                           dump_window_s=60.0)


def test_sync_until_quiet_typed_giveup_and_backoff():
    import automerge_tpu as A
    from automerge_tpu.fleet.faults import LossyLink, sync_until_quiet
    base = A.change(A.init('aa' * 16), lambda d: d.update({'x': 1}))
    db = A.merge(A.init('bb' * 16), base)
    da = A.change(base, lambda d: d.update({'y': 2}))
    db = A.change(db, lambda d: d.update({'z': 3}))
    ha = A.frontend.get_backend_state(da, 'giveup-a')
    hb = A.frontend.get_backend_state(db, 'giveup-b')
    # a dead wire: the driver must give up TYPED, not assert
    with pytest.raises(SyncStalled) as exc:
        sync_until_quiet(ha, hb, host_backend, host_backend,
                         LossyLink(seed=1, p_drop=1.0),
                         LossyLink(seed=2, p_drop=1.0), max_rounds=48)
    assert isinstance(exc.value, RetriesExhausted)
    assert exc.value.rounds == 48
    assert exc.value.detail['ab']['dropped'] > 0
    # bounded faults still converge through the jittered reconnects
    ha2 = A.frontend.get_backend_state(da, 'giveup-a2')
    hb2 = A.frontend.get_backend_state(db, 'giveup-b2')
    na, nb, _rounds, stats = sync_until_quiet(
        ha2, hb2, host_backend, host_backend,
        LossyLink(seed=3, p_drop=0.3, budget=6),
        LossyLink(seed=4, p_drop=0.3, budget=6))
    assert host_backend.get_heads(na) == host_backend.get_heads(nb)


def test_pending_fsync_bytes_counter_and_alert(tmp_path):
    from automerge_tpu.fleet import durability as dur
    from automerge_tpu.observability import health_counts, recorder
    prev = dur.set_fsync_alert_threshold(128)
    try:
        j = dur.ChangeJournal(str(tmp_path / 'journal-0.log'),
                              fsync_bytes=1 << 20)
        before_alerts = health_counts()['fsync_window_alerts']
        j.append(0, b'x' * 32)
        j.commit()                         # below threshold: no alert
        assert health_counts()['fsync_window_alerts'] == before_alerts
        j.append(0, b'y' * 256)
        j.commit()                         # crosses: one edge-triggered
        h = health_counts()
        assert h['pending_fsync_bytes'] >= 256
        assert h['fsync_window_alerts'] == before_alerts + 1
        assert any(e['kind'] == 'fsync_window_alert'
                   for e in recorder.recent_events())
        j.append(0, b'z' * 256)
        j.commit()                         # still open: NOT re-alerted
        assert health_counts()['fsync_window_alerts'] == before_alerts + 1
        j.sync()                           # window closes, counter drops
        assert health_counts()['pending_fsync_bytes'] == 0
        j.append(0, b'w' * 256)
        j.commit()                         # re-armed: alerts again
        assert health_counts()['fsync_window_alerts'] == before_alerts + 2
        j.close()
    finally:
        dur.set_fsync_alert_threshold(prev)


# ---------------------------------------------------------------------------
# query request kinds (round 13): time-travel reads + subscriptions
# ---------------------------------------------------------------------------

def _chained_change(actor, seq, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': seq, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _edited_session(svc, tenant='t0', rounds=3):
    """A session whose doc holds `rounds` chained changes; returns
    (session, frontiers) with frontiers[k] = heads after k changes."""
    from automerge_tpu.columnar import decode_change_meta
    session = svc.open_session(tenant)
    frontier, frontiers = [], [[]]
    for r in range(rounds):
        buf = _chained_change('ee' * 16, r + 1, frontier, f'k{r}', r)
        frontier = [decode_change_meta(buf, True)['hash']]
        frontiers.append(list(frontier))
        t = svc.submit(session, 'apply', [buf])
        svc.pump()
        assert t.status == 'ok', t.error
    return session, frontiers


def test_materialize_at_kind_returns_historical_chunk():
    svc = make_service()
    session, frontiers = _edited_session(svc)
    t = svc.submit(session, 'materialize_at', frontiers[2])
    svc.pump()
    assert t.status == 'ok', t.error
    doc = host_backend.load(t.result)
    assert host_backend.get_heads(doc) == sorted(frontiers[2])
    # the ephemeral read doc was freed: session doc still live, fleet
    # slot count unchanged after the read batch
    assert session.handle['state'].is_fleet


def test_materialize_at_unknown_heads_typed_contained():
    from automerge_tpu.errors import UnknownHeads
    svc = make_service()
    session, frontiers = _edited_session(svc)
    bad = svc.submit(session, 'materialize_at', ['ee' * 32])
    good = svc.submit(session, 'materialize_at', frontiers[1])
    svc.pump()
    assert bad.status == 'error'
    assert isinstance(bad.error, UnknownHeads)
    assert good.status == 'ok'     # the bad frontier cost only its slot


def test_subscribe_kind_incremental_and_wire_cursor():
    from automerge_tpu.query import encode_cursor
    svc = make_service()
    session, frontiers = _edited_session(svc)
    # first pull: full state from the session's empty cursor
    t1 = svc.submit(session, 'subscribe')
    svc.pump()
    assert t1.status == 'ok'
    assert t1.result['kind'] == 'patch'
    assert len(t1.result['changes']) == 3
    shadow = host_backend.init()
    shadow, _ = host_backend.apply_changes(shadow, t1.result['changes'])
    assert bytes(host_backend.save(shadow)) == \
        bytes(session.handle['state'].save())
    # cursor advanced server-side: next pull is an empty patch
    t2 = svc.submit(session, 'subscribe')
    svc.pump()
    assert t2.result['changes'] == []
    # an explicit wire cursor replays from its frontier (idempotent)
    t3 = svc.submit(session, 'subscribe', encode_cursor(frontiers[1]))
    svc.pump()
    assert len(t3.result['changes']) == 2


def test_subscribe_hostile_cursor_fails_typed():
    from automerge_tpu.errors import InvalidCursor
    svc = make_service()
    session, _ = _edited_session(svc, rounds=1)
    t = svc.submit(session, 'subscribe', b'\x00garbage')
    svc.pump()
    assert t.status == 'error'
    assert isinstance(t.error, InvalidCursor)


def test_subscribe_bogus_cursor_resyncs_typed():
    from automerge_tpu.query import encode_cursor
    svc = make_service()
    session, _ = _edited_session(svc)
    t = svc.submit(session, 'subscribe', encode_cursor(['99' * 32]))
    svc.pump()
    assert t.status == 'ok'
    assert t.result['kind'] == 'resync'
    shadow = host_backend.init()
    shadow, _ = host_backend.apply_changes(shadow, t.result['changes'])
    assert bytes(host_backend.save(shadow)) == \
        bytes(session.handle['state'].save())


def test_subscription_push_is_first_shed():
    """Subscription pushes default to sub-priority: at brownout stage 3
    they shed (typed, cursor unmoved) while default-priority sync and
    apply keep flowing."""
    from automerge_tpu.errors import Overloaded
    svc = make_service()
    session, _ = _edited_session(svc, rounds=1)
    svc.brownout.stage = 3
    sub = svc.submit(session, 'subscribe')
    app = svc.submit(session, 'apply',
                     [_chained_change('dd' * 16, 1, [], 'x', 1)])
    sync = svc.submit(session, 'sync', None)
    svc.pump()
    assert sub.status == 'error'
    assert isinstance(sub.error, Overloaded)
    assert sub.error.shed is True
    assert session.sub_cursor == []       # a shed never advances it
    assert app.status == 'ok'
    assert sync.status == 'ok'
    # explicit priority keeps a subscription alive through the shed
    kept = svc.submit(session, 'subscribe', priority=2)
    svc.pump()
    assert kept.status == 'ok'


def test_subscription_tick_diff_reuse_across_requests():
    from automerge_tpu.query import query_stats
    svc = make_service()
    session, _ = _edited_session(svc)
    before = query_stats()['subscription_diff_reuse']
    tickets = [svc.submit(session, 'subscribe', []) for _ in range(6)]
    svc.pump()
    assert all(t.status == 'ok' for t in tickets)
    assert query_stats()['subscription_diff_reuse'] - before == 5
