"""Prometheus `_sum` exposition + perf-observatory gauges (ISSUE-13).

The round-14 torn-read contract said: cumulative buckets always agree
with the `_count` rendered on the same page. This file extends the pin
to `_sum`: every histogram family (plain registry histograms AND the
per-(tenant, kind) SLO latency histograms) renders a `_sum` line next
to `_count`, derived from the SAME consistently-copied snapshot — so
rate(..._sum[m]) / rate(..._count[m]) PromQL (rate-of-mean) is honest
under concurrent recording. Histogram.record updates the per-bucket
sums BEFORE the bucket counts and the exposition copies counts-sums-
counts with a stability retry, so the page's sum can never UNDERcount
the records its `_count` claims — the only allowed skew is the value
of a record still in flight, which the hammer test bounds exactly.

Also pinned here: the new perf-observatory gauge families (seam
baselines, kernel ledger, memory watermarks) render iff their switch
is on — no series churn for processes that never enable them.
"""

import threading

import pytest

from automerge_tpu.observability import hist as obs_hist
from automerge_tpu.observability import perf as obs_perf
from automerge_tpu.observability.export import render_prometheus
from automerge_tpu.observability.slo import SloPolicy, SloRegistry


@pytest.fixture(autouse=True)
def _clean():
    # watermark sampling is sticky by design (once sampled, the mem
    # gauges render); reset so the not-enabled assertions mean something
    obs_perf.reset_watermarks()
    yield
    obs_perf.disable_observatory()
    obs_hist.disable()
    obs_perf.reset_ledger()
    obs_perf.reset_watermarks()


def _parse_series(page):
    out = {}
    for line in page.splitlines():
        if not line or line.startswith('#'):
            continue
        name, _, value = line.rpartition(' ')
        out[name] = float(value)
    return out


def _bucket_bounds(series, prefix):
    """[(lo, hi, count_in_bucket)] from a page's cumulative buckets."""
    items = [(k, v) for k, v in series.items()
             if k.startswith(f'{prefix}_bucket')]
    lo = 0.0
    prev = 0.0
    out = []
    for key, cum in items:
        le = key.split('le="', 1)[1].rstrip('"}')
        hi = float('inf') if le == '+Inf' else float(le)
        out.append((lo, hi, cum - prev))
        lo, prev = hi, cum
    return out


def test_sum_next_to_count_for_every_histogram_family():
    h = obs_hist.histogram('sum_probe_s', scale=1e9, unit='s')
    h.record(0.25)
    h.record(0.75)
    reg = SloRegistry(policies={'latency': SloPolicy(0.99,
                                                    threshold_s=0.05)})
    reg.record('tenantA', 'apply', 0.004)
    reg.record('tenantA', 'apply', 0.006)
    reg.tick()
    page = render_prometheus(slo=reg)
    series = _parse_series(page)
    # plain registry histogram: _sum exact and beside _count
    assert series['automerge_tpu_sum_probe_s_count'] == 2
    assert series['automerge_tpu_sum_probe_s_sum'] == \
        pytest.approx(1.0, rel=1e-9)
    # per-(tenant, kind) SLO latency histogram: same contract
    key = ('automerge_tpu_slo_request_latency_seconds_sum'
           '{tenant="tenantA",kind="apply"}')
    assert series[key] == pytest.approx(0.010, rel=1e-9)
    assert series[key.replace('_sum', '_count')] == 2
    # page ordering: the _sum line sits in the histogram block, right
    # before its _count line (the PromQL-friendly shape)
    lines = [ln for ln in page.splitlines()
             if ln.startswith('automerge_tpu_sum_probe_s')]
    assert lines[-2].startswith('automerge_tpu_sum_probe_s_sum')
    assert lines[-1].startswith('automerge_tpu_sum_probe_s_count')


def test_sum_consistent_under_concurrent_recording():
    """The `_sum` twin of the round-14 torn-read hammer: while a writer
    records, every rendered page must satisfy (a) +Inf bucket == count,
    (b) sum >= the bucketwise LOWER bound of the counted records, and
    (c) sum <= the bucketwise UPPER bound plus at most ONE in-flight
    value (sums update before counts; one writer => one in-flight)."""
    h = obs_hist.histogram('sum_torn_probe', scale=1, unit='B')
    stop = threading.Event()
    max_value = 1000.0

    def hammer():
        v = 1
        while not stop.is_set():
            h.record(1.0 + (v % 1000))
            v += 1

    writer = threading.Thread(target=hammer, daemon=True)
    writer.start()
    try:
        for _ in range(50):
            series = _parse_series(render_prometheus())
            prefix = 'automerge_tpu_sum_torn_probe'
            count = series[f'{prefix}_count']
            total = series[f'{prefix}_sum']
            assert series[f'{prefix}_bucket{{le="+Inf"}}'] == count
            buckets = _bucket_bounds(series, prefix)
            lower = sum(lo * n for lo, _, n in buckets)
            upper = sum(min(hi, max_value + 1) * n
                        for _, hi, n in buckets)
            assert total >= lower - 1e-6, (total, lower)
            assert total <= upper + max_value + 1 + 1e-6, (total, upper)
    finally:
        stop.set()
        writer.join(timeout=5)


def test_perf_gauges_render_only_when_enabled():
    page_off = render_prometheus()
    assert 'perf_drift_ratio' not in page_off
    assert 'automerge_tpu_mem_bytes' not in page_off
    reg = obs_perf.enable_observatory()
    for _ in range(2 * reg.window_events):
        reg.record('apply_batch', 0.05)
    reg.tick()
    page = render_prometheus()
    series = _parse_series(page)
    assert series['automerge_tpu_perf_drift_ratio{seam="apply_batch"}'] \
        == pytest.approx(1.0)
    assert series[
        'automerge_tpu_perf_window_seconds{seam="apply_batch"}'] == \
        pytest.approx(0.05)
    assert series[
        'automerge_tpu_perf_alert_active{seam="apply_batch"}'] == 0
    # memory watermarks: rss current + high present once sampled
    assert series['automerge_tpu_mem_bytes{tier="rss"}'] > 0
    assert series['automerge_tpu_mem_high_bytes{tier="rss"}'] >= \
        series['automerge_tpu_mem_bytes{tier="rss"}']


def test_kernel_ledger_gauges_render():
    import jax
    import jax.numpy as jnp
    fn = obs_perf.instrument_kernel('export_probe_kernel',
                                    jax.jit(lambda x: x * 3))
    obs_perf.enable_ledger()
    fn(jnp.arange(4))
    fn(jnp.arange(4))
    series = _parse_series(render_prometheus())
    key = ('automerge_tpu_kernel_dispatches_total'
           '{kernel="export_probe_kernel"}')
    assert series[key] == 2
    assert series[key.replace('dispatches_total', 'seconds_total')] > 0


def test_shard_label_composes_with_perf_gauges():
    reg = obs_perf.enable_observatory()
    for _ in range(reg.window_events):
        reg.record('sync_round', 0.01)
    reg.tick()
    page = render_prometheus(shard='s7')
    assert ('automerge_tpu_perf_drift_ratio{shard="s7",'
            'seam="sync_round"}') in page
    assert 'automerge_tpu_mem_bytes{shard="s7",tier="rss"}' in page


class _FlipPolicy:
    """A synthetic policy that decides every window and alternates
    direction — so decisions, reversals, and active-state all move on
    every tick (the worst case for a concurrent scrape)."""

    name = 'probe'

    def __init__(self):
        self.n = 0

    def decide(self, sig):
        self.n += 1
        return [{'policy': self.name, 'action': 'nudge',
                 'target': 'tenant:t0',
                 'direction': 'up' if self.n % 2 else 'down',
                 'detail': {'n': self.n}}]

    def active(self):
        return {'tenant:t0': self.n}


def test_control_gauges_consistent_under_hammer():
    """The controller twin of the torn-read hammer: a pump thread
    committing a decision (with a reversal) every tick, a scraper
    rendering pages. Every page must satisfy the invariants the
    controller lock guarantees: decisions and reversals move TOGETHER
    (flip policy => reversals == decisions - 1 exactly), windows trails
    decisions by at most one, and both are monotonic across scrapes."""
    from automerge_tpu.control import Controller
    ctrl = Controller(mode='shadow', window=1,
                      policies=[_FlipPolicy()])
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            ctrl.tick()

    writer = threading.Thread(target=pump, daemon=True)
    writer.start()
    try:
        prev_d = prev_w = 0.0
        dkey = ('automerge_tpu_control_decisions_total'
                '{policy="probe",action="nudge",mode="shadow"}')
        for _ in range(50):
            series = _parse_series(render_prometheus(control=ctrl))
            d = series.get(dkey, 0.0)
            w = series['automerge_tpu_control_windows_total']
            r = series.get(
                'automerge_tpu_control_reversals_total{policy="probe"}',
                0.0)
            if d >= 1:
                assert r == d - 1, (r, d)
            assert w <= d <= w + 1, (d, w)
            assert d >= prev_d and w >= prev_w, (d, prev_d, w, prev_w)
            prev_d, prev_w = d, w
    finally:
        stop.set()
        writer.join(timeout=5)


def test_control_series_compose_with_shard_label():
    from automerge_tpu.control import Controller
    ctrl = Controller(mode='shadow', window=1,
                      policies=[_FlipPolicy()])
    ctrl.tick()
    page = render_prometheus(shard='s3', control=ctrl)
    assert 'automerge_tpu_control_windows_total{shard="s3"}' in page
    assert ('automerge_tpu_control_decisions_total{shard="s3",'
            'policy="probe",action="nudge",mode="shadow"}') in page
    assert ('automerge_tpu_control_policy_active{shard="s3",'
            'policy="probe",target="tenant:t0"}') in page
    assert ('automerge_tpu_control_decide_seconds{shard="s3",'
            'window="last"}') in page
    # and the family is absent entirely when no controller is wired
    assert 'control_windows_total' not in render_prometheus()
