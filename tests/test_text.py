"""Text CRDT conformance tests (ported semantics of reference
test/text_test.js: editing, control characters, spans, elemIds)."""

import json

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.frontend import Text


def fresh_pair():
    s1 = am.change(am.init(), lambda d: d.update({'text': Text()}))
    s2 = am.load(am.save(s1))
    return s1, s2


class TestTextEditing:
    def test_insertion(self):
        s1, _ = fresh_pair()
        s1 = am.change(s1, lambda d: d['text'].insert_at(0, 'a'))
        actor = am.get_actor_id(s1)
        assert len(s1['text']) == 1
        assert s1['text'].get(0) == 'a'
        assert str(s1['text']) == 'a'
        assert s1['text'].get_elem_id(0) == f'2@{actor}'

    def test_deletion(self):
        s1, _ = fresh_pair()
        s1 = am.change(s1, lambda d: d['text'].insert_at(0, 'a', 'b', 'c'))
        s1 = am.change(s1, lambda d: d['text'].delete_at(1, 1))
        assert len(s1['text']) == 2
        assert s1['text'].get(0) == 'a'
        assert s1['text'].get(1) == 'c'
        assert str(s1['text']) == 'ac'

    def test_implicit_and_explicit_deletion(self):
        s1, _ = fresh_pair()
        s1 = am.change(s1, lambda d: d['text'].insert_at(0, 'a', 'b', 'c'))
        s1 = am.change(s1, lambda d: d['text'].delete_at(1))
        s1 = am.change(s1, lambda d: d['text'].delete_at(1, 0))
        assert len(s1['text']) == 2
        assert str(s1['text']) == 'ac'

    def test_concurrent_insertion(self):
        s1, s2 = fresh_pair()
        s1 = am.change(s1, lambda d: d['text'].insert_at(0, 'a', 'b', 'c'))
        s2 = am.change(s2, lambda d: d['text'].insert_at(0, 'x', 'y', 'z'))
        s1 = am.merge(s1, s2)
        assert len(s1['text']) == 6
        assert str(s1['text']) in ('abcxyz', 'xyzabc')

    def test_text_and_other_ops_in_same_change(self):
        s1, _ = fresh_pair()

        def edit(d):
            d['foo'] = 'bar'
            d['text'].insert_at(0, 'a')
        s1 = am.change(s1, edit)
        assert s1['foo'] == 'bar'
        assert str(s1['text']) == 'a'

    def test_json_serializes_as_string(self):
        s1, _ = fresh_pair()
        s1 = am.change(s1, lambda d: d['text'].insert_at(0, 'a', '"', 'b'))
        assert json.dumps(s1.to_py()) == '{"text": "a\\"b"}'

    def test_modification_before_assignment(self):
        def edit(d):
            text = Text()
            text.insert_at(0, 'a', 'b', 'c', 'd')
            text.delete_at(2)
            d['text'] = text
        s1 = am.change(am.init(), edit)
        assert str(s1['text']) == 'abd'

    def test_modification_after_assignment(self):
        def edit(d):
            d['text'] = Text()
            d['text'].insert_at(0, 'a', 'b', 'c', 'd')
            d['text'].delete_at(2)
        s1 = am.change(am.init(), edit)
        assert str(s1['text']) == 'abd'

    def test_no_modification_outside_change_callback(self):
        s1, _ = fresh_pair()
        with pytest.raises(TypeError, match='outside of a change block'):
            s1['text'].insert_at(0, 'x')
        with pytest.raises(TypeError, match='outside of a change block'):
            s1['text'].delete_at(0)


class TestInitialValue:
    def test_string_initial_value(self):
        s1 = am.change(am.init(), lambda d: d.update({'text': Text('init')}))
        assert len(s1['text']) == 4
        assert s1['text'].get(0) == 'i'
        assert str(s1['text']) == 'init'

    def test_array_initial_value(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text(['i', 'n', 'i', 't'])}))
        assert str(s1['text']) == 'init'

    def test_text_in_from(self):
        s1 = am.from_({'text': Text('init')})
        assert str(s1['text']) == 'init'

    def test_initial_value_encodes_as_change(self):
        s1 = am.change(am.init(), lambda d: d.update({'text': Text('init')}))
        changes = am.get_all_changes(s1)
        s2, _patch = am.apply_changes(am.init(), changes)
        assert str(s2['text']) == 'init'

    def test_immediate_access_in_callback(self):
        def edit(d):
            d['text'] = Text('init')
            assert len(d['text']) == 4
            assert str(d['text']) == 'init'
        am.change(am.init(), edit)

    def test_pre_assignment_modification(self):
        def edit(d):
            text = Text('init')
            text.delete_at(3)
            text.insert_at(0, 'I', 'n', 'i', 't', 'i', 'a', 'l', ' ')
            text.delete_at(8, 3)
            d['text'] = text
        s1 = am.change(am.init(), edit)
        assert str(s1['text']) == 'Initial '
        s2 = am.load(am.save(s1))
        assert str(s2['text']) == 'Initial '

    def test_post_assignment_modification(self):
        def edit(d):
            d['text'] = Text('init')
            d['text'].delete_at(0)
            d['text'].insert_at(0, 'I')
        s1 = am.change(am.init(), edit)
        assert str(s1['text']) == 'Init'
        s2 = am.load(am.save(s1))
        assert str(s2['text']) == 'Init'


class TestControlCharacters:
    def make(self):
        def edit(d):
            d['text'] = Text()
            d['text'].insert_at(0, 'a')
            d['text'].insert_at(1, {'attribute': 'bold'})
        return am.change(am.init(), edit)

    def test_fetch_non_textual(self):
        s1 = self.make()
        actor = am.get_actor_id(s1)
        assert s1['text'].get(1) == {'attribute': 'bold'}
        assert s1['text'].get_elem_id(1) == f'3@{actor}'

    def test_control_chars_in_length(self):
        s1 = self.make()
        assert len(s1['text']) == 2
        assert s1['text'].get(0) == 'a'

    def test_excluded_from_str(self):
        s1 = self.make()
        assert str(s1['text']) == 'a'

    def test_control_char_update(self):
        s1 = self.make()
        s2 = am.change(s1, lambda d: d['text'][1].update({'attribute': 'italic'}))
        s3 = am.load(am.save(s2))
        assert s1['text'].get(1)['attribute'] == 'bold'
        assert s2['text'].get(1)['attribute'] == 'italic'
        assert s3['text'].get(1)['attribute'] == 'italic'


class TestSpans:
    def test_simple_string_single_span(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('hello world')}))
        assert s1['text'].to_spans() == ['hello world']

    def test_empty_string_empty_spans(self):
        s1 = am.change(am.init(), lambda d: d.update({'text': Text()}))
        assert s1['text'].to_spans() == []

    def test_split_at_control_character(self):
        def edit(d):
            d['text'] = Text('hello world')
            d['text'].insert_at(5, {'attributes': {'bold': True}})
        s1 = am.change(am.init(), edit)
        assert s1['text'].to_spans() == \
            ['hello', {'attributes': {'bold': True}}, ' world']

    def test_consecutive_control_characters(self):
        def edit(d):
            d['text'] = Text('hello world')
            d['text'].insert_at(5, {'attributes': {'bold': True}})
            d['text'].insert_at(6, {'attributes': {'italic': True}})
        s1 = am.change(am.init(), edit)
        assert s1['text'].to_spans() == \
            ['hello', {'attributes': {'bold': True}},
             {'attributes': {'italic': True}}, ' world']

    def test_control_char_at_text_start(self):
        def edit(d):
            d['text'] = Text('hello')
            d['text'].insert_at(0, {'attributes': {'bold': True}})
        s1 = am.change(am.init(), edit)
        assert s1['text'].to_spans() == [{'attributes': {'bold': True}}, 'hello']


class TestLongEditTrace:
    def test_editing_trace_convergence(self):
        """Simulated multi-actor editing trace with interleaved inserts and
        deletes converges across merge (ref test/text_test.js editing-trace
        style, scaled down)."""
        import random
        rnd = random.Random(42)
        s1 = am.change(am.init('aa01'), lambda d: d.update({'text': Text('seed')}))
        s2 = am.load(am.save(s1), 'bb02')

        def mutate(s, rnd):
            def edit(d):
                t = d['text']
                for _ in range(5):
                    if len(t) > 2 and rnd.random() < 0.4:
                        t.delete_at(rnd.randrange(len(t)))
                    else:
                        t.insert_at(rnd.randrange(len(t) + 1),
                                    rnd.choice('abcdefgh'))
            return am.change(s, edit)

        for _ in range(6):
            s1 = mutate(s1, rnd)
            s2 = mutate(s2, rnd)
        m1 = am.merge(s1, s2)
        m2 = am.merge(s2, m1)
        assert str(m1['text']) == str(m2['text'])
        assert len(m1['text']) > 0


# --- Quill delta interop helpers (ref text_test.js:5-196) ---------------

def _attribute_state_to_attributes(accumulated):
    attributes = {}
    for key, values in accumulated.items():
        if values and values[0] is not None:
            attributes[key] = values[0]
    return attributes


def _is_control_marker(pseudo_char):
    return isinstance(pseudo_char, dict) and 'attributes' in pseudo_char


def _op_from(text, attributes):
    op = {'insert': text}
    if attributes:
        op['attributes'] = attributes
    return op


def _accumulate_attributes(span, accumulated):
    for key, value in span.items():
        if key not in accumulated:
            accumulated[key] = []
        if value is None:
            if not accumulated[key]:
                accumulated[key].insert(0, None)
            else:
                accumulated[key].pop(0)
        else:
            if accumulated[key] and accumulated[key][0] is None:
                accumulated[key].pop(0)
            else:
                accumulated[key].insert(0, value)
    return accumulated


def _plain(value):
    """Deep-convert document views into plain dicts/lists for helpers."""
    if hasattr(value, 'items'):
        return {k: _plain(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_plain(v) for v in value]
    return value


def automerge_text_to_delta_doc(text):
    ops = []
    control_state = {}
    current_string = ''
    attributes = {}
    for span in text.to_spans():
        span = _plain(span)
        if _is_control_marker(span):
            control_state = _accumulate_attributes(
                span['attributes'], control_state)
        else:
            next_attrs = _attribute_state_to_attributes(control_state)
            if isinstance(span, str) and next_attrs == attributes:
                current_string += span
                continue
            if current_string:
                ops.append(_op_from(current_string, attributes))
            if isinstance(span, str):
                current_string = span
                attributes = next_attrs
            else:
                ops.append(_op_from(span, next_attrs))
                current_string = ''
                attributes = {}
    if current_string:
        ops.append(_op_from(current_string, attributes))
    return ops


def _inverse_attributes(attributes):
    return {key: None for key in attributes}


def _apply_delete_op(text, offset, op):
    length = op['delete']
    while length > 0:
        if _is_control_marker(_plain(text.get(offset))):
            offset += 1
        else:
            text.delete_at(offset, 1)
            length -= 1
    return offset


def _apply_retain_op(text, offset, op):
    length = op['retain']
    if op.get('attributes'):
        text.insert_at(offset, {'attributes': op['attributes']})
        offset += 1
    while length > 0:
        char = _plain(text.get(offset))
        offset += 1
        if not _is_control_marker(char):
            length -= 1
    if op.get('attributes'):
        text.insert_at(offset, {'attributes':
                                _inverse_attributes(op['attributes'])})
        offset += 1
    return offset


def _apply_insert_op(text, offset, op):
    original_offset = offset
    if isinstance(op['insert'], str):
        text.insert_at(offset, *list(op['insert']))
        offset += len(op['insert'])
    else:
        text.insert_at(offset, op['insert'])
        offset += 1
    if op.get('attributes'):
        text.insert_at(original_offset, {'attributes': op['attributes']})
        offset += 1
        text.insert_at(offset, {'attributes':
                                _inverse_attributes(op['attributes'])})
        offset += 1
    return offset


def apply_delta_doc_to_automerge_text(delta, doc):
    offset = 0
    for op in delta:
        if 'retain' in op:
            offset = _apply_retain_op(doc['text'], offset, op)
        elif 'delete' in op:
            offset = _apply_delete_op(doc['text'], offset, op)
        elif 'insert' in op:
            offset = _apply_insert_op(doc['text'], offset, op)


class TestQuillDeltaInterop:
    """ref text_test.js:445-689"""

    def test_convertable_into_quill_delta(self):
        def edit(d):
            d['text'] = Text('Gandalf the Grey')
            d['text'].insert_at(0, {'attributes': {'bold': True}})
            d['text'].insert_at(7 + 1, {'attributes': {'bold': None}})
            d['text'].insert_at(12 + 2, {'attributes': {'color': '#cccccc'}})
        s1 = am.change(am.init(), edit)
        assert automerge_text_to_delta_doc(s1['text']) == [
            {'insert': 'Gandalf', 'attributes': {'bold': True}},
            {'insert': ' the '},
            {'insert': 'Grey', 'attributes': {'color': '#cccccc'}}]

    def test_delta_supports_embeds(self):
        def edit(d):
            d['text'] = Text('')
            d['text'].insert_at(0, {'attributes':
                                    {'link': 'https://quilljs.com'}})
            d['text'].insert_at(1, {
                'image': 'https://quilljs.com/assets/images/icon.png'})
            d['text'].insert_at(2, {'attributes': {'link': None}})
        s1 = am.change(am.init(), edit)
        assert automerge_text_to_delta_doc(s1['text']) == [{
            'insert': {'image': 'https://quilljs.com/assets/images/icon.png'},
            'attributes': {'link': 'https://quilljs.com'}}]

    def test_concurrent_overlapping_spans(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('Gandalf the Grey')}))
        s2 = am.merge(am.init(), s1)

        def bold_8_16(d):
            d['text'].insert_at(8, {'attributes': {'bold': True}})
            d['text'].insert_at(16 + 1, {'attributes': {'bold': None}})
        s3 = am.change(s1, bold_8_16)

        def bold_0_11(d):
            d['text'].insert_at(0, {'attributes': {'bold': True}})
            d['text'].insert_at(11 + 1, {'attributes': {'bold': None}})
        s4 = am.change(s2, bold_0_11)
        merged = am.merge(s3, s4)
        assert automerge_text_to_delta_doc(merged['text']) == [
            {'insert': 'Gandalf the Grey', 'attributes': {'bold': True}}]

    def test_debolding_spans(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('Gandalf the Grey')}))
        s2 = am.merge(am.init(), s1)

        def bold_all(d):
            d['text'].insert_at(0, {'attributes': {'bold': True}})
            d['text'].insert_at(16 + 1, {'attributes': {'bold': None}})
        s3 = am.change(s1, bold_all)

        def debold_8_11(d):
            d['text'].insert_at(8, {'attributes': {'bold': None}})
            d['text'].insert_at(11 + 1, {'attributes': {'bold': True}})
        s4 = am.change(s2, debold_8_11)
        merged = am.merge(s3, s4)
        assert automerge_text_to_delta_doc(merged['text']) == [
            {'insert': 'Gandalf ', 'attributes': {'bold': True}},
            {'insert': 'the'},
            {'insert': ' Grey', 'attributes': {'bold': True}}]

    def test_destyling_across_destyled_spans(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('Gandalf the Grey')}))
        s2 = am.merge(am.init(), s1)

        def bold_all(d):
            d['text'].insert_at(0, {'attributes': {'bold': True}})
            d['text'].insert_at(16 + 1, {'attributes': {'bold': None}})
        s3 = am.change(s1, bold_all)

        def debold_8_11(d):
            d['text'].insert_at(8, {'attributes': {'bold': None}})
            d['text'].insert_at(11 + 1, {'attributes': {'bold': True}})
        s4 = am.change(s2, debold_8_11)
        merged = am.merge(s3, s4)

        def final_edit(d):
            d['text'].insert_at(3 + 1, {'attributes': {'bold': None}})
            d['text'].insert_at(len(d['text']), {'attributes': {'bold': True}})
        final = am.change(merged, final_edit)
        assert automerge_text_to_delta_doc(final['text']) == [
            {'insert': 'Gan', 'attributes': {'bold': True}},
            {'insert': 'dalf the Grey'}]

    def test_apply_an_insert(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('Hello world')}))
        delta = [{'retain': 6}, {'insert': 'reader'}, {'delete': 5}]
        s2 = am.change(s1,
                       lambda d: apply_delta_doc_to_automerge_text(delta, d))
        assert str(s2['text']) == 'Hello reader'

    def test_apply_insert_with_control_characters(self):
        s1 = am.change(am.init(),
                       lambda d: d.update({'text': Text('Hello world')}))
        delta = [
            {'retain': 6},
            {'insert': 'reader', 'attributes': {'bold': True}},
            {'delete': 5},
            {'insert': '!'}]
        s2 = am.change(s1,
                       lambda d: apply_delta_doc_to_automerge_text(delta, d))
        assert str(s2['text']) == 'Hello reader!'
        assert [_plain(s) for s in s2['text'].to_spans()] == [
            'Hello ',
            {'attributes': {'bold': True}},
            'reader',
            {'attributes': {'bold': None}},
            '!']

    def test_control_characters_in_retain_delete_lengths(self):
        def setup(d):
            d['text'] = Text('Hello world')
            d['text'].insert_at(4, {'attributes': {'color': '#ccc'}})
            d['text'].insert_at(10, {'attributes': {'color': '#f00'}})
        s1 = am.change(am.init(), setup)
        delta = [
            {'retain': 6},
            {'insert': 'reader', 'attributes': {'bold': True}},
            {'delete': 5},
            {'insert': '!'}]
        s2 = am.change(s1,
                       lambda d: apply_delta_doc_to_automerge_text(delta, d))
        assert str(s2['text']) == 'Hello reader!'
        assert [_plain(s) for s in s2['text'].to_spans()] == [
            'Hell',
            {'attributes': {'color': '#ccc'}},
            'o ',
            {'attributes': {'bold': True}},
            'reader',
            {'attributes': {'bold': None}},
            {'attributes': {'color': '#f00'}},
            '!']

    def test_apply_delta_supports_embeds(self):
        s1 = am.change(am.init(), lambda d: d.update({'text': Text('')}))
        delta = [{
            'insert': {'image': 'https://quilljs.com/assets/images/icon.png'},
            'attributes': {'link': 'https://quilljs.com'}}]
        s2 = am.change(s1,
                       lambda d: apply_delta_doc_to_automerge_text(delta, d))
        assert [_plain(s) for s in s2['text'].to_spans()] == [
            {'attributes': {'link': 'https://quilljs.com'}},
            {'image': 'https://quilljs.com/assets/images/icon.png'},
            {'attributes': {'link': None}}]


class TestTextUnicode:
    """ref text_test.js:691-696"""

    def test_unicode_when_creating_text(self):
        s1 = am.from_({'text': Text('🐦')})
        assert s1['text'].get(0) == '🐦'
