"""Multi-core native codec: the determinism, pipelining, and thread-safety
contract (BASELINE.md "Multi-core contract").

Parallel parse output must be byte-identical to
``AUTOMERGE_TPU_NATIVE_THREADS=1`` at EVERY pool width — same column
arrays, hashes, interned key/actor table order, pred/value arenas, and
the same all-or-nothing verdicts over hostile bytes (fuzz-corpus mutants
replayed through the threaded path). The pipelined turbo driver must
commit state byte-identical to the plain call, with the span rig showing
the prefetched parse genuinely overlapping the previous sub-batch."""

import os
import random
import sys

import numpy as np
import pytest

from automerge_tpu import native, observability

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', 'tools'))

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native toolchain unavailable')

POOL_WIDTHS = (1, 2, 4, 8)


@pytest.fixture(autouse=True)
def _restore_threads():
    prev = native.native_threads()
    yield
    native.set_native_threads(prev)


def _chain(n_changes, n_keys=40, seed=0):
    """A linear change chain (two alternating actors) of flat int sets."""
    from automerge_tpu.columnar import decode_change_meta, encode_change
    rng = random.Random(seed)
    actors = ['aa' * 16, 'bb' * 16]
    changes, heads, seqs = [], [], [0, 0]
    for c in range(n_changes):
        a = c % 2
        seqs[a] += 1
        buf = encode_change({
            'actor': actors[a], 'seq': seqs[a], 'startOp': c + 1,
            'time': 0, 'message': f'm{c}' if c % 5 == 0 else '',
            'deps': heads,
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{rng.randrange(n_keys)}',
                     'value': rng.randrange(1, 1 << 20),
                     'datatype': 'int', 'pred': []}]})
        heads = [decode_change_meta(buf, True)['hash']]
        changes.append(buf)
    return changes


def _rich_changes():
    """Changes exercising the full with_seq surface: text/list/nested
    maps, strings, floats, bools, counters — boxed values, seq ops,
    makes, preds, multi-actor merges."""
    import automerge_tpu as A
    d = A.init('aa' * 16)
    d = A.change(d, {'time': 0}, lambda r: r.update(
        {'text': A.Text('parallel parse'), 'list': [1, 2, 3],
         'nested': {'k': 'v', 'n': 7}, 'count': A.Counter(3)}))
    d = A.change(d, {'time': 0}, lambda r: r.update(
        {'big': 'x' * 300, 'f': 2.5, 'b': True, 'neg': -12}))
    e = A.merge(A.init('bb' * 16), d)
    e = A.change(e, {'time': 0}, lambda r: r['list'].append(99))
    d = A.merge(d, e)
    d = A.change(d, {'time': 0}, lambda r: r['count'].increment(2))
    return [bytes(c) for c in A.get_all_changes(d)]


def _snapshot(out):
    """Every array/list/blob of an ingest result, normalized to bytes."""
    if out is None:
        return None
    rows, keys, actors = out[0], out[1], out[2]
    snap = {k: (v.tobytes() if hasattr(v, 'tobytes') else bytes(v))
            for k, v in rows.items()}
    snap['_keys'] = tuple(keys)
    snap['_actors'] = tuple(actors)
    if len(out) > 3:
        for k, v in out[3].items():
            snap['meta_' + k] = v.tobytes() if hasattr(v, 'tobytes') else v
    return snap


def _assert_same_snapshot(a, b, label):
    if a is None or b is None:
        assert a is None and b is None, f'{label}: verdict differs'
        return
    assert a.keys() == b.keys(), f'{label}: column sets differ'
    for k in a:
        assert a[k] == b[k], f'{label}: column {k!r} differs'


class TestParallelDeterminism:
    def test_flat_chain_byte_identical_at_every_width(self):
        bufs = _chain(400) * 25          # 10k buffers, doc i = buffer i
        native.set_native_threads(1)
        ref = _snapshot(native.ingest_changes(
            bufs, None, with_meta=True, with_seq=True))
        assert ref is not None
        for width in POOL_WIDTHS[1:]:
            native.set_native_threads(width)
            got = _snapshot(native.ingest_changes(
                bufs, None, with_meta=True, with_seq=True))
            _assert_same_snapshot(ref, got, f'width {width}')

    def test_rich_ops_byte_identical_at_every_width(self):
        # boxed values / seq ops / preds / multi-actor tables stress the
        # merge's id remapping (keys, actors, packed opIds, pred arenas)
        bufs = _rich_changes() * 200
        native.set_native_threads(1)
        ref = _snapshot(native.ingest_changes(
            bufs, None, with_meta=True, with_seq=True))
        assert ref is not None
        for width in POOL_WIDTHS[1:]:
            native.set_native_threads(width)
            got = _snapshot(native.ingest_changes(
                bufs, None, with_meta=True, with_seq=True))
            _assert_same_snapshot(ref, got, f'width {width}')

    def test_blob_entry_matches_list_entry(self):
        # the CDLL blob path (explicit doc_ids) and the zero-copy PyDLL
        # list path must agree at every width
        bufs = _chain(64) * 4
        native.set_native_threads(4)
        via_list = _snapshot(native.ingest_changes(
            bufs, None, with_meta=True, with_seq=True))
        via_blob = _snapshot(native.ingest_changes(
            bufs, list(range(len(bufs))), with_meta=True, with_seq=True))
        _assert_same_snapshot(via_list, via_blob, 'list vs blob')

    def test_fuzz_corpus_hostile_bytes_same_verdict(self):
        """Mutants of real wire changes replayed through the threaded
        path: the (all-or-nothing) parse verdict AND, when accepted, the
        full output must match the single-threaded parse — a worker
        thread failing a poisoned chunk while siblings succeed must not
        change what the caller observes."""
        from fuzz_wire import build_corpus, mutate
        corpus = build_corpus()
        good = corpus['change']
        rng = random.Random(1234)
        # case 0 is unmutated (verdict: accepted) so the sweep provably
        # exercises both verdicts even when every mutant breaks the parse
        cases = [[bytes(b) for b in good] * 2]
        for _ in range(60):
            base = good[rng.randrange(len(good))]
            cases.append([bytes(b) for b in good] +
                         [mutate(rng, base)] +
                         [bytes(b) for b in good])
        verdicts = []
        for ci, bufs in enumerate(cases):
            native.set_native_threads(1)
            ref = _snapshot(native.ingest_changes(
                bufs, None, with_meta=True, with_seq=True))
            verdicts.append(ref is not None)
            for width in (4, 8):
                native.set_native_threads(width)
                got = _snapshot(native.ingest_changes(
                    bufs, None, with_meta=True, with_seq=True))
                _assert_same_snapshot(ref, got, f'case {ci} width {width}')
        # the corpus must exercise BOTH verdicts or the test proves nothing
        assert any(verdicts) and not all(verdicts)

    def test_sha256_batch_parallel_identical(self):
        import hashlib
        bufs = [os.urandom(i % 513 + 1) for i in range(500)]
        expect = [hashlib.sha256(b).digest() for b in bufs]
        for width in POOL_WIDTHS:
            native.set_native_threads(width)
            assert native.sha256_batch(bufs) == expect, f'width {width}'


class TestPoolPlumbing:
    def test_abi_stamp_matches(self):
        assert native._abi_of(native._load()) == native._ABI_VERSION

    def test_set_native_threads_roundtrip(self):
        prev = native.set_native_threads(3)
        assert native.native_threads() == 3
        native.set_native_threads(prev)

    def test_pool_tasks_counter_moves(self):
        native.set_native_threads(4)
        before = native.pool_stats()['tasks']
        native.ingest_changes(_chain(200), None, with_meta=True,
                              with_seq=True)
        stats = native.pool_stats()
        assert stats['tasks'] > before
        assert stats['busy_s'] > 0.0
        assert observability.health_counts()['native_pool_tasks'] == \
            stats['tasks']

    def test_parse_chunk_spans_and_histograms(self):
        """Per-slice parse spans + parse_chunk_s / parse_pool_occupancy
        histograms land when observability is on (the obs_report pool
        view's feed)."""
        native.set_native_threads(4)
        observability.enable()
        try:
            observability.clear_spans()
            native.ingest_changes(_chain(300), None, with_meta=True,
                                  with_seq=True)
            spans = observability.iter_spans()
            chunk = [s for s in spans if s['name'] == 'parse_chunk']
            assert chunk, 'no parse_chunk spans recorded'
            assert all(s['attrs']['chunks'] > 0 for s in chunk)
            parent = [s for s in spans if s['name'] == 'native_parse']
            assert parent and parent[-1]['attrs']['threads'] == 4
            # slices tile inside the parent parse interval
            lo = min(s['t0_ns'] for s in chunk)
            hi = max(s['t1_ns'] for s in chunk)
            assert lo >= parent[-1]['t0_ns'] - 1_000_000
            assert hi <= parent[-1]['t1_ns'] + 1_000_000
            hists = observability.histogram_snapshot()
            assert hists['parse_chunk_s']['count'] >= len(chunk)
            assert hists['parse_pool_occupancy']['count'] >= 1
        finally:
            observability.disable()


class TestPipelinedApply:
    def _workload(self, n_docs, n_changes):
        chain = _chain(n_changes, n_keys=16, seed=5)
        return [list(chain) for _ in range(n_docs)]

    def test_pipelined_commits_byte_identical_state(self):
        from automerge_tpu.fleet.backend import (
            DocFleet, apply_changes_docs, apply_changes_docs_pipelined,
            init_docs, materialize_docs, save)
        per_doc = self._workload(60, 9)
        plain = DocFleet()
        ph = init_docs(60, plain)
        ph, _ = apply_changes_docs(ph, per_doc, mirror=False)
        for subs in (2, 3, 4):
            fleet = DocFleet()
            handles = init_docs(60, fleet)
            handles, _ = apply_changes_docs_pipelined(
                handles, per_doc, sub_batches=subs)
            assert materialize_docs(handles) == materialize_docs(ph)
            for i in (0, 31, 59):
                assert bytes(save(handles[i])) == bytes(save(ph[i])), \
                    f'doc {i} save bytes differ at sub_batches={subs}'

    def test_pipelined_single_dispatch_per_sub_batch(self):
        from automerge_tpu.fleet.backend import (
            DocFleet, apply_changes_docs_pipelined, init_docs)
        fleet = DocFleet()
        handles = init_docs(40, fleet)
        # warm the dispatch shape so the counted run is steady-state
        apply_changes_docs_pipelined(handles, self._workload(40, 4),
                                     sub_batches=2)
        fleet2 = DocFleet()
        handles2 = init_docs(40, fleet2)
        d0 = fleet2.metrics.dispatches
        apply_changes_docs_pipelined(handles2, self._workload(40, 4),
                                     sub_batches=2)
        assert fleet2.metrics.dispatches - d0 == 2   # one per sub-batch

    def test_pipelined_producer_failure_propagates(self, monkeypatch):
        """A producer-thread parse failure must raise in the caller, not
        hang the consumer's queue.get() forever."""
        from automerge_tpu.fleet import backend as fleet_backend
        from automerge_tpu.fleet.backend import (
            DocFleet, apply_changes_docs_pipelined, init_docs)

        def boom(*a, **k):
            raise RuntimeError('producer parse died')

        monkeypatch.setattr(fleet_backend.native, 'ingest_changes', boom)
        fleet = DocFleet()
        handles = init_docs(8, fleet)
        with pytest.raises(RuntimeError, match='producer parse died'):
            apply_changes_docs_pipelined(handles, self._workload(8, 4),
                                         sub_batches=2)

    def test_pipelined_parse_overlaps_previous_sub_batch(self):
        """The span rig must show the producer thread's parse running
        concurrently with the previous sub-batch's apply phases — the
        overlap the Perfetto trace renders as parallel tracks. Retries a
        few times before failing: genuine overlap is a scheduling fact,
        not a logical invariant, and a loaded CI box can starve one
        attempt."""
        from automerge_tpu.fleet.backend import (
            DocFleet, apply_changes_docs_pipelined, init_docs)
        per_doc = self._workload(800, 8)
        main_tid = None
        for attempt in range(3):
            fleet = DocFleet()
            handles = init_docs(800, fleet)
            observability.enable()
            observability.clear_spans()
            try:
                apply_changes_docs_pipelined(handles, per_doc,
                                             sub_batches=2)
                spans = observability.iter_spans()
            finally:
                observability.disable()
            applies = [s for s in spans if s['name'] == 'apply_batch']
            assert len(applies) == 2
            main_tid = applies[0]['tid']
            parses = [s for s in spans if s['name'] == 'native_parse'
                      and s['tid'] != main_tid]
            assert parses, 'parse never ran on the producer thread'
            overlap = 0
            for p in parses:
                for a in applies:
                    overlap += max(0, min(p['t1_ns'], a['t1_ns']) -
                                   max(p['t0_ns'], a['t0_ns']))
            # the main thread must never stall on a foreground parse
            # (structural: every sub-batch consumes a prefetched result)
            stalls = [s['dur_ns'] for s in spans
                      if s['name'] == 'turbo_parse']
            assert max(stalls) < 50_000_000, 'foreground parse stall'
            if overlap > 0:
                return
        pytest.fail('no parse/apply overlap in 3 attempts')


class TestMultiThreadedErrorPath:
    def test_count_bomb_stays_typed_at_every_width(self):
        """The -1/-2 malformed-vs-capacity split (PR 3's count-bomb fix)
        must hold when the poisoned column fails on a worker thread: the
        batch verdict is a clean refusal (None), never a crash or a
        multi-GB allocation, at every pool width."""
        def leb(v):
            out = bytearray()
            while True:
                b = v & 0x7F
                v >>= 7
                if v:
                    out.append(b | 0x80)
                else:
                    out.append(b)
                    return bytes(out)

        good = _chain(32)
        # a boolean column declaring ~2^62 values inside an otherwise
        # plausible chunk: the native parse must refuse it typed
        bomb = good[3][:20] + leb((1 << 62) + 7) + good[3][20:]
        bufs = good + [bomb] + good
        for width in POOL_WIDTHS:
            native.set_native_threads(width)
            assert native.ingest_changes(bufs, None, with_meta=True,
                                         with_seq=True) is None
