"""Delta+main storage engine (fleet/storage.py): park/revive round trips,
compute-on-compressed causal reads, columnar memory accounting, and the
1M-parked-docs-per-host ceiling (slow-marked).
"""

import os
import resource
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu.columnar import encode_change                 # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet.backend import DocFleet, init_docs      # noqa: E402
from automerge_tpu.fleet.storage import MainStore, StorageEngine  # noqa: E402


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _workload(fleet, n, rounds=2):
    handles = init_docs(n, fleet)
    for r in range(rounds):
        per_doc = [[_change(f'{d:04x}' * 4, r + 1, r + 1,
                            fleet_backend.get_heads(handles[d]),
                            f'k{r}', d * 10 + r)]
                   for d in range(n)]
        handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                      mirror=False)
    return handles


class TestStorageEngine:
    def test_park_revive_byte_identical(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 6)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        assert all(i is not None for i in ids)
        assert len(eng.main) == 6
        assert all(h.get('frozen') for h in handles)
        back = eng.revive(ids)
        assert [bytes(h['state'].save()) for h in back] == saves
        assert len(eng.main) == 0

    def test_park_frees_device_slots(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 5)
        slots = {h['state']._impl.slot for h in handles}
        eng.park(handles)
        assert slots <= set(fleet.free_slots)

    def test_causal_reads_match_live_state(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 4, rounds=3)
        live = [(sorted(h['state'].heads), dict(h['state'].clock),
                 h['state'].max_op) for h in handles]
        ids = eng.park(handles)
        for (heads, clock, max_op), r in zip(live, ids):
            assert eng.heads(r) == heads
            assert eng.clock(r) == clock
            assert eng.max_op(r) == max_op
            assert eng.n_changes(r) == 3

    def test_needs_sync_gate(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 2)
        heads = [list(h['state'].heads) for h in handles]
        ids = eng.park(handles)
        assert not eng.needs_sync(ids[0], heads[0])
        assert eng.needs_sync(ids[0], heads[1])
        assert eng.needs_sync(ids[0], [])
        assert eng.main.contains_head(ids[0], heads[0][0])
        assert not eng.main.contains_head(ids[0], 'ee' * 32)
        assert eng.main.covers_heads(ids[0], heads[0])

    def test_park_skips_queued_and_frozen(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 3)
        # doc 0: enqueue a causally-premature change (unknown dep)
        dangling = _change('ee' * 16, 2, 5, ['dd' * 32], 'q', 1)
        handles[0]['state'].apply_changes([dangling])
        handles[1]['frozen'] = True
        ids = eng.park(handles)
        assert ids[0] is None and ids[1] is None and ids[2] is not None
        assert not handles[0].get('frozen')     # stays live and usable

    def test_ingest_chunks_compute_on_compressed(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 4)
        saves = [bytes(h['state'].save()) for h in handles]
        live = [(sorted(h['state'].heads), dict(h['state'].clock),
                 h['state'].max_op) for h in handles]
        ids = eng.ingest_chunks(saves)
        for (heads, clock, max_op), r in zip(live, ids):
            assert eng.heads(r) == heads
            assert eng.clock(r) == clock
            assert eng.max_op(r) == max_op
        # revive from ingested chunks round-trips too
        back = eng.revive(ids[:2])
        assert [bytes(h['state'].save()) for h in back] == saves[:2]

    def test_ingest_rejects_hostile_chunk_typed(self):
        from automerge_tpu.errors import MalformedDocument
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 1)
        chunk = bytearray(bytes(handles[0]['state'].save()))
        chunk[5] ^= 0x10
        with pytest.raises(MalformedDocument):
            eng.ingest_chunks([bytes(chunk)])

    def test_vacuum_reclaims_discards(self):
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 8)
        ids = eng.park(handles)
        for r in ids[:4]:
            eng.main.discard(r)
        assert eng.main.dead_fraction == pytest.approx(0.5)
        keep = ids[4:]
        want = [(eng.heads(r), eng.clock(r), eng.max_op(r),
                 eng.main.chunk(r)) for r in keep]
        remap = eng.main.vacuum()
        assert sorted(remap) == sorted(keep)
        for (heads, clock, max_op, chunk), old in zip(want, keep):
            r = remap[old]
            assert eng.heads(r) == heads
            assert eng.clock(r) == clock
            assert eng.max_op(r) == max_op
            assert eng.main.chunk(r) == chunk
        assert eng.main.dead_fraction == 0.0

    def test_overhead_well_below_engine_resident_parking(self):
        """The acceptance signal at small scale: per-doc host overhead
        in the main store sits far under the ~3.3 KB/doc an in-fleet
        parked doc costs (BASELINE.md host-memory accounting)."""
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 256)
        eng.park(handles)
        stats = eng.memory_stats()
        assert stats['n_docs'] == 256
        assert stats['overhead_per_doc'] < 1024, stats

    def test_revive_through_durable_fleet_journals_baseline(self, tmp_path):
        from automerge_tpu.fleet.durability import DurableFleet
        fleet = DocFleet()
        eng = StorageEngine(fleet)
        handles = _workload(fleet, 3)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.park(handles)
        mgr = DurableFleet(str(tmp_path / 'dur'))
        eng2 = StorageEngine(mgr.fleet)
        eng2.adopt_main(eng)
        back = eng2.revive(ids, durable=mgr)
        assert [bytes(h['state'].save()) for h in back] == saves
        mgr.close()
        mgr2, rec, report = DurableFleet.recover(str(tmp_path / 'dur'))
        assert report.ok
        assert sorted(bytes(fleet_backend.save(h))
                      for h in rec.values()) == sorted(saves)
        mgr2.close()


@pytest.mark.slow
def test_million_parked_docs_resident(tmp_path):
    """1M parked docs resident on one host: distinct single-change docs
    bulk-ingested into the main store compute-on-compressed, with a
    memory ceiling assert on BOTH the store's own accounting and the
    process RSS high-water delta. Per-doc overhead must sit measurably
    below the ~3.3 KB/doc of in-fleet parked residency."""
    n = 1_000_000
    distinct = 2048
    fleet = DocFleet()
    eng = StorageEngine(fleet)
    handles = init_docs(distinct, fleet)
    per_doc = [[_change(f'{d % 128:04x}' * 4, 1, 1, [], f'k{d}', d)]
               for d in range(distinct)]
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    chunks = [bytes(h['state'].save()) for h in handles]
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KiB
    # distinct causal rows per doc (the chunks repeat; MainStore stores
    # each row's chunk by reference, so chunk bytes don't dominate and
    # the measured footprint is the per-doc OVERHEAD under test)
    for i in range(0, n, distinct):
        eng.ingest_chunks(chunks[:min(distinct, n - i)], check=(i == 0))
    assert len(eng.main) == n
    stats = eng.memory_stats()
    assert stats['overhead_per_doc'] < 512, stats
    # spot-check causal reads at the far end of the arrays (the last
    # ingest batch is a partial slice of `chunks`)
    view_id = n - 1
    last_chunk_idx = (n % distinct or distinct) - 1
    assert eng.n_changes(view_id) == 1
    assert eng.heads(view_id) == \
        sorted(handles[last_chunk_idx]['state'].heads)
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    grew_kib = rss1 - rss0
    # ceiling: 1M rows of causal state + lanes (+ interpreter slack)
    # must stay under 1 GiB of RSS growth — an in-fleet 3.3 KB/doc
    # residency would need >3.3 GiB
    assert grew_kib < 1 << 20, f'RSS grew {grew_kib} KiB'


class TestAutoVacuum:
    """dead_fraction-policy vacuum (round-13 satellite): discard churn
    past the threshold compacts the arenas automatically, behind a
    stable-id indirection so callers' ids survive."""

    def _engine(self, n, threshold=0.5):
        fleet = DocFleet()
        eng = StorageEngine(fleet, vacuum_dead_fraction=threshold)
        handles = _workload(fleet, n)
        saves = [bytes(h['state'].save()) for h in handles]
        ids = eng.ingest_chunks(saves)
        return eng, ids, saves

    def test_discard_churn_triggers_vacuum(self):
        from automerge_tpu.observability import health_counts
        eng, ids, saves = self._engine(12)
        before = health_counts()['storage_auto_vacuums']
        eng.discard(ids[:7])
        assert eng.vacuums == 1
        assert health_counts()['storage_auto_vacuums'] == before + 1
        assert eng.main.dead_fraction == 0.0
        # surviving ids stay valid across the row remap
        for i, save in zip(ids[7:], saves[7:]):
            assert bytes(eng.chunk(i)) == save

    def test_below_threshold_no_vacuum(self):
        eng, ids, _ = self._engine(12)
        eng.discard(ids[:3])
        assert eng.vacuums == 0
        assert eng.main.dead_fraction > 0

    def test_policy_disabled(self):
        eng, ids, _ = self._engine(12, threshold=None)
        eng.discard(ids[:10])
        assert eng.vacuums == 0
        assert eng.main.dead_fraction > 0.8   # caller vacuums by hand

    def test_revive_churn_triggers_and_reads_survive(self):
        eng, ids, saves = self._engine(16)
        live = [(sorted(eng.heads(i)), eng.max_op(i)) for i in ids]
        back = eng.revive(ids[:12])
        assert [bytes(h['state'].save()) for h in back] == saves[:12]
        assert eng.vacuums >= 1
        for i, (heads, max_op) in zip(ids[12:], live[12:]):
            assert eng.heads(i) == heads
            assert eng.max_op(i) == max_op
        # a revived (discarded) id is gone, typed
        with pytest.raises(KeyError):
            eng.heads(ids[0])

    def test_small_stores_never_churn(self):
        eng, ids, _ = self._engine(4)
        eng.discard(ids[:3])
        assert eng.vacuums == 0               # below VACUUM_MIN_ROWS

    def test_adopt_main_moves_ownership(self):
        # regression: adoption MOVES the store — the donor resets, so a
        # later auto-vacuum on either side cannot strand the other's ids
        eng, ids, saves = self._engine(16)
        other = StorageEngine(DocFleet())
        other.adopt_main(eng)
        assert len(eng.main) == 0 and len(eng._row_of) == 0
        # churn the adopter past the threshold: its ids survive its own
        # vacuum, and the donor is unaffected
        other.discard(ids[:12])
        assert other.vacuums >= 1
        for i, save in zip(ids[12:], saves[12:]):
            assert bytes(other.chunk(i)) == save
        with pytest.raises(KeyError):
            eng.heads(ids[15])

    def test_adopt_main_requires_empty_adopter(self):
        eng, ids, _ = self._engine(8)
        other = StorageEngine(DocFleet())
        other.ingest_chunks([bytes(eng.chunk(ids[0]))])
        with pytest.raises(ValueError):
            other.adopt_main(eng)
        # donor untouched by the refused adoption
        assert len(eng.main) == 8
