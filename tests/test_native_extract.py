"""Native change-list extraction (codec.cpp am_extract_changes): parity
with the Python decode_document + encode_change round trip, byte-identical
output at every pool width, typed containment, and the materialize seam
that consumes it (_FlatEngine._materialize_doc).

The parity contract is the delta+main engine's soundness core: a parked
document chunk must expand to EXACTLY the change buffers (and hashes) the
Python path produces, or the extractor must bail so the Python path runs
instead — never a third behavior.
"""

import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import automerge_tpu as A                                        # noqa: E402
from automerge_tpu import native                                 # noqa: E402
from automerge_tpu.columnar import (                             # noqa: E402
    decode_document, encode_change, DocChunkView,
    decode_document_header)
from automerge_tpu.errors import MalformedDocument               # noqa: E402

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


@pytest.fixture(autouse=True)
def _restore_threads():
    prev = native.native_threads()
    yield
    native.set_native_threads(prev)


def _flat_doc():
    d = A.init('aa' * 16)
    d = A.change(d, {'time': 0}, lambda r: r.update(
        {'k1': 1, 'k2': 'v', 'k3': True}))
    d = A.change(d, {'time': 3}, lambda r: r.update({'k1': 2}))
    return bytes(A.save(d))


def _rich_doc():
    d = A.init('aa' * 16)
    d = A.change(d, {'time': 0, 'message': 'first'}, lambda r: r.update(
        {'text': A.Text('hello'), 'list': [1, 'two', 3.5, None, True],
         'nested': {'deep': {'er': 'x'}}, 'c': A.Counter(10),
         'ts': 7, 'big': 'x' * 700}))
    d = A.change(d, {'time': 1}, lambda r: r['c'].increment(5))
    e = A.merge(A.init('bb' * 16), d)
    e = A.change(e, {'time': 2, 'message': 'peer'},
                 lambda r: r.update({'peer': -42}))
    d = A.merge(d, e)

    def edit(r):
        del r['ts']
        del r['list'][1]
        r['list'][0] = 99
        r['text'].insert_at(0, 'H')
        del r['nested']['deep']
    d = A.change(d, {'time': 4}, edit)
    return bytes(A.save(d))


def _merge_heavy_doc():
    """Multi-actor concurrent edits: several heads through history,
    deps fan-in, conflicts."""
    a = A.init('aa' * 16)
    a = A.change(a, {'time': 0}, lambda r: r.update({'k': 'a', 'n': 1}))
    b = A.merge(A.init('bb' * 16), a)
    c = A.merge(A.init('cc' * 16), a)
    a = A.change(a, {'time': 0}, lambda r: r.update({'k': 'a2'}))
    b = A.change(b, {'time': 0}, lambda r: r.update({'k': 'b2', 'x': 2}))
    c = A.change(c, {'time': 0}, lambda r: r.update({'y': [1, 2]}))
    a = A.merge(A.merge(a, b), c)
    a = A.change(a, {'time': 9}, lambda r: r.update({'done': True}))
    return bytes(A.save(a))


def _empty_doc():
    return bytes(A.save(A.init('dd' * 16)))


def _python_extract(chunk):
    decoded = decode_document(chunk)
    return ([bytes(encode_change(ch)) for ch in decoded],
            [ch['hash'] for ch in decoded],
            [ch['startOp'] + len(ch['ops']) - 1 for ch in decoded])


ALL_DOCS = [_flat_doc, _rich_doc, _merge_heavy_doc, _empty_doc]


class TestParity:
    @pytest.mark.parametrize('build', ALL_DOCS)
    def test_byte_identical_to_python(self, build):
        chunk = build()
        out = native.extract_changes([chunk])
        assert out is not None and out[0] is not None, \
            'extractor bailed on a canonical doc'
        bufs, hashes, max_ops = out[0]
        py_bufs, py_hashes, py_max_ops = _python_extract(chunk)
        assert bufs == py_bufs
        assert hashes == py_hashes
        assert max_ops == py_max_ops

    def test_batched_multi_doc(self):
        chunks = [b() for b in ALL_DOCS]
        out = native.extract_changes(chunks)
        for chunk, res in zip(chunks, out):
            assert res is not None
            assert res[0] == _python_extract(chunk)[0]

    def test_identical_across_pool_widths(self):
        chunks = [b() for b in ALL_DOCS] * 3
        native.set_native_threads(1)
        want = native.extract_changes(chunks)
        for width in (2, 4, 8):
            native.set_native_threads(width)
            assert native.extract_changes(chunks) == want

    def test_unknown_columns_fall_back(self):
        """A doc carrying forward-compat unknown columns extracts only
        through the Python path (which preserves them)."""
        from automerge_tpu.backend import op_set
        ops = op_set.OpSet()
        buf = encode_change({
            'actor': 'aa' * 16, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': 1, 'datatype': 'int', 'pred': [],
                     'unknownCols': {0x92: 5}}]})
        ops.apply_changes([buf])
        chunk = bytes(ops.save())
        out = native.extract_changes([chunk])
        assert out[0] is None                       # native bails...
        py_bufs, _h, _m = _python_extract(chunk)    # ...Python round-trips
        assert py_bufs == [bytes(buf)]


class TestContainment:
    def _mutants(self, n=120):
        rng = random.Random(7)
        base = _rich_doc()
        out = []
        for _ in range(n):
            m = bytearray(base)
            for _k in range(rng.randrange(1, 3)):
                roll = rng.random()
                if roll < 0.3 and m:
                    del m[rng.randrange(len(m)):]
                elif roll < 0.7 and m:
                    m[rng.randrange(len(m))] ^= 1 << rng.randrange(8)
                else:
                    pos = rng.randrange(len(m) + 1)
                    m[pos:pos] = bytes(rng.randrange(256)
                                       for _ in range(rng.randrange(1, 6)))
            out.append(bytes(m))
        return out

    def test_hostile_chunks_never_escape(self):
        """Wrapper never raises on hostile bytes; whenever it accepts,
        Python accepts with identical output (the heads check is the
        arbiter)."""
        for m in self._mutants():
            out = native.extract_changes([m])
            if out is None or out[0] is None:
                continue
            bufs, hashes, _ = out[0]
            py_bufs, py_hashes, _ = _python_extract(m)  # must NOT raise
            assert bufs == py_bufs and hashes == py_hashes

    def test_verdicts_identical_across_pool_widths(self):
        """Satellite pin: hostile document chunks get the SAME per-doc
        verdict (ok/bail) and the same bytes at widths 1/2/4/8."""
        mutants = self._mutants(60) + [b() for b in ALL_DOCS]
        native.set_native_threads(1)
        want = native.extract_changes(mutants)
        for width in (2, 4, 8):
            native.set_native_threads(width)
            assert native.extract_changes(mutants) == want

    def test_materialize_seam_raises_typed_on_hostile_chunk(self):
        """The _materialize_doc consumer: a parked hostile chunk
        surfaces as MalformedDocument (via the Python fallback path),
        never an untyped error."""
        from automerge_tpu.fleet.backend import DocFleet, _FlatEngine
        fleet = DocFleet()
        eng = _FlatEngine(fleet, fleet.alloc_slot())
        bad = bytearray(_flat_doc())
        bad[-3] ^= 0x40
        eng._install_parked_chunk(bytes(bad), 2)
        with pytest.raises(MalformedDocument):
            _ = eng.changes


class TestMaterializeSeam:
    def test_materialize_uses_native_and_matches_python(self):
        """_materialize_doc through the native extractor produces the
        same change log + graph as the Python path."""
        from automerge_tpu.fleet.backend import DocFleet, _FlatEngine
        chunk = _rich_doc()
        py_bufs, py_hashes, _ = _python_extract(chunk)

        fleet = DocFleet()
        eng = _FlatEngine(fleet, fleet.alloc_slot())
        eng._install_parked_chunk(chunk, len(py_bufs))
        logs = eng.changes
        assert [bytes(b) for b in logs] == py_bufs
        assert eng._doc_decoded is None          # native path: no dicts
        # graph resolution (hash + meta) from the extractor's arrays
        eng._ensure_graph()
        assert sorted(eng.change_index_by_hash) == sorted(py_hashes)
        metas = eng.changes_meta
        decoded = decode_document(chunk)
        for meta, ch in zip(metas, decoded):
            assert meta['actor'] == ch['actor']
            assert meta['seq'] == ch['seq']
            assert meta['maxOp'] == ch['startOp'] + len(ch['ops']) - 1
            assert meta['deps'] == sorted(ch['deps'])
            assert meta['message'] == (ch.get('message') or '')

    def test_view_matches_header(self):
        """DocChunkView answers header-derived reads without decoding
        ops columns."""
        for build in ALL_DOCS:
            chunk = build()
            view = DocChunkView(chunk)
            hdr = decode_document_header(chunk)
            decoded = decode_document(chunk)
            assert sorted(view.heads) == sorted(hdr['heads'])
            assert view.actor_ids == hdr['actorIds']
            assert view.n_changes == len(decoded)
            clock = {}
            max_op = 0
            for ch in decoded:
                clock[ch['actor']] = max(clock.get(ch['actor'], 0),
                                         ch['seq'])
                max_op = max(max_op, ch['startOp'] + len(ch['ops']) - 1)
            assert view.clock == clock
            assert view.max_op == max_op
            for h in hdr['heads']:
                assert view.contains_head(h)
            assert view.covers_heads(hdr['heads'])
            assert not view.contains_head('ee' * 32)


class TestParityEdgeCases:
    """Shapes the frontend rarely produces but the format allows."""

    def _parity(self, chunk):
        out = native.extract_changes([chunk])
        assert out[0] is not None, 'extractor bailed on a canonical doc'
        py_bufs, py_hashes, py_max_ops = _python_extract(chunk)
        assert out[0][0] == py_bufs
        assert out[0][1] == py_hashes
        assert out[0][2] == py_max_ops

    def test_two_head_document(self):
        from automerge_tpu.backend import op_set
        ops = op_set.OpSet()
        ops.apply_changes([encode_change({
            'actor': a * 16, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'a',
                     'value': i, 'datatype': 'int', 'pred': []}]})
            for i, a in enumerate(('aa', 'bb'))])
        assert len(ops.heads) == 2
        self._parity(bytes(ops.save()))

    def test_change_level_extra_bytes(self):
        from automerge_tpu.backend import op_set
        ops = op_set.OpSet()
        ops.apply_changes([encode_change({
            'actor': 'cc' * 16, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': 'm', 'deps': [], 'extraBytes': b'\x01\x02xtra',
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': 'v', 'pred': []}]})])
        self._parity(bytes(ops.save()))

    def test_preds_bytes_and_wire_datatypes(self):
        from automerge_tpu.backend import op_set
        from automerge_tpu.columnar import decode_change_meta
        ops = op_set.OpSet()
        b1 = encode_change({
            'actor': 'dd' * 16, 'seq': 1, 'startOp': 1, 'time': 5,
            'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': 3, 'datatype': 'uint', 'pred': []},
                    {'action': 'set', 'obj': '_root', 'key': 'ts',
                     'value': 123456, 'datatype': 'timestamp',
                     'pred': []}]})
        ops.apply_changes([b1])
        h = decode_change_meta(b1, True)['hash']
        ops.apply_changes([encode_change({
            'actor': 'dd' * 16, 'seq': 2, 'startOp': 3, 'time': 5,
            'message': '', 'deps': [h],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                     'value': b'\x00\xff',
                     'pred': [f'1@{"dd" * 16}']}]})])
        self._parity(bytes(ops.save()))
