"""Port of the reference public-API suite, part 3 (ref test/test.js:873-1508):
concurrent use, multiple insertions at the same list position, saving and
loading, the history API, and the changes API.
"""

import re

import pytest

import automerge_tpu as A
from automerge_tpu.backend import get_heads, get_missing_deps
from automerge_tpu.frontend import get_backend_state

UUID_PATTERN = re.compile(r'^[0-9a-f]{32}$')


def assert_equals_one_of(actual, *expected):
    assert any(A.equals(actual, e) for e in expected), \
        f'{actual!r} not equal to any of {expected!r}'


class TestConcurrentUse:
    """ref test/test.js:873-1131"""

    def test_merges_concurrent_updates_of_different_properties(self):
        s1 = A.change(A.init(), lambda d: d.update({'foo': 'bar'}))
        s2 = A.change(A.init(), lambda d: d.update({'hello': 'world'}))
        s3 = A.merge(s1, s2)
        assert s3['foo'] == 'bar'
        assert s3['hello'] == 'world'
        assert A.equals(s3, {'foo': 'bar', 'hello': 'world'})
        assert A.get_conflicts(s3, 'foo') is None
        assert A.get_conflicts(s3, 'hello') is None

    def test_adds_concurrent_increments_of_same_property(self):
        s1 = A.change(A.init(), lambda d: d.update({'counter': A.Counter()}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['counter'].increment())
        s2 = A.change(s2, lambda d: d['counter'].increment(2))
        s3 = A.merge(s1, s2)
        assert s1['counter'].value == 1
        assert s2['counter'].value == 2
        assert s3['counter'].value == 3
        assert A.get_conflicts(s3, 'counter') is None

    def test_adds_increments_only_to_the_values_they_precede(self):
        s1 = A.change(A.init(), lambda d: d.update({'counter': A.Counter(0)}))
        s1 = A.change(s1, lambda d: d['counter'].increment())
        s2 = A.change(A.init(), lambda d: d.update({'counter': A.Counter(100)}))
        s2 = A.change(s2, lambda d: d['counter'].increment(3))
        s3 = A.merge(s1, s2)
        if A.get_actor_id(s1) > A.get_actor_id(s2):
            assert s3['counter'].value == 1
        else:
            assert s3['counter'].value == 103
        conflicts = A.get_conflicts(s3, 'counter')
        assert conflicts[f'1@{A.get_actor_id(s1)}'].value == 1
        assert conflicts[f'1@{A.get_actor_id(s2)}'].value == 103

    def test_detects_concurrent_updates_of_same_field(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 'one'}))
        s2 = A.change(A.init(), lambda d: d.update({'field': 'two'}))
        s3 = A.merge(s1, s2)
        if A.get_actor_id(s1) > A.get_actor_id(s2):
            assert A.equals(s3, {'field': 'one'})
        else:
            assert A.equals(s3, {'field': 'two'})
        assert A.get_conflicts(s3, 'field') == {
            f'1@{A.get_actor_id(s1)}': 'one',
            f'1@{A.get_actor_id(s2)}': 'two'}

    def test_detects_concurrent_updates_of_same_list_element(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['finch']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].__setitem__(0, 'greenfinch'))
        s2 = A.change(s2, lambda d: d['birds'].__setitem__(0, 'goldfinch'))
        s3 = A.merge(s1, s2)
        if A.get_actor_id(s1) > A.get_actor_id(s2):
            assert list(s3['birds']) == ['greenfinch']
        else:
            assert list(s3['birds']) == ['goldfinch']
        assert A.get_conflicts(s3['birds'], 0) == {
            f'3@{A.get_actor_id(s1)}': 'greenfinch',
            f'3@{A.get_actor_id(s2)}': 'goldfinch'}

    def test_assignment_conflicts_of_different_types(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 'string'}))
        s2 = A.change(A.init(), lambda d: d.update({'field': ['list']}))
        s3 = A.change(A.init(), lambda d: d.update({'field': {'thing': 'map'}}))
        s1 = A.merge(A.merge(s1, s2), s3)
        assert_equals_one_of(s1['field'], 'string', ['list'], {'thing': 'map'})
        conflicts = A.get_conflicts(s1, 'field')
        assert conflicts[f'1@{A.get_actor_id(s1)}'] == 'string'
        assert A.equals(conflicts[f'1@{A.get_actor_id(s2)}'], ['list'])
        assert A.equals(conflicts[f'1@{A.get_actor_id(s3)}'], {'thing': 'map'})

    def test_changes_within_a_conflicting_map_field(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 'string'}))
        s2 = A.change(A.init(), lambda d: d.update({'field': {}}))
        s2 = A.change(s2, lambda d: d['field'].update({'innerKey': 42}))
        s3 = A.merge(s1, s2)
        assert_equals_one_of(s3['field'], 'string', {'innerKey': 42})
        conflicts = A.get_conflicts(s3, 'field')
        assert conflicts[f'1@{A.get_actor_id(s1)}'] == 'string'
        assert A.equals(conflicts[f'1@{A.get_actor_id(s2)}'], {'innerKey': 42})

    def test_changes_within_a_conflicting_list_element(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': ['hello']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['list'].__setitem__(0, {'map1': True}))
        s1 = A.change(s1, lambda d: d['list'][0].update({'key': 1}))
        s2 = A.change(s2, lambda d: d['list'].__setitem__(0, {'map2': True}))
        s2 = A.change(s2, lambda d: d['list'][0].update({'key': 2}))
        s3 = A.merge(s1, s2)
        if A.get_actor_id(s1) > A.get_actor_id(s2):
            assert A.equals(s3['list'], [{'map1': True, 'key': 1}])
        else:
            assert A.equals(s3['list'], [{'map2': True, 'key': 2}])
        conflicts = A.get_conflicts(s3['list'], 0)
        assert A.equals(conflicts[f'3@{A.get_actor_id(s1)}'],
                        {'map1': True, 'key': 1})
        assert A.equals(conflicts[f'3@{A.get_actor_id(s2)}'],
                        {'map2': True, 'key': 2})

    def test_does_not_merge_concurrently_assigned_nested_maps(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'config': {'background': 'blue'}}))
        s2 = A.change(A.init(), lambda d: d.update(
            {'config': {'logo_url': 'logo.png'}}))
        s3 = A.merge(s1, s2)
        assert_equals_one_of(s3['config'],
                             {'background': 'blue'}, {'logo_url': 'logo.png'})
        conflicts = A.get_conflicts(s3, 'config')
        assert A.equals(conflicts[f'1@{A.get_actor_id(s1)}'],
                        {'background': 'blue'})
        assert A.equals(conflicts[f'1@{A.get_actor_id(s2)}'],
                        {'logo_url': 'logo.png'})

    def test_clears_conflicts_after_assigning_new_value(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 'one'}))
        s2 = A.change(A.init(), lambda d: d.update({'field': 'two'}))
        s3 = A.merge(s1, s2)
        s3 = A.change(s3, lambda d: d.update({'field': 'three'}))
        assert A.equals(s3, {'field': 'three'})
        assert A.get_conflicts(s3, 'field') is None
        s2 = A.merge(s2, s3)
        assert A.equals(s2, {'field': 'three'})
        assert A.get_conflicts(s2, 'field') is None

    def test_concurrent_insertions_at_different_list_positions(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': ['one', 'three']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['list'].insert(1, 'two'))
        s2 = A.change(s2, lambda d: d['list'].append('four'))
        s3 = A.merge(s1, s2)
        assert A.equals(s3, {'list': ['one', 'two', 'three', 'four']})
        assert A.get_conflicts(s3, 'list') is None

    def test_concurrent_insertions_at_same_list_position(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['parakeet']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].append('starling'))
        s2 = A.change(s2, lambda d: d['birds'].append('chaffinch'))
        s3 = A.merge(s1, s2)
        assert_equals_one_of(s3['birds'],
                             ['parakeet', 'starling', 'chaffinch'],
                             ['parakeet', 'chaffinch', 'starling'])
        s2 = A.merge(s2, s3)
        assert A.equals(s2, s3)

    def test_concurrent_assignment_and_deletion_of_map_entry(self):
        # Add-wins semantics
        s1 = A.change(A.init(), lambda d: d.update({'bestBird': 'robin'}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d.__delitem__('bestBird'))
        s2 = A.change(s2, lambda d: d.update({'bestBird': 'magpie'}))
        s3 = A.merge(s1, s2)
        assert A.equals(s1, {})
        assert A.equals(s2, {'bestBird': 'magpie'})
        assert A.equals(s3, {'bestBird': 'magpie'})
        assert A.get_conflicts(s3, 'bestBird') is None

    def test_concurrent_assignment_and_deletion_of_list_element(self):
        # Concurrent assignment resurrects a deleted list element
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': ['blackbird', 'thrush', 'goldfinch']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].__setitem__(1, 'starling'))
        s2 = A.change(s2, lambda d: d['birds'].delete_at(1))
        s3 = A.merge(s1, s2)
        assert list(s1['birds']) == ['blackbird', 'starling', 'goldfinch']
        assert list(s2['birds']) == ['blackbird', 'goldfinch']
        assert list(s3['birds']) == ['blackbird', 'starling', 'goldfinch']

    def test_insertion_after_a_deleted_list_element(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': ['blackbird', 'thrush', 'goldfinch']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].delete_at(1, 2))
        s2 = A.change(s2, lambda d: d['birds'].insert(2, 'starling'))
        s3 = A.merge(s1, s2)
        assert A.equals(s3, {'birds': ['blackbird', 'starling']})
        assert A.equals(A.merge(s2, s3), {'birds': ['blackbird', 'starling']})

    def test_concurrent_deletion_of_same_element(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': ['albatross', 'buzzard', 'cormorant']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].delete_at(1))
        s2 = A.change(s2, lambda d: d['birds'].delete_at(1))
        s3 = A.merge(s1, s2)
        assert list(s3['birds']) == ['albatross', 'cormorant']

    def test_concurrent_deletion_of_different_elements(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': ['albatross', 'buzzard', 'cormorant']}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].delete_at(0))
        s2 = A.change(s2, lambda d: d['birds'].delete_at(1))
        s3 = A.merge(s1, s2)
        assert list(s3['birds']) == ['cormorant']

    def test_concurrent_updates_at_different_tree_levels(self):
        s1 = A.change(A.init(), lambda d: d.update({'animals': {
            'birds': {'pink': 'flamingo', 'black': 'starling'},
            'mammals': ['badger']}}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['animals']['birds'].update(
            {'brown': 'sparrow'}))
        s2 = A.change(s2, lambda d: d['animals'].__delitem__('birds'))
        s3 = A.merge(s1, s2)
        assert A.equals(s1['animals'], {
            'birds': {'pink': 'flamingo', 'brown': 'sparrow',
                      'black': 'starling'},
            'mammals': ['badger']})
        assert A.equals(s2['animals'], {'mammals': ['badger']})
        assert A.equals(s3['animals'], {'mammals': ['badger']})

    def test_updates_of_concurrently_deleted_objects(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': {'blackbird': {'feathers': 'black'}}}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['birds'].__delitem__('blackbird'))
        s2 = A.change(s2, lambda d: d['birds']['blackbird'].update(
            {'beak': 'orange'}))
        s3 = A.merge(s1, s2)
        assert A.equals(s1, {'birds': {}})

    def test_does_not_interleave_sequence_insertions_at_same_position(self):
        s1 = A.change(A.init(), lambda d: d.update({'wisdom': []}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['wisdom'].append(
            'to', 'be', 'is', 'to', 'do'))
        s2 = A.change(s2, lambda d: d['wisdom'].append(
            'to', 'do', 'is', 'to', 'be'))
        s3 = A.merge(s1, s2)
        assert_equals_one_of(
            s3['wisdom'],
            ['to', 'be', 'is', 'to', 'do', 'to', 'do', 'is', 'to', 'be'],
            ['to', 'do', 'is', 'to', 'be', 'to', 'be', 'is', 'to', 'do'])


class TestMultipleInsertionsAtSamePosition:
    """ref test/test.js:1133-1171"""

    def test_insertion_by_greater_actor_id(self):
        s1 = A.init('aaaa')
        s2 = A.init('bbbb')
        s1 = A.change(s1, lambda d: d.update({'list': ['two']}))
        s2 = A.merge(s2, s1)
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'one'))
        assert list(s2['list']) == ['one', 'two']

    def test_insertion_by_lesser_actor_id(self):
        s1 = A.init('bbbb')
        s2 = A.init('aaaa')
        s1 = A.change(s1, lambda d: d.update({'list': ['two']}))
        s2 = A.merge(s2, s1)
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'one'))
        assert list(s2['list']) == ['one', 'two']

    def test_insertion_regardless_of_actor_id(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': ['two']}))
        s2 = A.merge(A.init(), s1)
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'one'))
        assert list(s2['list']) == ['one', 'two']

    def test_insertion_order_consistent_with_causality(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': ['four']}))
        s2 = A.merge(A.init(), s1)
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'three'))
        s1 = A.merge(s1, s2)
        s1 = A.change(s1, lambda d: d['list'].insert(0, 'two'))
        s2 = A.merge(s2, s1)
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'one'))
        assert list(s2['list']) == ['one', 'two', 'three', 'four']


class TestSavingAndLoading:
    """ref test/test.js:1172-1305"""

    def test_save_and_restore_empty_document(self):
        assert A.equals(A.load(A.save(A.init())), {})

    def test_generates_a_new_random_actor_id(self):
        s1 = A.init()
        s2 = A.load(A.save(s1))
        assert UUID_PATTERN.match(A.get_actor_id(s1))
        assert UUID_PATTERN.match(A.get_actor_id(s2))
        assert A.get_actor_id(s1) != A.get_actor_id(s2)

    def test_allows_custom_actor_id_on_load(self):
        s = A.load(A.save(A.init()), '333333')
        assert A.get_actor_id(s) == '333333'

    def test_reconstitutes_complex_datatypes(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'todos': [{'title': 'water plants', 'done': False}]}))
        s2 = A.load(A.save(s1))
        assert A.equals(s2, {'todos': [{'title': 'water plants',
                                        'done': False}]})

    def test_saves_and_loads_keys_with_at_symbols(self):
        s1 = A.change(A.init(), lambda d: d.update({'123@4567': 'hello'}))
        s2 = A.load(A.save(s1))
        assert A.equals(s2, {'123@4567': 'hello'})

    def test_reconstitutes_conflicts(self):
        s1 = A.change(A.init('111111'), lambda d: d.update({'x': 3}))
        s2 = A.change(A.init('222222'), lambda d: d.update({'x': 5}))
        s1 = A.merge(s1, s2)
        s3 = A.load(A.save(s1))
        assert s1['x'] == 5
        assert s3['x'] == 5
        assert A.get_conflicts(s1, 'x') == {'1@111111': 3, '1@222222': 5}
        assert A.get_conflicts(s3, 'x') == {'1@111111': 3, '1@222222': 5}

    def test_reconstitutes_element_id_counters(self):
        s1 = A.init('01234567')
        s2 = A.change(s1, lambda d: d.update({'list': ['a']}))
        list_id = A.get_object_id(s2['list'])
        changes12 = [A.decode_change(c) for c in A.get_all_changes(s2)]
        assert len(changes12) == 1
        assert changes12[0]['actor'] == '01234567'
        assert changes12[0]['seq'] == 1
        assert changes12[0]['startOp'] == 1
        assert changes12[0]['deps'] == []
        assert changes12[0]['ops'] == [
            {'obj': '_root', 'action': 'makeList', 'key': 'list',
             'insert': False, 'pred': []},
            {'obj': list_id, 'action': 'set', 'elemId': '_head',
             'insert': True, 'value': 'a', 'pred': []}]
        s3 = A.change(s2, lambda d: d['list'].delete_at(0))
        s4 = A.load(A.save(s3), '01234567')
        s5 = A.change(s4, lambda d: d['list'].append('b'))
        changes45 = [A.decode_change(c) for c in A.get_all_changes(s5)]
        assert A.equals(s5, {'list': ['b']})
        assert changes45[2]['actor'] == '01234567'
        assert changes45[2]['seq'] == 3
        assert changes45[2]['startOp'] == 4
        assert changes45[2]['deps'] == [changes45[1]['hash']]
        assert changes45[2]['ops'] == [
            {'obj': list_id, 'action': 'set', 'elemId': '_head',
             'insert': True, 'value': 'b', 'pred': []}]

    def test_allows_a_reloaded_list_to_be_mutated(self):
        doc = A.change(A.init(), lambda d: d.update({'foo': []}))
        doc = A.load(A.save(doc))
        doc = A.change(doc, 'add', lambda d: d['foo'].append(1))
        doc = A.load(A.save(doc))
        assert A.equals(doc['foo'], [1])

    def test_reloads_document_containing_deflated_columns(self):
        import random
        rng = random.Random(0)

        def cb(doc):
            doc['list'] = []
            for i in range(200):
                doc['list'].insert(rng.randint(0, max(i, 0)), 'a')
        doc = A.change(A.init(), cb)
        A.load(A.save(doc))
        assert list(doc['list']) == ['a'] * 200

    def test_calls_patch_callback_on_load(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Goldfinch']}))
        s2 = A.change(s1, lambda d: d['birds'].append('Chaffinch'))
        callbacks = []
        actor = A.get_actor_id(s1)
        reloaded = A.load(A.save(s2), {
            'patchCallback': lambda patch, before, after, local, changes:
                callbacks.append((patch, before, after, local))})
        assert len(callbacks) == 1
        patch, before, after, local = callbacks[0]
        second_hash = A.decode_change(A.get_all_changes(s2)[1])['hash']
        assert patch == {
            'maxOp': 3, 'deps': [second_hash], 'clock': {actor: 2},
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {f'1@{actor}': {
                    'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                        {'action': 'multi-insert', 'index': 0,
                         'elemId': f'2@{actor}',
                         'values': ['Goldfinch', 'Chaffinch']}]}}}},
        }
        assert A.equals(before, {})
        assert after is reloaded
        assert local is False

    def test_reconstructs_original_changes_if_needed(self):
        doc = A.init()
        for i in range(10):
            doc = A.change(doc, lambda d, i=i: d.update({'x': i}))
        doc = A.load(A.save(doc))
        assert len(A.get_all_changes(doc)) == 10

    def test_deduplicates_changes_after_save_and_reload(self):
        init_change = A.get_last_local_change(A.change(
            A.init('0000'), {'time': 0}, lambda d: d.update({'panels': []})))
        s1, _ = A.apply_changes(A.init(), [init_change])
        s2, _ = A.apply_changes(A.init(), [init_change])
        s1 = A.change(s1, lambda d: d['panels'].append({'id': 'panel1'}))
        s2 = A.change(s2, lambda d: d['panels'].append({'id': 'panel2'}))
        s1 = A.load(A.save(s1))
        s3, _ = A.apply_changes(s1, A.get_all_changes(s2))
        assert len(s3['panels']) == 2


class TestHistoryAPI:
    """ref test/test.js:1305-1333"""

    def test_empty_history_for_empty_document(self):
        assert A.get_history(A.init()) == []

    def test_makes_past_document_states_accessible(self):
        s = A.init()
        s = A.change(s, lambda d: d.update({'config': {'background': 'blue'}}))
        s = A.change(s, lambda d: d.update({'birds': ['mallard']}))
        s = A.change(s, lambda d: d['birds'].insert(0, 'oystercatcher'))
        snapshots = [h.snapshot for h in A.get_history(s)]
        assert A.equals(snapshots[0], {'config': {'background': 'blue'}})
        assert A.equals(snapshots[1],
                        {'config': {'background': 'blue'},
                         'birds': ['mallard']})
        assert A.equals(snapshots[2],
                        {'config': {'background': 'blue'},
                         'birds': ['oystercatcher', 'mallard']})

    def test_makes_change_messages_accessible(self):
        s = A.init()
        s = A.change(s, 'Empty Bookshelf', lambda d: d.update({'books': []}))
        s = A.change(s, 'Add Orwell',
                     lambda d: d['books'].append('Nineteen Eighty-Four'))
        s = A.change(s, 'Add Huxley',
                     lambda d: d['books'].append('Brave New World'))
        assert list(s['books']) == ['Nineteen Eighty-Four', 'Brave New World']
        assert [h.change['message'] for h in A.get_history(s)] == \
            ['Empty Bookshelf', 'Add Orwell', 'Add Huxley']


class TestChangesAPI:
    """ref test/test.js:1333-1507"""

    def test_empty_list_on_empty_document(self):
        assert A.get_all_changes(A.init()) == []

    def test_empty_list_when_nothing_changed(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Chaffinch']}))
        assert A.get_changes(s1, s1) == []

    def test_does_nothing_applying_empty_list_of_changes(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Chaffinch']}))
        assert A.equals(A.apply_changes(s1, [])[0], s1)

    def test_useful_error_for_wrong_apply_changes_argument(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Chaffinch']}))
        changes = A.get_all_changes(s1)
        with pytest.raises(Exception):
            A.apply_changes(A.init(), changes[0])
        with pytest.raises(Exception):
            A.apply_changes(A.init(), ['this is a string'])

    def test_returns_all_changes_compared_to_empty_document(self):
        s1 = A.change(A.init(), 'Add Chaffinch',
                      lambda d: d.update({'birds': ['Chaffinch']}))
        s2 = A.change(s1, 'Add Bullfinch',
                      lambda d: d['birds'].append('Bullfinch'))
        changes = A.get_changes(A.init(), s2)
        assert len(changes) == 2

    def test_allows_document_copy_reconstruction_from_scratch(self):
        s1 = A.change(A.init(), 'Add Chaffinch',
                      lambda d: d.update({'birds': ['Chaffinch']}))
        s2 = A.change(s1, 'Add Bullfinch',
                      lambda d: d['birds'].append('Bullfinch'))
        changes = A.get_all_changes(s2)
        s3, _ = A.apply_changes(A.init(), changes)
        assert list(s3['birds']) == ['Chaffinch', 'Bullfinch']

    def test_returns_changes_since_last_given_version(self):
        s1 = A.change(A.init(), 'Add Chaffinch',
                      lambda d: d.update({'birds': ['Chaffinch']}))
        changes1 = A.get_all_changes(s1)
        s2 = A.change(s1, 'Add Bullfinch',
                      lambda d: d['birds'].append('Bullfinch'))
        changes2 = A.get_changes(s1, s2)
        assert len(changes1) == 1
        assert len(changes2) == 1

    def test_incrementally_applies_changes_since_last_version(self):
        s1 = A.change(A.init(), 'Add Chaffinch',
                      lambda d: d.update({'birds': ['Chaffinch']}))
        changes1 = A.get_all_changes(s1)
        s2 = A.change(s1, 'Add Bullfinch',
                      lambda d: d['birds'].append('Bullfinch'))
        changes2 = A.get_changes(s1, s2)
        s3, _ = A.apply_changes(A.init(), changes1)
        s4, _ = A.apply_changes(s3, changes2)
        assert list(s3['birds']) == ['Chaffinch']
        assert list(s4['birds']) == ['Chaffinch', 'Bullfinch']

    def test_handles_updates_to_a_list_element(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': ['Chaffinch', 'Bullfinch']}))
        s2 = A.change(s1, lambda d: d['birds'].__setitem__(0, 'Goldfinch'))
        s3, _ = A.apply_changes(A.init(), A.get_all_changes(s2))
        assert list(s3['birds']) == ['Goldfinch', 'Bullfinch']
        assert A.get_conflicts(s3['birds'], 0) is None

    def test_handles_updates_to_a_text_object(self):
        s1 = A.change(A.init(), lambda d: d.update({'text': A.Text('ab')}))
        s2 = A.change(s1, lambda d: d['text'].set(0, 'A'))
        s3, _ = A.apply_changes(A.init(), A.get_all_changes(s2))
        assert list(s3['text']) == ['A', 'b']

    def test_reports_missing_dependencies(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Chaffinch']}))
        s2 = A.merge(A.init(), s1)
        s2 = A.change(s2, lambda d: d['birds'].append('Bullfinch'))
        changes = A.get_all_changes(s2)
        s3, patch = A.apply_changes(A.init(), [changes[1]])
        assert A.equals(s3, {})
        assert get_missing_deps(get_backend_state(s3)) == \
            A.decode_change(changes[1])['deps']
        assert patch['pendingChanges'] == 1
        s3, patch = A.apply_changes(s3, [changes[0]])
        assert list(s3['birds']) == ['Chaffinch', 'Bullfinch']
        assert get_missing_deps(get_backend_state(s3)) == []
        assert patch['pendingChanges'] == 0

    def test_allows_changes_to_be_applied_in_any_order(self):
        s1 = A.change(A.init(), lambda d: d.update({'bird': 'Goldfinch'}))
        s2 = A.change(s1, lambda d: d.update({'bird': 'Chaffinch'}))
        s3 = A.change(s2, lambda d: d.update({'bird': 'Greenfinch'}))
        changes = list(reversed(A.get_all_changes(s3)))
        s4, _ = A.apply_changes(A.init(), changes)
        assert A.equals(s4, {'bird': 'Greenfinch'})

    def test_missing_dependencies_with_out_of_order_apply_changes(self):
        s0 = A.init()
        s1 = A.change(s0, lambda d: d.update({'test': ['a']}))
        changes01 = A.get_all_changes(s1)
        s2 = A.change(s1, lambda d: d.update({'test': ['b']}))
        changes12 = A.get_changes(s1, s2)
        s3 = A.change(s2, lambda d: d.update({'test': ['c']}))
        changes23 = A.get_changes(s2, s3)
        s4 = A.init()
        s5, _ = A.apply_changes(s4, changes23)
        s6, patch6 = A.apply_changes(s5, changes12)
        assert get_missing_deps(get_backend_state(s6)) == \
            [A.decode_change(changes01[0])['hash']]
        assert patch6['pendingChanges'] == 2

    def test_calls_patch_callback_when_applying_changes(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Goldfinch']}))
        callbacks = []
        actor = A.get_actor_id(s1)
        before = A.init()
        after, patch = A.apply_changes(
            before, A.get_all_changes(s1),
            {'patchCallback': lambda patch, before, after, local, changes:
             callbacks.append((patch, before, after, local))})
        assert len(callbacks) == 1
        cb_patch, cb_before, cb_after, cb_local = callbacks[0]
        first_hash = A.decode_change(A.get_all_changes(s1)[0])['hash']
        assert cb_patch == {
            'maxOp': 2, 'deps': [first_hash], 'clock': {actor: 1},
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {f'1@{actor}': {
                    'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                        {'action': 'insert', 'index': 0,
                         'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                         'value': {'type': 'value', 'value': 'Goldfinch'}}]}}}},
        }
        assert cb_patch is patch
        assert cb_before is before
        assert cb_after is after
        assert cb_local is False

    def test_merges_multiple_applied_changes_into_one_patch(self):
        s1 = A.change(A.init(), lambda d: d.update({'birds': ['Goldfinch']}))
        s2 = A.change(s1, lambda d: d['birds'].append('Chaffinch'))
        patches = []
        actor = A.get_actor_id(s2)
        A.apply_changes(A.init(), A.get_all_changes(s2),
                        {'patchCallback':
                         lambda p, *args: patches.push(p)
                         if hasattr(patches, 'push') else patches.append(p)})
        second_hash = A.decode_change(A.get_all_changes(s2)[1])['hash']
        assert patches == [{
            'maxOp': 3, 'deps': [second_hash], 'clock': {actor: 2},
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {f'1@{actor}': {
                    'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                        {'action': 'multi-insert', 'index': 0,
                         'elemId': f'2@{actor}',
                         'values': ['Goldfinch', 'Chaffinch']}]}}}},
        }]

    def test_calls_patch_callback_registered_on_initialisation(self):
        s1 = A.change(A.init(), lambda d: d.update({'bird': 'Goldfinch'}))
        patches = []
        actor = A.get_actor_id(s1)
        before = A.init({'patchCallback': lambda p, *args: patches.append(p)})
        A.apply_changes(before, A.get_all_changes(s1))
        first_hash = A.decode_change(A.get_all_changes(s1)[0])['hash']
        assert patches == [{
            'maxOp': 1, 'deps': [first_hash], 'clock': {actor: 1},
            'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {f'1@{actor}': {'type': 'value',
                                        'value': 'Goldfinch'}}}},
        }]
