"""Port of the reference public-API suite, part 2 (ref test/test.js:575-872):
lists, numbers, and counters.
"""

import datetime

import pytest

import automerge_tpu as A


def assert_equals_one_of(actual, *expected):
    assert any(A.equals(actual, e) for e in expected), \
        f'{actual!r} not equal to any of {expected!r}'


class TestLists:
    """ref test/test.js:575-800"""

    def test_allows_elements_to_be_inserted(self):
        s1 = A.change(A.init(), lambda d: d.update({'noodles': []}))
        s1 = A.change(s1, lambda d: d['noodles'].insert_at(0, 'udon', 'soba'))
        s1 = A.change(s1, lambda d: d['noodles'].insert_at(1, 'ramen'))
        assert A.equals(s1, {'noodles': ['udon', 'ramen', 'soba']})
        assert list(s1['noodles']) == ['udon', 'ramen', 'soba']
        assert s1['noodles'][0] == 'udon'
        assert s1['noodles'][1] == 'ramen'
        assert s1['noodles'][2] == 'soba'
        assert len(s1['noodles']) == 3

    def test_assignment_of_list_literal(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'ramen', 'soba']}))
        assert A.equals(s1, {'noodles': ['udon', 'ramen', 'soba']})
        assert list(s1['noodles']) == ['udon', 'ramen', 'soba']
        assert len(s1['noodles']) == 3

    def test_only_numeric_indexes(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'ramen', 'soba']}))
        s1 = A.change(s1, lambda d: d['noodles'].__setitem__(1, 'Ramen!'))
        assert s1['noodles'][1] == 'Ramen!'
        with pytest.raises(Exception):
            A.change(s1, lambda d: d['noodles'].__setitem__('favourite', 'udon'))
        with pytest.raises(Exception):
            A.change(s1, lambda d: d['noodles'].__setitem__('', 'udon'))
        with pytest.raises(Exception):
            A.change(s1, lambda d: d['noodles'].__setitem__('1e6', 'udon'))

    def test_deletion_of_list_elements(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'ramen', 'soba']}))
        s1 = A.change(s1, lambda d: d['noodles'].__delitem__(1))
        assert list(s1['noodles']) == ['udon', 'soba']
        s1 = A.change(s1, lambda d: d['noodles'].delete_at(1))
        assert list(s1['noodles']) == ['udon']
        assert s1['noodles'][0] == 'udon'
        assert len(s1['noodles']) == 1

    def test_assignment_of_individual_list_indexes(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'japaneseFood': ['udon', 'ramen', 'soba']}))
        s1 = A.change(s1, lambda d: d['japaneseFood'].__setitem__(1, 'sushi'))
        assert list(s1['japaneseFood']) == ['udon', 'sushi', 'soba']
        assert len(s1['japaneseFood']) == 3

    def test_out_by_one_assignment_is_insertion(self):
        s1 = A.change(A.init(), lambda d: d.update({'japaneseFood': ['udon']}))
        s1 = A.change(s1, lambda d: d['japaneseFood'].__setitem__(1, 'sushi'))
        assert list(s1['japaneseFood']) == ['udon', 'sushi']
        assert len(s1['japaneseFood']) == 2

    def test_bulk_assignment_of_multiple_list_indexes(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'ramen', 'soba']}))

        def cb(doc):
            doc['noodles'][0] = 'うどん'
            doc['noodles'][2] = 'そば'
        s1 = A.change(s1, cb)
        assert list(s1['noodles']) == ['うどん', 'ramen', 'そば']
        assert len(s1['noodles']) == 3

    def test_nested_objects_in_lists(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': [{'type': 'ramen', 'dishes': ['tonkotsu', 'shoyu']}]}))
        s1 = A.change(s1, lambda d: d['noodles'].append(
            {'type': 'udon', 'dishes': ['tempura udon']}))
        s1 = A.change(s1, lambda d: d['noodles'][0]['dishes'].append('miso'))
        assert A.equals(s1, {'noodles': [
            {'type': 'ramen', 'dishes': ['tonkotsu', 'shoyu', 'miso']},
            {'type': 'udon', 'dishes': ['tempura udon']}]})
        assert A.equals(s1['noodles'][0],
                        {'type': 'ramen', 'dishes': ['tonkotsu', 'shoyu', 'miso']})
        assert A.equals(s1['noodles'][1],
                        {'type': 'udon', 'dishes': ['tempura udon']})

    def test_nested_lists(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodleMatrix': [['ramen', 'tonkotsu', 'shoyu']]}))
        s1 = A.change(s1, lambda d: d['noodleMatrix'].append(
            ['udon', 'tempura udon']))
        s1 = A.change(s1, lambda d: d['noodleMatrix'][0].append('miso'))
        assert A.equals(s1['noodleMatrix'],
                        [['ramen', 'tonkotsu', 'shoyu', 'miso'],
                         ['udon', 'tempura udon']])

    def test_deep_nesting_mutations(self):
        s1 = A.change(A.init(), lambda d: d.update({'nesting': {
            'maps': {'m1': {'m2': {'foo': 'bar', 'baz': {}}, 'm2a': {}}},
            'lists': [[1, 2, 3], [[3, 4, 5, [6]], 7]],
            'mapsinlists': [{'foo': 'bar'}, [{'bar': 'baz'}]],
            'listsinmaps': {'foo': [1, 2, 3], 'bar': [[{'baz': '123'}]]},
        }}))

        def cb(doc):
            doc['nesting']['maps']['m1a'] = '123'
            doc['nesting']['maps']['m1']['m2']['baz']['xxx'] = '123'
            del doc['nesting']['maps']['m1']['m2a']
            doc['nesting']['lists'].delete_at(0)
            doc['nesting']['lists'][0][0].pop()
            doc['nesting']['lists'][0][0].append(100)
            doc['nesting']['mapsinlists'][0]['foo'] = 'baz'
            doc['nesting']['mapsinlists'][1][0]['foo'] = 'bar'
            del doc['nesting']['mapsinlists'][1]
            doc['nesting']['listsinmaps']['foo'].append(4)
            doc['nesting']['listsinmaps']['bar'][0][0]['baz'] = '456'
            del doc['nesting']['listsinmaps']['bar']
        s1 = A.change(s1, cb)
        assert A.equals(s1, {'nesting': {
            'maps': {'m1': {'m2': {'foo': 'bar', 'baz': {'xxx': '123'}}},
                     'm1a': '123'},
            'lists': [[[3, 4, 5, 100], 7]],
            'mapsinlists': [{'foo': 'baz'}],
            'listsinmaps': {'foo': [1, 2, 3, 4]},
        }})

    def test_replacement_of_the_entire_list(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'soba', 'ramen']}))
        s1 = A.change(s1, lambda d: d.update(
            {'japaneseNoodles': list(d['noodles'])}))
        s1 = A.change(s1, lambda d: d.update({'noodles': ['wonton', 'pho']}))
        assert A.equals(s1, {'noodles': ['wonton', 'pho'],
                             'japaneseNoodles': ['udon', 'soba', 'ramen']})
        assert list(s1['noodles']) == ['wonton', 'pho']
        assert len(s1['noodles']) == 2

    def test_assignment_changes_type_of_list_element(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'noodles': ['udon', 'soba', 'ramen']}))
        s1 = A.change(s1, lambda d: d['noodles'].__setitem__(
            1, {'type': 'soba', 'options': ['hot', 'cold']}))
        assert A.equals(s1['noodles'],
                        ['udon', {'type': 'soba', 'options': ['hot', 'cold']},
                         'ramen'])
        s1 = A.change(s1, lambda d: d['noodles'].__setitem__(
            1, ['hot soba', 'cold soba']))
        assert A.equals(s1['noodles'],
                        ['udon', ['hot soba', 'cold soba'], 'ramen'])
        s1 = A.change(s1, lambda d: d['noodles'].__setitem__(
            1, 'soba is the best'))
        assert A.equals(s1['noodles'], ['udon', 'soba is the best', 'ramen'])

    def test_list_creation_and_assignment_in_same_change(self):
        def cb(doc):
            doc['letters'] = ['a', 'b', 'c']
            doc['letters'][1] = 'd'
        s1 = A.change(A.init(), cb)
        assert s1['letters'][1] == 'd'

    def test_add_and_remove_list_elements_in_same_change(self):
        s1 = A.change(A.init(), lambda d: d.update({'noodles': []}))

        def cb(doc):
            doc['noodles'].append('udon')
            doc['noodles'].delete_at(0)
        s1 = A.change(s1, cb)
        assert A.equals(s1, {'noodles': []})
        # twice, for reference issue #151

        def cb2(doc):
            doc['noodles'].append('soba')
            doc['noodles'].delete_at(0)
        s1 = A.change(s1, cb2)
        assert A.equals(s1, {'noodles': []})

    def test_arbitrary_depth_list_nesting(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'maze': [[[[[[[['noodles', ['here']]]]]]]]]}))
        s1 = A.change(s1, lambda d:
                      d['maze'][0][0][0][0][0][0][0][1].insert(0, 'found'))
        assert A.equals(s1['maze'], [[[[[[[['noodles', ['found', 'here']]]]]]]]])
        assert s1['maze'][0][0][0][0][0][0][0][1][1] == 'here'

    def test_does_not_allow_several_references_to_same_list(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': []}))
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, lambda d: d.update({'x': d['list']}))
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, lambda d: d.update({'x': s1['list']}))

        def copy_cb(doc):
            doc['x'] = []
            doc['y'] = doc['x']
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, copy_cb)

    def test_concurrent_edits_insert_in_reverse_actorid_order(self):
        s1 = A.init('aaaa')
        s2 = A.init('bbbb')
        s1 = A.change(s1, lambda d: d.update({'list': []}))
        s2 = A.merge(s2, s1)
        s1 = A.change(s1, lambda d: d['list'].insert(0, '2@aaaa'))
        s2 = A.change(s2, lambda d: d['list'].insert(0, '2@bbbb'))
        s2 = A.merge(s2, s1)
        assert list(s2['list']) == ['2@bbbb', '2@aaaa']

    def test_concurrent_edits_insert_in_reverse_counter_order(self):
        s1 = A.init('aaaa')
        s2 = A.init('bbbb')
        s1 = A.change(s1, lambda d: d.update({'list': []}))
        s2 = A.merge(s2, s1)
        s1 = A.change(s1, lambda d: d['list'].insert(0, '2@aaaa'))
        s2 = A.change(s2, lambda d: d.update({'foo': '2@bbbb'}))
        s2 = A.change(s2, lambda d: d['list'].insert(0, '3@bbbb'))
        s2 = A.merge(s2, s1)
        assert list(s2['list']) == ['3@bbbb', '2@aaaa']


class TestNumbers:
    """ref test/test.js:800-844"""

    def _last_op(self, s1):
        return A.decode_change(A.get_last_local_change(s1))['ops'][0]

    def test_defaults_to_int_for_positive_numbers(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': 1}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'int', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': 1}

    def test_defaults_to_int_for_negative_numbers(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': -1}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'int', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': -1}

    def test_defaults_to_float64_for_floats(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': 1.1}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'float64', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': 1.1}

    def test_float64_can_be_specified_manually(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': A.Float64(3)}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'float64', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': 3}

    def test_int_can_be_specified_manually(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': A.Int(3)}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'int', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': 3}

    def test_uint_can_be_specified_manually(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': A.Uint(3)}))
        assert self._last_op(s1) == {
            'action': 'set', 'datatype': 'uint', 'insert': False,
            'key': 'number', 'obj': '_root', 'pred': [], 'value': 3}


class TestCounters:
    """ref test/test.js:844-871 (the fuller counter matrix lives in
    test_new_backend.py / test_backend.py)"""

    def test_allows_deleting_counters_from_maps(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'birds': {'wrens': A.Counter(1)}}))
        s2 = A.change(s1, lambda d: d['birds']['wrens'].increment(2))
        s3 = A.change(s2, lambda d: d['birds'].__delitem__('wrens'))
        assert s2['birds']['wrens'].value == 3
        assert A.equals(s3, {'birds': {}})

    def test_does_not_allow_deleting_counters_from_lists(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'recordings': [A.Counter(1)]}))
        s2 = A.change(s1, lambda d: d['recordings'][0].increment(2))
        assert s2['recordings'][0].value == 3
        with pytest.raises(Exception):
            A.change(s2, lambda d: d['recordings'].delete_at(0))

    def test_allows_multiple_counters_in_a_list(self):
        s1 = A.from_({'counters': [A.Counter(1), A.Counter(2)]})
        assert s1['counters'][0].value == 1
        assert s1['counters'][1].value == 2

    def test_allows_counters_in_a_list_with_non_counters(self):
        date = datetime.datetime.now(
            datetime.timezone.utc).replace(microsecond=0)
        s1 = A.from_({'counters': [A.Counter(1), -1, A.Counter(2), 2.2,
                                   True, date]})
        lst = s1['counters']
        assert lst[0].value == 1
        assert lst[1] == -1
        assert lst[2].value == 2
        assert lst[3] == 2.2
        assert lst[4] is True
        assert lst[5] == date
