"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip sharding is validated on host CPU devices (the driver
separately dry-runs the multi-chip path via __graft_entry__.py);
benchmarks run on real TPU outside of pytest.
"""

import os
import sys

# Force CPU even when the environment points JAX at a TPU tunnel: unit tests
# must run on the virtual 8-device mesh, not the single real chip. The site
# hook imports jax at interpreter startup, so setting the env var is not
# enough — update the already-imported config too.
os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_count=8').strip()
try:
    import jax
    jax.config.update('jax_platforms', 'cpu')
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        'markers',
        'slow: long-running (full crash/chaos matrices); tier-1 runs '
        "-m 'not slow'")


# ---------------------------------------------------------------------------
# slow-marker audit bookkeeping (ISSUE-7 satellite): accumulate wall time
# per test FAMILY (a parametrized function is one family) across the
# session, and record which families carry the `slow` marker. The audit
# test itself lives in tests/test_slow_audit.py and is reordered to run
# LAST, so it sees the whole session's totals — an unmarked family that
# grows past its budget fails tier-1 loudly instead of silently pushing
# the suite toward its 870s timeout.
# ---------------------------------------------------------------------------

FAMILY_DURATIONS = {}      # nodeid-without-parametrization -> seconds
SLOW_FAMILIES = set()      # families carrying the `slow` marker


def _family(nodeid):
    return nodeid.split('[', 1)[0]


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker('slow'):
            SLOW_FAMILIES.add(_family(item.nodeid))
    # the audit must observe every other test: push its module to the end
    items.sort(key=lambda item: item.module.__name__ == 'test_slow_audit'
               if hasattr(item, 'module') else False)


def pytest_runtest_logreport(report):
    if report.when in ('setup', 'call', 'teardown'):
        fam = _family(report.nodeid)
        FAMILY_DURATIONS[fam] = FAMILY_DURATIONS.get(fam, 0.0) + \
            (report.duration or 0.0)
