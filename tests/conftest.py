"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip sharding is validated on host CPU devices (the driver
separately dry-runs the multi-chip path via __graft_entry__.py);
benchmarks run on real TPU outside of pytest.
"""

import os
import sys

# Force CPU even when the environment points JAX at a TPU tunnel: unit tests
# must run on the virtual 8-device mesh, not the single real chip. The site
# hook imports jax at interpreter startup, so setting the env var is not
# enough — update the already-imported config too.
os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_count=8').strip()
try:
    import jax
    jax.config.update('jax_platforms', 'cpu')
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        'markers',
        'slow: long-running (full crash/chaos matrices); tier-1 runs '
        "-m 'not slow'")
