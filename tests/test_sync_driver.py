"""Batched fleet sync driver: differential equality with the host
per-document protocol and single-dispatch filter batching
(fleet/sync_driver.py; ref backend/sync.js:234-306)."""

import pytest

import automerge_tpu as A
from automerge_tpu import backend as Backend
from automerge_tpu.backend import init_sync_state
from automerge_tpu.backend.sync import (
    generate_sync_message, receive_sync_message)
from automerge_tpu.fleet import bloom as fleet_bloom
from automerge_tpu.fleet.sync_driver import (
    generate_sync_messages_docs, receive_sync_messages_docs)
from automerge_tpu.frontend import get_backend_state


def _backend_of(doc):
    return get_backend_state(doc)


def _make_pairs(n_docs, rounds=3):
    """n_docs local/remote doc pairs with divergent histories."""
    pairs = []
    for d in range(n_docs):
        a = A.init(f'{d:02x}' * 4 + 'aa')
        for i in range(1 + d % 3):
            a = A.change(a, {'time': 0},
                         lambda doc, i=i: doc.update({'x': i}))
        b = A.merge(A.init(f'{d:02x}' * 4 + 'bb'), a) if d % 2 else \
            A.init(f'{d:02x}' * 4 + 'bb')
        for i in range(d % 4):
            b = A.change(b, {'time': 0},
                         lambda doc, i=i: doc.update({'y': i}))
        pairs.append((a, b))
    return pairs


class TestDifferentialEquality:
    def test_messages_byte_identical_to_host(self):
        pairs = _make_pairs(12)
        batch_sa = [init_sync_state() for _ in pairs]
        batch_sb = [init_sync_state() for _ in pairs]
        host_sa = [init_sync_state() for _ in pairs]
        host_sb = [init_sync_state() for _ in pairs]
        # Backend handles freeze on use: host and batch drivers need their
        # own copies of every document
        host_a = [Backend.clone(_backend_of(a)) for a, _ in pairs]
        host_b = [Backend.clone(_backend_of(b)) for _, b in pairs]
        batch_a = [Backend.clone(_backend_of(a)) for a, _ in pairs]
        batch_b = [Backend.clone(_backend_of(b)) for _, b in pairs]

        for round_no in range(6):
            batch_sa, msgs_ab = generate_sync_messages_docs(batch_a, batch_sa)
            host_out = [generate_sync_message(doc, s)
                        for doc, s in zip(host_a, host_sa)]
            host_sa = [o[0] for o in host_out]
            host_msgs = [o[1] for o in host_out]
            for i in range(len(pairs)):
                assert (msgs_ab[i] is None) == (host_msgs[i] is None), \
                    f'round {round_no} doc {i} presence'
                if msgs_ab[i] is not None:
                    assert bytes(msgs_ab[i]) == bytes(host_msgs[i]), \
                        f'round {round_no} doc {i} bytes'

            # deliver a->b on both drivers
            batch_b, batch_sb, _ = receive_sync_messages_docs(
                batch_b, batch_sb,
                [m for m in msgs_ab])
            for i, m in enumerate(host_msgs):
                if m is not None:
                    host_b[i], host_sb[i], _ = receive_sync_message(
                        host_b[i], host_sb[i], m)

            # and the reply direction b->a
            batch_sb, msgs_ba = generate_sync_messages_docs(batch_b, batch_sb)
            host_out = [generate_sync_message(doc, s)
                        for doc, s in zip(host_b, host_sb)]
            host_sb = [o[0] for o in host_out]
            host_msgs_ba = [o[1] for o in host_out]
            for i in range(len(pairs)):
                assert (msgs_ba[i] is None) == (host_msgs_ba[i] is None)
                if msgs_ba[i] is not None:
                    assert bytes(msgs_ba[i]) == bytes(host_msgs_ba[i]), \
                        f'round {round_no} reply doc {i} bytes'
            batch_a, batch_sa, _ = receive_sync_messages_docs(
                batch_a, batch_sa, [m for m in msgs_ba])
            for i, m in enumerate(host_msgs_ba):
                if m is not None:
                    host_a[i], host_sa[i], _ = receive_sync_message(
                        host_a[i], host_sa[i], m)

        # Everyone converged
        for i in range(len(pairs)):
            assert Backend.get_heads(batch_a[i]) == \
                Backend.get_heads(batch_b[i]), f'doc {i} diverged'
            assert Backend.get_heads(batch_a[i]) == \
                Backend.get_heads(host_a[i])

    def _count_dispatches(self, monkeypatch):
        calls = {'build': 0, 'probe': 0}
        orig_build = fleet_bloom._build_flat_packed
        orig_probe = fleet_bloom._probe_flat_packed

        def count_build(*args):
            calls['build'] += 1
            return orig_build(*args)

        def count_probe(*args):
            calls['probe'] += 1
            return orig_probe(*args)
        monkeypatch.setattr(fleet_bloom, '_build_flat_packed', count_build)
        monkeypatch.setattr(fleet_bloom, '_probe_flat_packed', count_probe)
        return calls

    def test_two_filter_dispatches_per_generate(self, monkeypatch):
        # Uniform histories: every filter lands in one size class, so a
        # whole generate round is exactly one build (and, once peer filters
        # have arrived, exactly one probe) dispatch
        pairs = []
        for d in range(10):
            a = A.init(f'{d:02x}' * 4 + 'aa')
            b = A.init(f'{d:02x}' * 4 + 'bb')
            for i in range(3):
                a = A.change(a, {'time': 0},
                             lambda doc, i=i: doc.update({'x': i}))
                b = A.change(b, {'time': 0},
                             lambda doc, i=i: doc.update({'y': i}))
            pairs.append((a, b))
        a_docs = [_backend_of(a) for a, _ in pairs]
        b_docs = [_backend_of(b) for _, b in pairs]
        sa = [init_sync_state() for _ in pairs]
        sb = [init_sync_state() for _ in pairs]
        calls = self._count_dispatches(monkeypatch)

        # Round 1: both sides generate (build only: no peer filters yet)
        sa, msgs = generate_sync_messages_docs(a_docs, sa)
        assert calls['build'] == 1
        assert calls['probe'] == 0
        b_docs, sb, _ = receive_sync_messages_docs(b_docs, sb, msgs)
        # Round 2: the replies probe the received filters in ONE dispatch
        calls['build'] = calls['probe'] = 0
        sb, msgs2 = generate_sync_messages_docs(b_docs, sb)
        assert calls['probe'] == 1

    def test_skewed_filter_sizes_one_dispatch(self, monkeypatch):
        # One high-churn peer must neither inflate every row to its width
        # (the flat packed layout gives each filter its exact byte span)
        # nor split the batch into extra dispatches: skew or not, the whole
        # build is ONE device dispatch, and every filter stays
        # byte-identical to the host BloomFilter
        import hashlib
        from automerge_tpu.fleet.bloom import build_bloom_filters_batch
        from automerge_tpu.backend.sync import BloomFilter
        calls = self._count_dispatches(monkeypatch)
        hash_lists = [[hashlib.sha256(f'{i}:{j}'.encode()).hexdigest()
                       for j in range(3)] for i in range(20)]
        hash_lists.append([hashlib.sha256(f'big:{j}'.encode()).hexdigest()
                           for j in range(500)])
        built = build_bloom_filters_batch(hash_lists)
        assert calls['build'] == 1
        for row, fb in zip(hash_lists, built):
            assert bytes(fb) == bytes(BloomFilter(row).bytes)

    def test_skewed_probe_one_dispatch(self, monkeypatch):
        # Probe side of the same guarantee: filters of wildly different
        # sizes probe in ONE gather dispatch through the flat byte layout
        import hashlib
        from automerge_tpu.fleet.bloom import (
            build_bloom_filters_batch, probe_bloom_filters_batch)
        calls = self._count_dispatches(monkeypatch)
        sizes = [1, 3, 40, 500, 7]
        hash_lists = [[hashlib.sha256(f'{i}:{j}'.encode()).hexdigest()
                       for j in range(n)] for i, n in enumerate(sizes)]
        built = build_bloom_filters_batch(hash_lists)
        calls['build'] = calls['probe'] = 0
        hits = probe_bloom_filters_batch(built, hash_lists)
        assert calls['probe'] == 1
        # a filter contains everything it was built over (no false negatives)
        assert all(all(row) for row in hits)
        # and cross-probing mostly misses (bit-layout sanity, not just True)
        cross = probe_bloom_filters_batch(built[1:] + built[:1], hash_lists)
        assert not all(all(row) for row in cross)

    def test_generate_round_dispatches_size_independent(self):
        # THE O(1)-dispatch contract for sync rounds: a generate round over
        # 4x the peers issues exactly the same number of device dispatches
        # (2: one flat Bloom build, one flat probe), observed through the
        # observability roll-up the bench reports from
        from automerge_tpu.observability import dispatch_counts
        counts = {}
        for n in (6, 24):
            pairs = _make_pairs(n)
            docs = [_backend_of(a) for a, _ in pairs]
            states = [init_sync_state() for _ in docs]
            # prime theirHave/theirNeed so the probe phase runs too
            states, msgs = generate_sync_messages_docs(docs, states)
            docs_b = [_backend_of(b) for _, b in pairs]
            states_b = [init_sync_state() for _ in docs]
            docs_b, states_b, _ = receive_sync_messages_docs(
                docs_b, states_b, msgs)
            states_b, replies = generate_sync_messages_docs(docs_b, states_b)
            docs, states, _ = receive_sync_messages_docs(docs, states,
                                                         replies)
            before = dispatch_counts()
            states, msgs = generate_sync_messages_docs(docs, states)
            after = dispatch_counts()
            counts[n] = after['total'] - before['total']
            assert after['bloom'] - before['bloom'] == counts[n]
        assert counts[6] == counts[24] == 2, counts

    def test_empty_and_missing_messages(self):
        pairs = _make_pairs(4)
        docs = [_backend_of(a) for a, _ in pairs]
        states = [init_sync_state() for _ in pairs]
        out_docs, out_states, patches = receive_sync_messages_docs(
            docs, states, [None] * len(pairs))
        assert out_docs == docs
        assert out_states == states
        assert patches == [None] * len(pairs)


class TestParkedGate:
    """The StorageEngine.needs_sync parked gate (round-13 satellite): a
    sync round over a mixed live/parked population revives ONLY the docs
    a peer actually needs; quiet converged handshakes are answered
    compute-on-compressed with the doc still parked."""

    def _converged_population(self, n=6):
        """n (fleet doc, host peer) pairs driven to sync quiescence, plus
        the sync states of both sides."""
        from automerge_tpu.columnar import encode_change, decode_change_meta
        from automerge_tpu.fleet import backend as fleet_backend
        from automerge_tpu.fleet.backend import DocFleet, init_docs

        fleet = DocFleet()
        docs = init_docs(n, fleet)
        heads = [[] for _ in range(n)]
        for r in range(3):
            per_doc = []
            for d in range(n):
                buf = encode_change({
                    'actor': f'{d:04x}' * 4, 'seq': r + 1,
                    'startOp': r + 1, 'time': 0, 'message': '',
                    'deps': heads[d],
                    'ops': [{'action': 'set', 'obj': '_root',
                             'key': f'k{r}', 'value': d * 10 + r,
                             'datatype': 'int', 'pred': []}]})
                heads[d] = [decode_change_meta(buf, True)['hash']]
                per_doc.append([buf])
            docs, _ = fleet_backend.apply_changes_docs(docs, per_doc,
                                                       mirror=False)
        peers = [Backend.init() for _ in range(n)]
        ls = [init_sync_state() for _ in range(n)]
        ps = [init_sync_state() for _ in range(n)]
        for _ in range(10):
            traffic = False
            ls, msgs = generate_sync_messages_docs(docs, ls)
            for i, m in enumerate(msgs):
                if m is not None:
                    traffic = True
                    peers[i], ps[i], _ = Backend.receive_sync_message(
                        peers[i], ps[i], m)
            replies = []
            for i in range(n):
                ps[i], back = generate_sync_message(peers[i], ps[i])
                replies.append(back)
                if back is not None:
                    traffic = True
            docs, ls, _ = receive_sync_messages_docs(docs, ls, replies)
            if not traffic:
                break
        for i in range(n):
            assert Backend.get_heads(peers[i]) == \
                sorted(docs[i]['state'].heads)
        return fleet, docs, peers, ls, ps

    def test_quiet_parked_docs_stay_parked(self):
        from automerge_tpu.fleet.storage import StorageEngine
        from automerge_tpu.fleet.sync_driver import (
            generate_sync_messages_mixed, receive_sync_messages_mixed)
        from automerge_tpu.observability import health_counts

        fleet, docs, peers, ls, ps = self._converged_population()
        eng = StorageEngine(fleet)
        ids = eng.park(docs)
        assert all(i is not None for i in ids)
        before = health_counts()['storage_parked_syncs_skipped']
        out_docs, out_ls, msgs = generate_sync_messages_mixed(eng, ids, ls)
        assert msgs == [None] * len(ids)
        assert out_docs == ids               # nothing revived
        assert len(eng.main) == len(ids)
        assert out_ls == ls
        assert health_counts()['storage_parked_syncs_skipped'] > before
        # a quiet peer message (no changes, heads == ours) is absorbed
        # parked too
        ps2, peer_msgs = zip(*[generate_sync_message(p, dict(
            s, lastSentHeads=None)) for p, s in zip(peers, ps)])
        out_docs, out_ls, _patches = receive_sync_messages_mixed(
            eng, ids, out_ls, list(peer_msgs))
        assert out_docs == ids
        assert len(eng.main) == len(ids)
        for i, state in enumerate(out_ls):
            assert sorted(state['theirHeads']) == eng.heads(ids[i])

    def test_enveloped_messages_pass_the_parked_gate(self):
        """A trace-enveloped sync message from a tracing peer must be
        stripped BEFORE the parked gate's decode — unstripped, the
        0x54 magic read as hostile bytes and a valid quiet message was
        quarantined (regression: the strip lived only in the batched
        receive entry point)."""
        from automerge_tpu.fleet.storage import StorageEngine
        from automerge_tpu.fleet.sync_driver import (
            receive_sync_messages_mixed)
        from automerge_tpu.observability import tracecontext as tc

        import automerge_tpu.observability as obs

        fleet, docs, peers, ls, ps = self._converged_population()
        eng = StorageEngine(fleet)
        ids = eng.park(docs)
        ps2, peer_msgs = zip(*[generate_sync_message(p, dict(
            s, lastSentHeads=None)) for p, s in zip(peers, ps)])
        ctxs = [tc.mint() for _ in peer_msgs]
        wrapped = [tc.wrap(m, c) for m, c in zip(peer_msgs, ctxs)]
        obs.enable()
        obs.clear_spans()
        try:
            # on_error='raise': an unstripped envelope raises typed here
            out_docs, out_ls, _patches = receive_sync_messages_mixed(
                eng, ids, ls, wrapped)
            spans = {s['name']: s for s in obs.iter_spans()}
        finally:
            obs.disable()
        assert out_docs == ids               # quiet: still parked
        for i, state in enumerate(out_ls):
            assert sorted(state['theirHeads']) == eng.heads(ids[i])
        # the mixed entry point ADOPTS the stripped envelope's trace id
        # (first one wins), not just tolerates it — stitching works for
        # parked populations too
        assert spans['sync_parked_gate']['attrs']['trace'] == \
            ctxs[0].trace_id

    def test_divergent_peer_revives_only_its_doc(self):
        from automerge_tpu.fleet.storage import StorageEngine
        from automerge_tpu.fleet.sync_driver import (
            generate_sync_messages_mixed, receive_sync_messages_mixed)

        fleet, docs, peers, ls, ps = self._converged_population()
        n = len(docs)
        eng = StorageEngine(fleet)
        ids = eng.park(docs)
        # peer 2 edits: its doc (and only its doc) must revive
        from automerge_tpu.columnar import encode_change
        edit = encode_change({
            'actor': 'dd' * 16, 'seq': 1, 'startOp': 100, 'time': 0,
            'message': '', 'deps': Backend.get_heads(peers[2]),
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'new',
                     'value': 1, 'datatype': 'int', 'pred': []}]})
        peers[2], _ = Backend.apply_changes(peers[2], [edit])
        mixed = list(ids)
        for _ in range(10):
            traffic = False
            replies = []
            for i in range(n):
                ps[i], back = generate_sync_message(peers[i], ps[i])
                replies.append(back)
                traffic = traffic or back is not None
            mixed, ls, _ = receive_sync_messages_mixed(eng, mixed, ls,
                                                       replies)
            mixed, ls, msgs = generate_sync_messages_mixed(eng, mixed, ls)
            for i, m in enumerate(msgs):
                if m is not None:
                    traffic = True
                    peers[i], ps[i], _ = Backend.receive_sync_message(
                        peers[i], ps[i], m)
            if not traffic:
                break
        # only doc 2 left the main store
        assert [isinstance(x, int) for x in mixed] == \
            [i != 2 for i in range(n)]
        assert len(eng.main) == n - 1
        assert sorted(mixed[2]['state'].heads) == \
            Backend.get_heads(peers[2])

    def test_deadline_abort_leaves_storage_whole(self):
        """All-or-nothing over the parked gate: a deadline firing at
        entry touches nothing, and one firing mid-round (after the gate
        already revived) re-parks the revived docs under their ORIGINAL
        ids — the caller's handles never dangle."""
        from automerge_tpu.errors import DeadlineExceeded
        from automerge_tpu.fleet.storage import StorageEngine
        from automerge_tpu.fleet.sync_driver import (
            generate_sync_messages_mixed)
        from automerge_tpu.service.deadline import Deadline

        fleet, docs, peers, ls, ps = self._converged_population(3)
        eng = StorageEngine(fleet)
        ids = eng.park(docs)
        heads_before = [eng.heads(i) for i in ids]
        # make the round NOT quiet so the gate wants to revive
        fresh = [dict(s, theirHeads=None) for s in ls]
        # expired at entry: nothing revived, nothing discarded
        past = Deadline(-1.0, clock=lambda: 0.0)
        with pytest.raises(DeadlineExceeded):
            generate_sync_messages_mixed(eng, ids, fresh, deadline=past)
        assert len(eng.main) == len(ids)
        # expires BETWEEN the entry check and the sub-round's own check:
        # the revived docs must re-park under their original ids
        ticks = [0.0]

        def clock():
            ticks[0] += 1.0
            return ticks[0]
        mid = Deadline(1.5, clock=clock)      # 1st check ok, 2nd late
        with pytest.raises(DeadlineExceeded):
            generate_sync_messages_mixed(eng, ids, fresh, deadline=mid)
        assert len(eng.main) == len(ids)
        for i, heads in zip(ids, heads_before):
            assert eng.heads(i) == heads
