"""Metrics counters, host-phase spans, log2 histograms, and the flight
recorder (automerge_tpu.observability package)."""

import numpy as np
import pytest

from automerge_tpu import observability
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend
from automerge_tpu.observability import Histogram, Metrics, timed
from automerge_tpu.observability import hist as obs_hist
from automerge_tpu.observability import spans as obs_spans
from tests.test_fleet_backend import change_buf, ACTORS


@pytest.fixture(autouse=True)
def _obs_off():
    """Leave the module switches as the test found them (off)."""
    yield
    observability.disable()


def test_metrics_counters_track_turbo_and_exact():
    fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
    m = fb.fleet.metrics
    base = m.snapshot()
    handles = fleet_backend.init_docs(2, fb.fleet)
    per_doc = [[change_buf(ACTORS[0], 1, 1, [
        {'action': 'set', 'obj': '_root', 'key': 'a', 'value': d,
         'datatype': 'int', 'pred': []}])] for d in range(2)]
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    d = m.delta(base)
    assert d['turbo_calls'] == 1
    assert d['dispatches'] == 1
    assert d['changes_ingested'] == 2
    assert d['device_ops'] == 2
    assert d['bytes_ingested'] > 0

    # Lazy rebuilds are counted
    handles[0]['state'].materialize()
    fleet_backend.get_missing_deps(handles[0])
    d = m.delta(base)
    assert d['mirror_rebuilds'] == 1
    assert d['graph_builds'] >= 1

    # Exact path and promotion (nested maps AND objects inside sequences
    # are fleet-resident now; a sequence make past the packed-counter
    # window is the remaining promotion trigger)
    from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
    c = change_buf(ACTORS[0], 2, CTR_LIMIT + 1, [
        {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []}],
        deps=fleet_backend.get_heads(handles[0]))
    h0, _ = fleet_backend.apply_changes(handles[0], [c])
    d = m.delta(base)
    assert d['exact_calls'] >= 1
    assert d['promotions'] == 1


def test_metrics_repr_and_timed():
    m = Metrics()
    m.dispatches += 3
    with timed(m, 'decode'):
        pass
    assert 'dispatches=3' in repr(m)
    assert m.seconds['decode'] >= 0
    snap = m.snapshot()
    assert snap['dispatches'] == 3
    d = m.delta(snap)
    assert d['dispatches'] == 0


def test_fleet_memory_stats():
    """DocFleet.memory_stats reports per-component device byte accounting
    (grid/registers + each sequence size-class pool)."""
    import automerge_tpu as A
    from automerge_tpu.fleet.backend import DocFleet, FleetBackend
    fleet = DocFleet(doc_capacity=4, key_capacity=8)
    A.set_default_backend(FleetBackend(fleet))
    try:
        d = A.from_({'t': A.Text('hello'), 'x': 1}, '01' * 8)
        big = A.from_({'t': A.Text('y' * 200)}, '89' * 8)
        fleet.flush()
        stats = fleet.memory_stats()
        assert stats['total'] > 0
        assert 'lww_grid' in stats
        assert len(stats['seq_pools']) >= 2      # two size classes in use
        for pool in stats['seq_pools'].values():
            assert pool['bytes'] > 0 and pool['capacity'] >= 64
        # the 200-char Text span interned at least one boxed value
        assert stats['value_table_entries'] >= 1
        del d, big
    finally:
        from automerge_tpu import backend as host_backend
        A.set_default_backend(host_backend)


# ---------------------------------------------------------------------------
# roll-up registries: reserved-name rejection (key-collision hazard)
# ---------------------------------------------------------------------------


def test_register_sources_reject_reserved_names():
    """dispatch_counts() synthesizes 'total' and 'fleet<N>' keys; a source
    registered under one used to silently corrupt the roll-up (the module
    counter summed into / overwritten by the synthetic key). Both
    registries must refuse them."""
    from automerge_tpu.observability import (register_dispatch_source,
                                             register_health_source)
    for bad in ('total', 'fleet0', 'fleet7', 'fleet123'):
        with pytest.raises(ValueError):
            register_dispatch_source(bad, lambda: 0)
        with pytest.raises(ValueError):
            register_health_source(bad, lambda: 0)
    # non-reserved names that merely CONTAIN a reserved substring are fine
    from automerge_tpu.observability import metrics as obs_metrics
    try:
        register_dispatch_source('total_test_src', lambda: 0)
        register_dispatch_source('fleet_bloom_test', lambda: 0)
        counts = observability.dispatch_counts()
        assert counts['total_test_src'] == 0
        assert counts['fleet_bloom_test'] == 0
        # and the synthetic keys stay intact alongside them
        fleet = DocFleet(doc_capacity=2, key_capacity=2)
        counts = observability.dispatch_counts([fleet])
        assert counts['fleet0'] == fleet.metrics.dispatches
        assert counts['total'] == sum(v for k, v in counts.items()
                                      if k != 'total')
    finally:
        obs_metrics._dispatch_sources.pop('total_test_src', None)
        obs_metrics._dispatch_sources.pop('fleet_bloom_test', None)


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------


def test_histogram_bucket_boundaries():
    h = Histogram('bytes', scale=1)
    # bucket b holds scaled values in [2^(b-1), 2^b); bucket 0 holds < 1
    assert h.bucket_of(0) == 0
    assert h.bucket_of(1) == 1
    assert h.bucket_of(2) == 2
    assert h.bucket_of(3) == 2
    assert h.bucket_of(4) == 3
    assert h.bucket_of(1023) == 10
    assert h.bucket_of(1024) == 11
    assert h.bucket_bounds(3) == (4.0, 8.0)
    # nanosecond-scaled seconds histograms
    hs = Histogram('lat', scale=1e9)
    assert hs.bucket_of(0.0) == 0
    assert hs.bucket_of(1e-9) == 1
    assert hs.bucket_of(1.0) == 30     # 1e9 ns -> bit_length 30
    lo, hi = hs.bucket_bounds(hs.bucket_of(0.001))
    assert lo <= 0.001 < hi


def test_histogram_record_and_percentiles():
    h = Histogram('lat', scale=1)
    for v in (1, 1, 1, 1, 1, 1, 1, 1, 1, 100):
        h.record(v)
    s = h.summary()
    assert s['count'] == 10 and s['sum'] == 109
    assert s['min'] == 1 and s['max'] == 100
    # p50 falls in bucket 1 (upper bound 2); p99 in 100's bucket (128)
    assert s['p50'] == 2.0
    assert s['p99'] == 128.0


def test_histogram_record_many_matches_scalar_path():
    a = Histogram('a', scale=1e9)
    b = Histogram('b', scale=1e9)
    values = [0.0, 1e-9, 5e-7, 3.2e-4, 0.01, 0.25, 1.5]
    for v in values:
        a.record(v)
    b.record_many(np.asarray(values))
    assert a.counts == b.counts
    assert a.count == b.count
    assert a.vmin == b.vmin and a.vmax == b.vmax


def test_histogram_snapshot_delta():
    h = Histogram('lat', scale=1)
    for v in (1, 2, 4):
        h.record(v)
    snap = h.snapshot()
    assert snap['count'] == 3 and snap['buckets'][1] == 1
    for v in (64, 64, 64):
        h.record(v)
    d = h.delta(snap)
    # the delta distribution is ONLY the three 64s
    assert d['count'] == 3 and d['sum'] == 192
    assert d['p50'] == 128.0 and d['p99'] == 128.0
    assert sum(d['buckets']) == 3 and d['buckets'][7] == 3
    assert 'min' not in d          # min/max are not delta-able


def test_record_value_respects_master_switch():
    obs_hist.reset()
    observability.record_value('gated_metric', 1.0)
    assert 'gated_metric' not in observability.histogram_snapshot()
    observability.enable()
    observability.record_value('gated_metric', 1.0)
    observability.disable()
    assert observability.histogram_snapshot()['gated_metric']['count'] == 1
    obs_hist.reset()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_ring_wraparound_keeps_newest():
    observability.enable(span_capacity=4)
    for i in range(10):
        with observability.span(f's{i}'):
            pass
    spans = observability.iter_spans()
    assert [s['name'] for s in spans] == ['s6', 's7', 's8', 's9']
    assert observability.span_count() == 10
    observability.disable()


def test_wrapped_ring_discloses_truncation():
    """No-silent-caps: a wrapped span ring must disclose the loss — in
    the spans_dropped() count, the health counter, and a synthetic
    marker event inside the Chrome-trace export itself."""
    from automerge_tpu.observability import health_counts
    h0 = health_counts()
    observability.enable(span_capacity=4)
    for i in range(10):
        with observability.span(f's{i}'):
            pass
    assert observability.spans_dropped() == 6
    assert observability.health_delta(h0)['spans_dropped'] == 6
    events = observability.export_chrome_trace()
    marker = [e for e in events if e['ph'] == 'I' and
              e['name'] == 'spans_dropped']
    assert len(marker) == 1
    assert marker[0]['args']['dropped'] == 6
    assert marker[0]['ts'] == events[1]['ts']   # at the window's start
    # an unwrapped ring emits NO marker
    observability.enable(span_capacity=16)
    with observability.span('only'):
        pass
    assert observability.spans_dropped() == 0
    assert not [e for e in observability.export_chrome_trace()
                if e['ph'] == 'I']
    observability.disable()


def test_counts_delta_unions_keys():
    from automerge_tpu.observability import counts_delta
    assert counts_delta({'a': 5, 'b': 2}, {'a': 3}) == {'a': 2, 'b': 2}
    # a source present only in the baseline still reports its movement
    assert counts_delta({}, {'gone': 4}) == {'gone': -4}
    assert counts_delta({}, {}) == {}


def test_spans_balanced_under_exceptions():
    """Every begin has an end even when the block raises; the exception
    type is recorded on the span."""
    observability.enable(span_capacity=16)
    with pytest.raises(ValueError):
        with observability.span('outer'):
            with observability.span('inner', doc=3):
                raise ValueError('boom')
    spans = observability.iter_spans()
    assert [s['name'] for s in spans] == ['inner', 'outer']
    assert all(s['t1_ns'] >= s['t0_ns'] for s in spans)
    assert spans[0]['error'] == 'ValueError'
    assert spans[0]['attrs'] == {'doc': 3}
    assert spans[1]['error'] == 'ValueError'
    observability.disable()


def test_span_seq_tiles_contiguously():
    observability.enable(span_capacity=16)
    ps = observability.span_seq()
    ps.mark('a')
    ps.mark('b')
    ps.mark('c')
    ps.done()
    spans = observability.iter_spans()
    assert [s['name'] for s in spans] == ['a', 'b', 'c']
    # each phase ends exactly where the next begins: no unattributed gap
    assert spans[0]['t1_ns'] == spans[1]['t0_ns']
    assert spans[1]['t1_ns'] == spans[2]['t0_ns']
    observability.disable()


def test_span_off_is_noop_and_cheap():
    assert not obs_spans.on()
    before = observability.span_count()
    with observability.span('never'):
        pass
    assert observability.span_count() == before


def test_export_chrome_trace_format(tmp_path):
    import json
    observability.enable(span_capacity=8)
    with observability.span('phase', docs=2):
        pass
    path = tmp_path / 'trace.json'
    events = observability.export_chrome_trace(str(path))
    assert events and events[-1]['ph'] == 'X'
    assert events[-1]['name'] == 'phase'
    assert events[-1]['dur'] >= 0 and 'ts' in events[-1]
    assert events[-1]['args'] == {'docs': 2}
    on_disk = json.loads(path.read_text())
    assert on_disk['traceEvents'] == events
    observability.disable()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_dump(tmp_path):
    import json
    from automerge_tpu.observability import recorder
    recorder.clear_events()
    recorder.configure(capacity=3)
    for i in range(5):
        observability.record_event('probe', doc=i)
    evs = observability.recent_events()
    assert [e['doc'] for e in evs] == [2, 3, 4]       # bounded ring
    report = observability.dump_flight_record(
        'unit_test', detail={'docs': [4]},
        path=str(tmp_path / 'dump.json'))
    assert report['trigger'] == 'unit_test'
    assert [e['doc'] for e in report['events']] == [2, 3, 4]
    assert observability.last_flight_record() is report
    on_disk = json.loads((tmp_path / 'dump.json').read_text())
    assert on_disk['trigger'] == 'unit_test'
    assert on_disk['detail'] == {'docs': [4]}
    assert 'health' in on_disk
    recorder.configure(capacity=256)
    recorder.clear_events()


def test_dump_carries_recent_spans_without_evicting_events():
    """Span closes must NOT churn the small fault-event ring (a traced
    recovery would otherwise evict the rot/quarantine events the dump
    exists for); instead the dump reads the span ring's tail."""
    from automerge_tpu.observability import recorder
    recorder.clear_events()
    recorder.configure(capacity=4)
    observability.record_event('journal_rot', durable_id=9, at_byte=123)
    observability.enable(span_capacity=64)
    for i in range(32):                       # far past event capacity
        with observability.span(f'phase{i}'):
            pass
    observability.disable()
    evs = observability.recent_events()
    assert [e['kind'] for e in evs] == ['journal_rot']   # not evicted
    report = observability.dump_flight_record('unit_test')
    assert report['events'][0]['kind'] == 'journal_rot'
    assert [s['name'] for s in report['recent_spans']][-1] == 'phase31'
    assert len(report['recent_spans']) <= 64
    recorder.configure(capacity=256)
    recorder.clear_events()
