"""Metrics counters and profiler trace helper (automerge_tpu.observability)."""

import numpy as np

from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, FleetBackend
from automerge_tpu.observability import Metrics, timed
from tests.test_fleet_backend import change_buf, ACTORS


def test_metrics_counters_track_turbo_and_exact():
    fb = FleetBackend(DocFleet(doc_capacity=4, key_capacity=4))
    m = fb.fleet.metrics
    base = m.snapshot()
    handles = fleet_backend.init_docs(2, fb.fleet)
    per_doc = [[change_buf(ACTORS[0], 1, 1, [
        {'action': 'set', 'obj': '_root', 'key': 'a', 'value': d,
         'datatype': 'int', 'pred': []}])] for d in range(2)]
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    d = m.delta(base)
    assert d['turbo_calls'] == 1
    assert d['dispatches'] == 1
    assert d['changes_ingested'] == 2
    assert d['device_ops'] == 2
    assert d['bytes_ingested'] > 0

    # Lazy rebuilds are counted
    handles[0]['state'].materialize()
    fleet_backend.get_missing_deps(handles[0])
    d = m.delta(base)
    assert d['mirror_rebuilds'] == 1
    assert d['graph_builds'] >= 1

    # Exact path and promotion (nested maps AND objects inside sequences
    # are fleet-resident now; a sequence make past the packed-counter
    # window is the remaining promotion trigger)
    from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
    c = change_buf(ACTORS[0], 2, CTR_LIMIT + 1, [
        {'action': 'makeList', 'obj': '_root', 'key': 'l', 'pred': []}],
        deps=fleet_backend.get_heads(handles[0]))
    h0, _ = fleet_backend.apply_changes(handles[0], [c])
    d = m.delta(base)
    assert d['exact_calls'] >= 1
    assert d['promotions'] == 1


def test_metrics_repr_and_timed():
    m = Metrics()
    m.dispatches += 3
    with timed(m, 'decode'):
        pass
    assert 'dispatches=3' in repr(m)
    assert m.seconds['decode'] >= 0
    snap = m.snapshot()
    assert snap['dispatches'] == 3
    d = m.delta(snap)
    assert d['dispatches'] == 0


def test_fleet_memory_stats():
    """DocFleet.memory_stats reports per-component device byte accounting
    (grid/registers + each sequence size-class pool)."""
    import automerge_tpu as A
    from automerge_tpu.fleet.backend import DocFleet, FleetBackend
    fleet = DocFleet(doc_capacity=4, key_capacity=8)
    A.set_default_backend(FleetBackend(fleet))
    try:
        d = A.from_({'t': A.Text('hello'), 'x': 1}, '01' * 8)
        big = A.from_({'t': A.Text('y' * 200)}, '89' * 8)
        fleet.flush()
        stats = fleet.memory_stats()
        assert stats['total'] > 0
        assert 'lww_grid' in stats
        assert len(stats['seq_pools']) >= 2      # two size classes in use
        for pool in stats['seq_pools'].values():
            assert pool['bytes'] > 0 and pool['capacity'] >= 64
        # the 200-char Text span interned at least one boxed value
        assert stats['value_table_entries'] >= 1
        del d, big
    finally:
        from automerge_tpu import backend as host_backend
        A.set_default_backend(host_backend)
