"""The exact multi-value register engine (fleet/registers.py) against the
host OpSet oracle: conflict sets, set-vs-delete resurrection, per-op counter
accumulation, self-conflict flagging — the corners the LWW scatter engine
documents away must be exact here."""

import numpy as np
import pytest

from automerge_tpu.backend.op_set import OpSet
from automerge_tpu.columnar import encode_change, decode_change
from automerge_tpu.common import lamport_key, parse_op_id
from automerge_tpu.fleet.registers import (
    DEL, INC, PAD, SET, RegisterOpBatch, RegisterState,
    apply_register_batch, materialize_registers)

ACTORS = sorted(['aa' * 16, 'bb' * 16, 'cc' * 16])
ANUM = {a: i for i, a in enumerate(ACTORS)}
KEYS = ['k0', 'k1', 'k2', 'k3']
KNUM = {k: i for i, k in enumerate(KEYS)}


def pack(op_id):
    ctr, actor = parse_op_id(op_id)
    return (ctr << 8) | ANUM[actor]


def batch_of(op_lists, n_docs=1, d_preds=2):
    """op_lists: per-doc list of (kind, key, op_id, value, preds)."""
    width = max((len(o) for o in op_lists), default=1)
    kind = np.zeros((n_docs, width), dtype=np.int32)
    key_id = np.zeros((n_docs, width), dtype=np.int32)
    packed = np.zeros((n_docs, width), dtype=np.int32)
    value = np.zeros((n_docs, width), dtype=np.int32)
    preds = np.zeros((n_docs, width, d_preds), dtype=np.int32)
    overflow = np.zeros((n_docs, width), dtype=bool)
    for d, ops in enumerate(op_lists):
        for i, (k, key, op_id, val, pred) in enumerate(ops):
            kind[d, i] = k
            key_id[d, i] = KNUM[key]
            packed[d, i] = pack(op_id)
            value[d, i] = val
            if len(pred) > d_preds:
                overflow[d, i] = True
            for j, p in enumerate(pred[:d_preds]):
                preds[d, i, j] = pack(p)
    return RegisterOpBatch(kind, key_id, packed, value, preds, overflow)


def run_device(ops, n_actor_slots=4):
    state = RegisterState.empty(1, len(KEYS), n_actor_slots)
    state, _ = apply_register_batch(state, batch_of([ops]))
    return state


def host_oracle(changes):
    """Apply hand-built changes to the host engine; return
    {key: (winner_value, {opId: value})} from the whole-doc patch."""
    doc = OpSet()
    doc.apply_changes([encode_change(c) for c in changes])
    props = doc.get_patch()['diffs']['props']
    out = {}
    for key, candidates in props.items():
        if not candidates:
            continue
        winner = max(candidates.keys(), key=lamport_key)
        conflicts = {pack(op_id): leaf['value']
                     for op_id, leaf in candidates.items()} \
            if len(candidates) > 1 else {}
        out[key] = (candidates[winner]['value'], conflicts)
    return out


def device_view(state):
    docs = materialize_registers(state, KEYS)
    assert not bool(np.asarray(state.inexact)[0])
    return docs[0]


class TestExactCorners:
    def test_concurrent_conflict_set(self):
        a, b = ACTORS[0], ACTORS[1]
        ops = [(SET, 'k0', f'1@{a}', 10, []),
               (SET, 'k0', f'1@{b}', 20, [])]
        state = run_device(ops)
        assert device_view(state) == {'k0': (20, {pack(f'1@{a}'): 10,
                                                  pack(f'1@{b}'): 20})}
        changes = [
            {'actor': a, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
             'ops': [{'action': 'set', 'obj': '_root', 'key': 'k0',
                      'value': 10, 'datatype': 'int', 'pred': []}]},
            {'actor': b, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
             'ops': [{'action': 'set', 'obj': '_root', 'key': 'k0',
                      'value': 20, 'datatype': 'int', 'pred': []}]},
        ]
        assert host_oracle(changes) == device_view(state)

    def test_set_vs_delete_resurrection(self):
        """A delete kills only its preds: a concurrent set survives even
        when the delete's opId is Lamport-greater (the LWW engine's
        documented divergence; exact here)."""
        a, b, c = ACTORS
        ops = [(SET, 'k1', f'1@{a}', 5, []),
               (SET, 'k1', f'2@{b}', 7, [f'1@{a}']),     # concurrent branch 1
               (DEL, 'k1', f'9@{c}', 0, [f'1@{a}'])]     # concurrent branch 2
        state = run_device(ops)
        # 9@cc > 2@bb, yet bb's set survives because the del pred'd only 1@aa
        assert device_view(state) == {'k1': (7, {})}

        h1 = {'actor': a, 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
              'ops': [{'action': 'set', 'obj': '_root', 'key': 'k1',
                       'value': 5, 'datatype': 'int', 'pred': []}]}
        dep = decode_change(encode_change(h1))['hash']
        changes = [h1,
                   {'actor': b, 'seq': 1, 'startOp': 2, 'time': 0,
                    'deps': [dep],
                    'ops': [{'action': 'set', 'obj': '_root', 'key': 'k1',
                             'value': 7, 'datatype': 'int',
                             'pred': [f'1@{a}']}]},
                   {'actor': c, 'seq': 1, 'startOp': 9, 'time': 0,
                    'deps': [dep],
                    'ops': [{'action': 'del', 'obj': '_root', 'key': 'k1',
                             'pred': [f'1@{a}']}]}]
        assert host_oracle(changes) == device_view(state)

    def test_counter_accumulates_into_its_op(self):
        a, b = ACTORS[0], ACTORS[1]
        ops = [(SET, 'k2', f'1@{a}', 10, []),
               (INC, 'k2', f'2@{a}', 4, [f'1@{a}']),
               (INC, 'k2', f'2@{b}', -2, [f'1@{a}'])]
        state = run_device(ops)
        assert device_view(state) == {'k2': (12, {})}

    def test_counter_overwrite_drops_accumulator(self):
        a = ACTORS[0]
        ops = [(SET, 'k2', f'1@{a}', 10, []),
               (INC, 'k2', f'2@{a}', 3, [f'1@{a}']),
               (SET, 'k2', f'3@{a}', 100, [f'1@{a}'])]
        state = run_device(ops)
        assert device_view(state) == {'k2': (100, {})}

    def test_delete_then_nothing_visible(self):
        a = ACTORS[0]
        ops = [(SET, 'k3', f'1@{a}', 1, []),
               (DEL, 'k3', f'2@{a}', 0, [f'1@{a}'])]
        state = run_device(ops)
        assert device_view(state) == {}

    def test_same_batch_kill_ordering(self):
        """An op and its killer in one batch: the scan applies them in
        order, so the kill lands (the unordered scatter engine can't)."""
        a, b = ACTORS[0], ACTORS[1]
        ops = [(SET, 'k0', f'5@{b}', 1, []),
               (SET, 'k0', f'6@{a}', 2, [f'5@{b}'])]   # smaller actor, kills
        state = run_device(ops)
        assert device_view(state) == {'k0': (2, {})}


class TestInexactFlags:
    def test_self_conflict_flags_doc(self):
        a = ACTORS[0]
        ops = [(SET, 'k0', f'1@{a}', 1, []),
               (SET, 'k0', f'2@{a}', 2, [])]   # own overwrite without pred
        state = run_device(ops)
        assert bool(np.asarray(state.inexact)[0])

    def test_bad_inc_flags_doc(self):
        a = ACTORS[0]
        ops = [(INC, 'k0', f'1@{a}', 1, [f'9@{a}'])]
        state = run_device(ops)
        assert bool(np.asarray(state.inexact)[0])

    def test_pred_overflow_flags_doc(self):
        a = ACTORS[0]
        ops = [(SET, 'k0', f'1@{a}', 1, []),
               (SET, 'k0', f'9@{a}', 2,
                [f'1@{a}', f'3@{a}', f'4@{a}'])]   # > d_preds=2
        state = run_device(ops)
        assert bool(np.asarray(state.inexact)[0])


class TestRandomizedDifferential:
    @pytest.mark.parametrize('seed', [0, 1, 2])
    def test_random_histories_match_host(self, seed):
        """Random causally-valid op streams (sets/dels/incs with correct
        preds) through both engines; visible winners and conflict sets must
        match the host patch exactly."""
        rng = np.random.default_rng(seed)
        visible = {k: set() for k in KEYS}    # key -> visible opIds
        counters = {}                          # opId -> is counter
        ops, changes = [], []
        ctr = {a: 0 for a in ACTORS}
        seqs = {a: 0 for a in ACTORS}
        deps = []
        for step in range(40):
            actor = ACTORS[int(rng.integers(0, 3))]
            key = KEYS[int(rng.integers(0, len(KEYS)))]
            ctr[actor] = max(ctr.values()) + 1
            seqs[actor] += 1
            op_id = f'{ctr[actor]}@{actor}'
            vis = sorted(visible[key], key=lamport_key)
            roll = rng.random()
            counter_targets = [v for v in vis if counters.get(v)]
            if roll < 0.2 and counter_targets:
                target = counter_targets[int(rng.integers(0, len(counter_targets)))]
                delta = int(rng.integers(-5, 10))
                ops.append((INC, key, op_id, delta, [target]))
                op = {'action': 'inc', 'obj': '_root', 'key': key,
                      'value': delta, 'pred': [target]}
            elif roll < 0.4 and vis:
                pred = vis if rng.random() < 0.7 else vis[:1]
                ops.append((DEL, key, op_id, 0, pred))
                op = {'action': 'del', 'obj': '_root', 'key': key,
                      'pred': pred}
                visible[key] -= set(pred)
            else:
                is_counter = rng.random() < 0.3
                val = int(rng.integers(0, 100))
                pred = vis  # always supersede what we see (frontend shape)
                ops.append((SET, key, op_id, val, pred))
                op = {'action': 'set', 'obj': '_root', 'key': key,
                      'value': val, 'pred': pred,
                      'datatype': 'counter' if is_counter else 'int'}
                visible[key] -= set(pred)
                visible[key].add(op_id)
                counters[op_id] = is_counter
            change = {'actor': actor, 'seq': seqs[actor],
                      'startOp': ctr[actor], 'time': 0, 'deps': deps,
                      'ops': [op]}
            deps = [decode_change(encode_change(change))['hash']]
            changes.append(change)

        state = run_device(ops, n_actor_slots=4)
        assert host_oracle(changes) == device_view(state)


class TestSlotWidthFlags:
    def test_actor_beyond_slot_width_flags(self):
        a, c = ACTORS[0], ACTORS[2]
        state = RegisterState.empty(1, len(KEYS), 2)   # slots for 2 actors
        state, _ = apply_register_batch(state, batch_of([[
            (SET, 'k0', f'1@{c}', 1, [])]]))           # actor num 2 >= 2
        assert bool(np.asarray(state.inexact)[0])

    def test_pred_actor_beyond_slot_width_flags(self):
        a, c = ACTORS[0], ACTORS[2]
        state = RegisterState.empty(1, len(KEYS), 2)
        state, _ = apply_register_batch(state, batch_of([[
            (SET, 'k0', f'1@{a}', 1, []),
            (DEL, 'k0', f'2@{a}', 0, [f'1@{c}'])]]))
        assert bool(np.asarray(state.inexact)[0])

    def test_null_valued_set_keeps_conflicts(self):
        """A winner decoding to None must not drop the key or its conflict
        set (regression)."""
        a, b = ACTORS[0], ACTORS[1]
        table = [None]
        state = RegisterState.empty(1, len(KEYS), 4)
        batch = batch_of([[
            (SET, 'k0', f'1@{a}', 5, []),
            (SET, 'k0', f'1@{b}', -2, [])]])   # -2 = table ref 0 -> None
        state, _ = apply_register_batch(state, batch)
        docs = materialize_registers(state, KEYS, value_table=table)
        winner, conflicts = docs[0]['k0']
        assert winner is None
        assert conflicts == {pack(f'1@{a}'): 5, pack(f'1@{b}'): None}


class TestWireToRegisters:
    """Full wire path: binary changes -> native C++ parse (with preds) ->
    RegisterOpBatch -> exact device state, against the host oracle."""

    @pytest.mark.parametrize('seed', [5, 6])
    def test_native_ingest_to_registers(self, seed):
        from automerge_tpu import native
        from automerge_tpu.fleet.registers import rows_to_register_batch
        if not native.available():
            pytest.skip('native codec unavailable')
        rng = np.random.default_rng(seed)
        visible = {k: set() for k in KEYS}
        counters = {}
        changes, deps = [], []
        ctr = {a: 0 for a in ACTORS}
        seqs = {a: 0 for a in ACTORS}
        for step in range(30):
            actor = ACTORS[int(rng.integers(0, 3))]
            key = KEYS[int(rng.integers(0, len(KEYS)))]
            ctr[actor] = max(ctr.values()) + 1
            seqs[actor] += 1
            op_id = f'{ctr[actor]}@{actor}'
            vis = sorted(visible[key], key=lamport_key)
            roll = rng.random()
            ctr_targets = [v for v in vis if counters.get(v)]
            if roll < 0.2 and ctr_targets:
                op = {'action': 'inc', 'obj': '_root', 'key': key,
                      'value': int(rng.integers(-5, 10)),
                      'pred': ctr_targets[:1]}
            elif roll < 0.35 and vis:
                op = {'action': 'del', 'obj': '_root', 'key': key,
                      'pred': vis}
                visible[key] -= set(vis)
            else:
                is_counter = rng.random() < 0.3
                op = {'action': 'set', 'obj': '_root', 'key': key,
                      'value': int(rng.integers(0, 100)), 'pred': vis,
                      'datatype': 'counter' if is_counter else 'int'}
                visible[key] -= set(vis)
                visible[key].add(op_id)
                counters[op_id] = is_counter
            change = {'actor': actor, 'seq': seqs[actor],
                      'startOp': ctr[actor], 'time': 0, 'deps': deps,
                      'ops': [op]}
            deps = [decode_change(encode_change(change))['hash']]
            changes.append(change)

        buffers = [encode_change(c) for c in changes]
        out = native.ingest_changes(buffers, list(range(len(buffers))),
                                    with_meta=True)
        assert out is not None
        rows, nat_keys, nat_actors, meta = out
        # Remap native key/actor numbering to the test's sorted tables
        key_remap = np.array([KNUM[k] for k in nat_keys], dtype=np.int32)
        actor_remap = np.array([ANUM[a] for a in nat_actors], dtype=np.int32)
        key_ids = key_remap[rows['key']]
        def remap(p):
            return np.where(p != 0,
                            (p >> 8 << 8) | actor_remap[p & 0xff], 0)
        packed = remap(rows['packed'])
        preds = remap(rows['pred'])
        doc_ids = np.zeros(len(key_ids), dtype=np.int64)   # all one doc
        batch = rows_to_register_batch(doc_ids, rows['flags'], key_ids,
                                       packed, rows['value'], rows['pred_off'],
                                       preds, n_docs=1)
        state = RegisterState.empty(1, len(KEYS), 4)
        state, _ = apply_register_batch(state, batch)
        assert host_oracle(changes) == device_view(state)
