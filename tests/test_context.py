"""Mutation-context conformance tests: assert the exact ops and local diffs
each mutation emits (ported semantics of reference test/context_test.js, which
replaces applyPatch with a sinon spy and inspects context.ops)."""

import datetime

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.frontend.context import Context
from automerge_tpu.frontend.apply_patch import interpret_patch
from automerge_tpu.frontend.proxies import root_object_proxy
from automerge_tpu.frontend import Text, Table, Counter

ACTOR = 'aabbcc'


class PatchSpy:
    """Records every local diff handed to applyPatch, then really applies it
    so multi-step mutations inside one test still see their own writes."""

    def __init__(self):
        self.calls = []

    def __call__(self, diff, root, updated):
        self.calls.append(diff)
        interpret_patch(diff, root, updated)


def make_doc(setup=None):
    """A document built through the real API (so caches/conflicts are real),
    plus a fresh Context with a recording patch spy."""
    doc = am.init(ACTOR)
    if setup is not None:
        doc = am.change(doc, setup)
    spy = PatchSpy()
    context = Context(doc, ACTOR, apply_patch=spy)
    root_object_proxy(context)   # wires context.instantiate_object
    return doc, context, spy


class TestSetMapKey:
    def test_assign_primitive_to_map_key(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'sparrows', 5)
        assert context.ops == [{'obj': '_root', 'action': 'set',
                                'key': 'sparrows', 'insert': False, 'value': 5,
                                'datatype': 'int', 'pred': []}]
        assert spy.calls == [{
            'objectId': '_root', 'type': 'map', 'props': {
                'sparrows': {f'1@{ACTOR}': {'type': 'value', 'value': 5,
                                            'datatype': 'int'}}}}]

    def test_noop_if_value_unchanged(self):
        _doc, context, spy = make_doc(lambda d: d.update({'goldfinches': 3}))
        context.set_map_key([], 'goldfinches', 3)
        assert context.ops == []
        assert spy.calls == []

    def test_allows_conflict_resolution(self):
        # A doc with a conflict on 'magpies': assigning even the winning value
        # must emit an op (it resolves the conflict)
        doc1 = am.init('aa11')
        doc1 = am.change(doc1, lambda d: d.update({'magpies': 1}))
        doc2 = am.init('bb22')
        doc2 = am.change(doc2, lambda d: d.update({'magpies': 2}))
        merged = am.merge(doc1, doc2)
        assert am.get_conflicts(merged, 'magpies') is not None
        spy = PatchSpy()
        context = Context(merged, ACTOR, apply_patch=spy)
        context.set_map_key([], 'magpies', merged['magpies'])
        assert len(context.ops) == 1
        assert len(context.ops[0]['pred']) == 2

    def test_create_nested_maps(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'birds', {'goldfinches': 3})
        assert context.ops == [
            {'obj': '_root', 'action': 'makeMap', 'key': 'birds',
             'insert': False, 'pred': []},
            {'obj': f'1@{ACTOR}', 'action': 'set', 'key': 'goldfinches',
             'insert': False, 'value': 3, 'datatype': 'int', 'pred': []},
        ]
        assert spy.calls == [{
            'objectId': '_root', 'type': 'map', 'props': {'birds': {
                f'1@{ACTOR}': {'objectId': f'1@{ACTOR}', 'type': 'map',
                               'props': {'goldfinches': {
                                   f'2@{ACTOR}': {'type': 'value', 'value': 3,
                                                  'datatype': 'int'}}}}}}}]

    def test_assignment_inside_nested_maps(self):
        doc, context, spy = make_doc(lambda d: d.update({'birds': {'goldfinches': 3}}))
        birds_id = Frontend.get_object_id(doc['birds'])
        context.set_map_key([{'key': 'birds', 'objectId': birds_id}],
                            'goldfinches', 15)
        assert context.ops == [{'obj': birds_id, 'action': 'set',
                                'key': 'goldfinches', 'insert': False,
                                'value': 15, 'datatype': 'int', 'pred': [f'2@{ACTOR}']}]

    def test_create_nested_lists(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'birds', ['sparrow', 'goldfinch'])
        assert context.ops == [
            {'obj': '_root', 'action': 'makeList', 'key': 'birds',
             'insert': False, 'pred': []},
            {'obj': f'1@{ACTOR}', 'action': 'set', 'elemId': '_head',
             'insert': True, 'values': ['sparrow', 'goldfinch'], 'pred': []},
        ]

    def test_create_nested_text(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'text', Text('hi'))
        assert context.ops == [
            {'obj': '_root', 'action': 'makeText', 'key': 'text',
             'insert': False, 'pred': []},
            {'obj': f'1@{ACTOR}', 'action': 'set', 'elemId': '_head',
             'insert': True, 'values': ['h', 'i'], 'pred': []},
        ]

    def test_create_nested_table(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'books', Table())
        assert context.ops == [{'obj': '_root', 'action': 'makeTable',
                                'key': 'books', 'insert': False, 'pred': []}]
        assert spy.calls == [{
            'objectId': '_root', 'type': 'map', 'props': {'books': {
                f'1@{ACTOR}': {'objectId': f'1@{ACTOR}', 'type': 'table',
                               'props': {}}}}}]

    def test_assign_date_value(self):
        now = datetime.datetime.now(datetime.timezone.utc)
        _doc, context, spy = make_doc()
        context.set_map_key([], 'now', now)
        ts = int(round(now.timestamp() * 1000))
        assert context.ops == [{'obj': '_root', 'action': 'set', 'key': 'now',
                                'insert': False, 'value': ts,
                                'datatype': 'timestamp', 'pred': []}]

    def test_assign_counter_value(self):
        _doc, context, spy = make_doc()
        context.set_map_key([], 'counter', Counter(3))
        assert context.ops == [{'obj': '_root', 'action': 'set',
                                'key': 'counter', 'insert': False, 'value': 3,
                                'datatype': 'counter', 'pred': []}]


class TestDeleteMapKey:
    def test_remove_existing_key(self):
        _doc, context, spy = make_doc(lambda d: d.update({'sparrows': 5}))
        context.delete_map_key([], 'sparrows')
        assert context.ops == [{'obj': '_root', 'action': 'del',
                                'key': 'sparrows', 'insert': False,
                                'pred': [f'1@{ACTOR}']}]
        assert spy.calls == [{'objectId': '_root', 'type': 'map',
                              'props': {'sparrows': {}}}]

    def test_noop_if_key_missing(self):
        _doc, context, spy = make_doc()
        context.delete_map_key([], 'sparrows')
        assert context.ops == []
        assert spy.calls == []


class TestListManipulation:
    def setup_list(self):
        doc, context, spy = make_doc(lambda d: d.update({'birds': ['sparrow',
                                                                  'goldfinch']}))
        list_id = Frontend.get_object_id(doc['birds'])
        path = [{'key': 'birds', 'objectId': list_id}]
        return doc, context, spy, list_id, path

    def test_overwrite_existing_element(self):
        _doc, context, _spy, list_id, path = self.setup_list()
        context.set_list_index(path, 0, 'starling')
        assert context.ops == [{'obj': list_id, 'action': 'set',
                                'elemId': f'2@{ACTOR}', 'insert': False,
                                'value': 'starling', 'pred': [f'2@{ACTOR}']}]

    def test_nested_objects_on_assignment(self):
        _doc, context, _spy, list_id, path = self.setup_list()
        context.set_list_index(path, 1, {'english': 'goldfinch'})
        assert context.ops == [
            {'obj': list_id, 'action': 'makeMap', 'elemId': f'3@{ACTOR}',
             'insert': False, 'pred': [f'3@{ACTOR}']},
            {'obj': f'4@{ACTOR}', 'action': 'set', 'key': 'english',
             'insert': False, 'value': 'goldfinch', 'pred': []},
        ]

    def test_nested_objects_on_insertion(self):
        _doc, context, _spy, list_id, path = self.setup_list()
        context.splice(path, 2, 0, [{'english': 'goldfinch'}])
        assert context.ops == [
            {'obj': list_id, 'action': 'makeMap', 'elemId': f'3@{ACTOR}',
             'insert': True, 'pred': []},
            {'obj': f'4@{ACTOR}', 'action': 'set', 'key': 'english',
             'insert': False, 'value': 'goldfinch', 'pred': []},
        ]

    def test_multi_insert_for_primitive_runs(self):
        _doc, context, _spy, list_id, path = self.setup_list()
        context.splice(path, 2, 0, ['greenfinch', 'bullfinch'])
        assert context.ops == [{'obj': list_id, 'action': 'set',
                                'elemId': f'3@{ACTOR}', 'insert': True,
                                'values': ['greenfinch', 'bullfinch'],
                                'pred': []}]

    def test_delete_single_element(self):
        _doc, context, spy, list_id, path = self.setup_list()
        context.splice(path, 0, 1, [])
        assert context.ops == [{'obj': list_id, 'action': 'del',
                                'elemId': f'2@{ACTOR}', 'insert': False,
                                'pred': [f'2@{ACTOR}']}]
        subpatch = next(iter(spy.calls[-1]['props']['birds'].values()))
        assert subpatch['edits'] == [{'action': 'remove', 'index': 0,
                                      'count': 1}]

    def test_multi_delete_compression(self):
        # Consecutive elemIds with consecutive preds compress to one multiOp
        _doc, context, _spy, list_id, path = self.setup_list()
        context.splice(path, 0, 2, [])
        assert context.ops == [{'obj': list_id, 'action': 'del',
                                'elemId': f'2@{ACTOR}', 'insert': False,
                                'pred': [f'2@{ACTOR}'], 'multiOp': 2}]

    def test_multi_delete_broken_run(self):
        # Overwriting the middle element breaks the consecutive-pred run:
        # deletion must emit separate del ops
        doc = am.init(ACTOR)
        doc = am.change(doc, lambda d: d.update({'birds': ['a', 'b', 'c']}))
        doc = am.change(doc, lambda d: d['birds'].__setitem__(1, 'B'))
        spy = PatchSpy()
        context = Context(doc, ACTOR, apply_patch=spy)
        list_id = Frontend.get_object_id(doc['birds'])
        path = [{'key': 'birds', 'objectId': list_id}]
        context.splice(path, 0, 3, [])
        del_ops = [op for op in context.ops if op['action'] == 'del']
        assert len(del_ops) > 1

    def test_splice_delete_and_insert(self):
        _doc, context, spy, list_id, path = self.setup_list()
        context.splice(path, 0, 1, ['wren'])
        assert context.ops == [
            {'obj': list_id, 'action': 'del', 'elemId': f'2@{ACTOR}',
             'insert': False, 'pred': [f'2@{ACTOR}']},
            {'obj': list_id, 'action': 'set', 'elemId': '_head',
             'insert': True, 'value': 'wren', 'pred': []},
        ]

    def test_counter_delete_from_list_rejected(self):
        doc = am.init(ACTOR)
        doc = am.change(doc, lambda d: d.update({'counts': [Counter(1)]}))
        spy = PatchSpy()
        context = Context(doc, ACTOR, apply_patch=spy)
        context.instantiate_object = lambda *a, **k: None
        list_id = Frontend.get_object_id(doc['counts'])
        path = [{'key': 'counts', 'objectId': list_id}]
        with pytest.raises(TypeError):
            context.splice(path, 0, 1, [])


class TestTableManipulation:
    def test_add_table_row(self):
        doc = am.init(ACTOR)
        doc = am.change(doc, lambda d: d.update({'books': Table()}))
        spy = PatchSpy()
        context = Context(doc, ACTOR, apply_patch=spy)
        table_id = Frontend.get_object_id(doc['books'])
        path = [{'key': 'books', 'objectId': table_id}]
        am.set_uuid_factory(lambda: '11111111-1111-1111-1111-111111111111')
        try:
            row_id = context.add_table_row(
                path, {'title': 'Korm', 'author': 'Fravia'})
        finally:
            am.set_uuid_factory(None)
        assert row_id == '11111111-1111-1111-1111-111111111111'
        assert context.ops == [
            {'obj': table_id, 'action': 'makeMap', 'key': row_id,
             'insert': False, 'pred': []},
            {'obj': f'2@{ACTOR}', 'action': 'set', 'key': 'author',
             'insert': False, 'value': 'Fravia', 'pred': []},
            {'obj': f'2@{ACTOR}', 'action': 'set', 'key': 'title',
             'insert': False, 'value': 'Korm', 'pred': []},
        ]

    def test_delete_table_row(self):
        doc = am.init(ACTOR)

        def setup(d):
            d['books'] = Table()
            d['books'].add({'title': 'Korm', 'author': 'Fravia'})
        doc = am.change(doc, setup)
        table = doc['books']
        row_id = table.ids[0]
        row_op_id = table.op_ids[row_id]
        spy = PatchSpy()
        context = Context(doc, ACTOR, apply_patch=spy)
        table_id = Frontend.get_object_id(table)
        path = [{'key': 'books', 'objectId': table_id}]
        context.delete_table_row(path, row_id, row_op_id)
        assert context.ops == [{'obj': table_id, 'action': 'del',
                                'key': row_id, 'insert': False,
                                'pred': [row_op_id]}]


class TestIncrement:
    def test_increment_counter(self):
        doc, context, spy = make_doc(lambda d: d.update({'counter': Counter(0)}))
        context.increment([], 'counter', 1)
        assert context.ops == [{'obj': '_root', 'action': 'inc',
                                'key': 'counter', 'insert': False, 'value': 1,
                                'pred': [f'1@{ACTOR}']}]
        assert spy.calls == [{'objectId': '_root', 'type': 'map', 'props': {
            'counter': {f'2@{ACTOR}': {'value': 1, 'datatype': 'counter'}}}}]


class TestConflictedContexts:
    """Remaining context cases (ref context_test.js:80-119, 205-218,
    344-359), built through the real API so conflicts are genuine."""

    def test_assignment_inside_conflicted_maps(self):
        # Two actors concurrently assign a nested map to the same key; a
        # write through the winner must patch BOTH conflict branches (the
        # loser gets an empty props node)
        doc1 = am.change(am.init('aa11'),
                         lambda d: d.update({'birds': {'robins': 1}}))
        doc2 = am.change(am.init('bb22'),
                         lambda d: d.update({'birds': {'wrens': 2}}))
        merged = am.merge(doc1, doc2)
        conflicts = am.get_conflicts(merged, 'birds')
        assert len(conflicts) == 2
        winner_id = Frontend.get_object_id(merged['birds'])
        spy = PatchSpy()
        context = Context(merged, ACTOR, apply_patch=spy)
        context.set_map_key([{'key': 'birds', 'objectId': winner_id}],
                            'goldfinches', 3)
        assert context.ops == [
            {'obj': winner_id, 'action': 'set', 'key': 'goldfinches',
             'insert': False, 'value': 3, 'datatype': 'int', 'pred': []}]
        branches = spy.calls[0]['props']['birds']
        assert len(branches) == 2
        winner_key = next(k for k, v in branches.items()
                          if v['objectId'] == winner_id)
        assert list(branches[winner_key]['props']['goldfinches'].values()) \
            == [{'type': 'value', 'value': 3, 'datatype': 'int'}]
        loser = next(v for v in branches.values()
                     if v['objectId'] != winner_id)
        assert loser['props'] == {}

    def test_conflict_values_of_various_types(self):
        # Conflicting values of different types all surface in the patch
        # with their correct datatypes
        now = datetime.datetime.now(
            datetime.timezone.utc).replace(microsecond=0)
        docs = [
            am.change(am.init('aa11'), lambda d: d.update({'v': now})),
            am.change(am.init('bb22'), lambda d: d.update({'v': Counter()})),
            am.change(am.init('cc33'), lambda d: d.update({'v': 42})),
            am.change(am.init('dd44'), lambda d: d.update({'v': None})),
            am.change(am.init('ee55'), lambda d: d.update({'v': {'x': 1}})),
        ]
        merged = docs[0]
        for other in docs[1:]:
            merged = am.merge(merged, other)
        conflicts = am.get_conflicts(merged, 'v')
        assert len(conflicts) == 5
        # Update inside the nested-map branch (if it won) or assign through
        # the root; either way the context must describe all five branches
        spy = PatchSpy()
        context = Context(merged, ACTOR, apply_patch=spy)
        nested_id = Frontend.get_object_id(docs[4]['v'])
        context.set_map_key([{'key': 'v', 'objectId': nested_id}], 'x', 2)
        branches = spy.calls[0]['props']['v']
        assert len(branches) == 5
        values = {k: v for k, v in branches.items()}
        assert {'type': 'value', 'value': 42,
                'datatype': 'int'} in values.values()
        assert {'type': 'value', 'value': None} in values.values()
        assert any(v.get('datatype') == 'timestamp'
                   for v in values.values())
        assert any(v.get('datatype') == 'counter' for v in values.values())
        assert any(v.get('type') == 'map' for v in values.values())

    def test_delete_key_in_nested_object(self):
        doc, context, spy = make_doc(
            lambda d: d.update({'birds': {'goldfinches': 3}}))
        birds_id = Frontend.get_object_id(doc['birds'])
        context.delete_map_key([{'key': 'birds', 'objectId': birds_id}],
                               'goldfinches')
        assert context.ops == [
            {'obj': birds_id, 'action': 'del', 'key': 'goldfinches',
             'insert': False, 'pred': [f'2@{ACTOR}']}]
        branch = next(iter(spy.calls[0]['props']['birds'].values()))
        assert branch['props'] == {'goldfinches': {}}

    def test_multi_delete_consecutive_preds_after_overwrite(self):
        # An overwritten element (pred points at the overwrite op) followed
        # by an original element: preds 3@.. then 2@.. are NOT consecutive,
        # so two separate del ops are emitted; but overwriting in a way that
        # leaves preds consecutive compresses (ref context_test.js:344)
        doc = am.change(am.init(ACTOR),
                        lambda d: d.update({'birds': ['swallow', 'magpie']}))
        doc = am.change(doc, lambda d: d['birds'].__setitem__(1, 'sparrow'))
        spy = PatchSpy()
        context = Context(doc, ACTOR, apply_patch=spy)
        list_id = Frontend.get_object_id(doc['birds'])
        path = [{'key': 'birds', 'objectId': list_id}]
        context.splice(path, 0, 2, [])
        # elemIds 2@,3@ are consecutive and preds 2@,4@ are not: the run
        # must break on preds
        del_ops = [op for op in context.ops if op['action'] == 'del']
        assert [op.get('multiOp') for op in del_ops] == [None, None]
        subpatch = next(iter(spy.calls[-1]['props']['birds'].values()))
        assert subpatch['edits'] == [
            {'action': 'remove', 'index': 0, 'count': 2}]
