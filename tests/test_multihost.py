"""True multi-controller sync: two OS processes, each owning half the
mesh's shards and their fleet-resident documents, converge through the
all_to_all payload exchange (fleet/exchange.py sync_round_multihost).
This is the DCN leg of SURVEY §2.12's communication backend: within a
process the collective rides the device mesh; across processes it rides
jax.distributed's wire — the seam where a real deployment spans hosts."""

import json
import os
import socket
import subprocess
import sys


HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_pairwise_sync_converges():
    port = _free_port()
    env = dict(os.environ)
    env.pop('JAX_PLATFORMS', None)     # worker pins its own
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, 'multihost_worker.py'),
         str(p), '2', str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for p in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f'worker {p.args[-3]} failed:\n{out}'
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith('RESULT '):
                r = json.loads(line[len('RESULT '):])
                results[r['process']] = r
    assert set(results) == {0, 1}, results
    # every shard on every host converged to the same 4-key doc and the
    # same heads
    want = {f'k{s}': s for s in range(4)}
    all_heads = []
    for r in results.values():
        for read in r['reads']:
            assert read == want, read
        all_heads += r['heads']
    assert all(h == all_heads[0] for h in all_heads), all_heads
