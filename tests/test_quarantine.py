"""Fault-isolated batched apply: a batch of N docs with K poisoned inputs
must commit the N-K healthy docs in the SAME fused dispatch (no per-doc
fallback for the survivors), return K structured per-doc errors, and leave
the survivors byte-identical to a control universe that never saw the
poison. The same contract through the sync driver's receive path."""

import pytest

import automerge_tpu as A
from automerge_tpu import native, observability
from automerge_tpu.backend.sync import encode_sync_message
from automerge_tpu.columnar import encode_change
from automerge_tpu.errors import (DanglingPred, DocError, DuplicateOpId,
                                  MalformedChange, MalformedSyncMessage)
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import (DocFleet, init_docs,
                                         materialize_docs, quarantine_stats)
from automerge_tpu.fleet.sync_driver import receive_sync_messages_docs

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')


def _change(actor, key, value, seq=1, start_op=None, deps=(), pred=()):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op or seq, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': value, 'datatype': 'int', 'pred': list(pred)}]})


def _flip(buf, pos=10):
    out = bytearray(buf)
    out[pos] ^= 0xFF
    return bytes(out)


def _poisoned_workload(n):
    """n docs, one flat change each, with doc 2 corrupt (checksum-breaking
    bit flip) and doc 4 causally invalid (dangling pred)."""
    per_doc = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in range(n)]
    per_doc[2] = [_flip(per_doc[2][0])]
    per_doc[4] = [encode_change({
        'actor': 'ee' * 16, 'seq': 1, 'startOp': 5, 'time': 0,
        'message': '', 'deps': [],
        'ops': [{'action': 'set', 'obj': '_root', 'key': 'kx', 'value': 9,
                 'datatype': 'int', 'pred': ['3@' + 'dd' * 16]}]})]
    return per_doc


def test_poisoned_batch_quarantines_only_offenders():
    n = 6
    fleet = DocFleet(doc_capacity=8, key_capacity=16)
    handles = init_docs(n, fleet)
    per_doc = _poisoned_workload(n)
    stats_before = dict(quarantine_stats)

    new_handles, patches, errors = fleet_backend.apply_changes_docs(
        handles, per_doc, mirror=False, on_error='quarantine')

    assert isinstance(errors[2], DocError)
    assert isinstance(errors[2].error, MalformedChange)
    assert errors[2].stage == 'decode'
    assert isinstance(errors[4], DocError)
    assert isinstance(errors[4].error, DanglingPred)
    assert errors[4].error.doc_index == 4
    assert [i for i, e in enumerate(errors) if e is None] == [0, 1, 3, 5]
    assert quarantine_stats['quarantined_docs'] == \
        stats_before['quarantined_docs'] + 2
    assert quarantine_stats['rejected_changes'] == \
        stats_before['rejected_changes'] + 2

    mats = materialize_docs(new_handles)
    assert mats[2] == {} and mats[4] == {}        # offenders rolled back
    for i in (0, 1, 3, 5):
        assert mats[i] == {f'k{i}': i}            # survivors committed


def test_survivors_commit_in_same_fused_dispatch():
    """Dispatch-count regression: K rejected docs must add ZERO device
    dispatches over a clean batch of the N-K survivors — quarantine is a
    host-side retry, never a per-doc fallback for the healthy docs."""
    n = 6
    fleet = DocFleet(doc_capacity=8, key_capacity=16)
    handles = init_docs(n, fleet)
    before = observability.dispatch_counts([fleet])
    _, _, errors = fleet_backend.apply_changes_docs(
        handles, _poisoned_workload(n), mirror=False, on_error='quarantine')
    after = observability.dispatch_counts([fleet])
    assert sum(1 for e in errors if e) == 2

    control = DocFleet(doc_capacity=8, key_capacity=16)
    chandles = init_docs(4, control)
    clean = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in (0, 1, 3, 5)]
    cbefore = observability.dispatch_counts([control])
    fleet_backend.apply_changes_docs(chandles, clean, mirror=False)
    cafter = observability.dispatch_counts([control])

    assert after['fleet0'] - before['fleet0'] == \
        cafter['fleet0'] - cbefore['fleet0']
    assert after['total'] - after['fleet0'] - \
        (before['total'] - before['fleet0']) == \
        cafter['total'] - cafter['fleet0'] - \
        (cbefore['total'] - cbefore['fleet0'])


def test_survivors_byte_identical_to_control_universe():
    """No healthy doc's state may be perturbed by a quarantined neighbour:
    survivor save bytes must equal a universe that never saw the poison."""
    n = 6
    fleet = DocFleet(doc_capacity=8, key_capacity=16)
    handles = init_docs(n, fleet)
    new_handles, _, errors = fleet_backend.apply_changes_docs(
        handles, _poisoned_workload(n), mirror=False, on_error='quarantine')

    control = DocFleet(doc_capacity=8, key_capacity=16)
    chandles = init_docs(n, control)
    clean = _poisoned_workload(n)
    clean[2], clean[4] = [], []                   # the poison never existed
    chandles, _ = fleet_backend.apply_changes_docs(chandles, clean,
                                                   mirror=False)
    for i in (0, 1, 3, 5):
        assert bytes(fleet_backend.save(new_handles[i])) == \
            bytes(fleet_backend.save(chandles[i])), f'doc {i} perturbed'


def test_quarantine_verdicts_identical_across_pool_widths():
    """Thread-safety of the native error path: a poisoned chunk failing
    on a WORKER thread while sibling slices succeed must produce exactly
    the single-threaded outcome — same quarantined docs, same typed
    errors, same survivor states. Includes a count-bomb boolean column
    (the PR 3 -1/-2 malformed-vs-capacity split) so the refusal path, not
    just the checksum path, crosses threads."""
    def leb(v):
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                return bytes(out)

    n = 8
    def workload():
        per_doc = _poisoned_workload(n)
        bomb_src = per_doc[6][0]
        per_doc[6] = [bomb_src[:20] + leb((1 << 62) + 3) + bomb_src[20:]]
        return per_doc

    def run(width):
        prev = native.set_native_threads(width)
        try:
            fleet = DocFleet(doc_capacity=8, key_capacity=16)
            handles = init_docs(n, fleet)
            new_handles, _, errors = fleet_backend.apply_changes_docs(
                handles, workload(), mirror=False, on_error='quarantine')
            mats = materialize_docs(new_handles)
            kinds = [type(e.error).__name__ if e else None for e in errors]
            stages = [e.stage if e else None for e in errors]
            return kinds, stages, mats
        finally:
            native.set_native_threads(prev)

    ref = run(1)
    assert ref[0][2] == 'MalformedChange'
    assert ref[0][4] == 'DanglingPred'
    assert ref[0][6] == 'MalformedChange'      # count bomb: typed refusal
    for width in (2, 4, 8):
        got = run(width)
        assert got == ref, f'quarantine outcome diverged at width {width}'


def test_duplicate_opid_is_typed_and_scoped():
    fleet = DocFleet(doc_capacity=4, key_capacity=16)
    handles = init_docs(2, fleet)
    actor = 'cc' * 16
    good = _change('aa' * 16, 'g', 1)
    c1 = _change(actor, 'a', 1, seq=1)
    from automerge_tpu.columnar import decode_change
    meta = decode_change(c1)
    dup = encode_change({
        'actor': actor, 'seq': 2, 'startOp': 1, 'time': 0, 'message': '',
        'deps': [meta['hash']],
        'ops': [{'action': 'set', 'obj': '_root', 'key': 'b', 'value': 2,
                 'datatype': 'int', 'pred': []}]})
    with pytest.raises(DuplicateOpId) as ei:
        fleet_backend.apply_changes_docs(handles, [[good], [c1, dup]],
                                         mirror=False)
    assert ei.value.doc_index == 1
    # quarantine mode: doc 0 commits, doc 1 rejected with the same error
    fleet2 = DocFleet(doc_capacity=4, key_capacity=16)
    handles2 = init_docs(2, fleet2)
    new_handles, _, errors = fleet_backend.apply_changes_docs(
        handles2, [[good], [c1, dup]], mirror=False, on_error='quarantine')
    assert errors[0] is None
    assert isinstance(errors[1].error, DuplicateOpId)
    assert materialize_docs(new_handles) == [{'g': 1}, {}]


def test_exact_path_quarantine_isolates_per_doc():
    """mirror=True (exact path): per-doc isolation comes from the per-doc
    loop; a poisoned doc must not stop later docs from applying — and the
    device work still lands in the exact path's single flush dispatch
    (quarantine costs the exact path no batching, clean or poisoned)."""
    n = 4
    fleet = DocFleet(doc_capacity=4, key_capacity=16)
    handles = init_docs(n, fleet)
    per_doc = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in range(n)]
    per_doc[1] = [_flip(per_doc[1][0])]
    before = observability.dispatch_counts([fleet])
    new_handles, patches, errors = fleet_backend.apply_changes_docs(
        handles, per_doc, mirror=True, on_error='quarantine')
    after = observability.dispatch_counts([fleet])
    assert isinstance(errors[1].error, MalformedChange)
    assert [i for i, e in enumerate(errors) if e is None] == [0, 2, 3]
    assert patches[0] is not None and patches[2] is not None

    control = DocFleet(doc_capacity=4, key_capacity=16)
    chandles = init_docs(n, control)
    clean = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in range(n)]
    clean[1] = []
    cbefore = observability.dispatch_counts([control])
    fleet_backend.apply_changes_docs(chandles, clean, mirror=True)
    cafter = observability.dispatch_counts([control])
    assert after['fleet0'] - before['fleet0'] == \
        cafter['fleet0'] - cbefore['fleet0']

    mats = materialize_docs(new_handles)
    assert mats == [{'k0': 0}, {}, {'k2': 2}, {'k3': 3}]


def test_exact_path_quarantine_errors_always_typed():
    """The fallback path must normalize bare gate ValueErrors into typed
    AutomergeError subclasses — on host backends too."""
    from automerge_tpu import backend as host
    from automerge_tpu.errors import AutomergeError, InvalidChange
    handles = [host.init(), host.init()]
    actor = 'ab' * 16
    skipped_seq = _change(actor, 'k', 1, seq=3)   # seq 3 with empty clock
    good = _change('cd' * 16, 'g', 2)
    new_handles, _, errors = fleet_backend.apply_changes_docs(
        handles, [[skipped_seq], [good]], mirror=True,
        on_error='quarantine')
    assert errors[1] is None
    assert isinstance(errors[0].error, AutomergeError)
    assert isinstance(errors[0].error, InvalidChange)
    assert errors[0].error.doc_index == 0


def test_receive_sync_messages_quarantine():
    """An undecodable sync message (or one carrying a poisoned change)
    rejects only its own doc: the other peers' applies share the fused
    dispatch, the offender's sync state stays untouched."""
    from automerge_tpu import backend as host
    from automerge_tpu.backend import init_sync_state

    n = 4
    fleet = DocFleet(doc_capacity=4, key_capacity=16)
    handles = init_docs(n, fleet)
    states = [init_sync_state() for _ in range(n)]

    src = A.init('aa' * 16)
    src = A.change(src, {'time': 0}, lambda d: d.update({'x': 1}))
    src_b = A.Frontend.get_backend_state(src, 'q')
    good_change = bytes(A.get_all_changes(src)[0])
    msg = encode_sync_message(
        {'heads': host.get_heads(src_b), 'need': [], 'have': [],
         'changes': [good_change]})

    poisoned_change = _flip(good_change)
    poison_msg = encode_sync_message(
        {'heads': host.get_heads(src_b), 'need': [], 'have': [],
         'changes': [poisoned_change]})

    msgs = [msg, bytes([0x13]) + msg[1:], poison_msg, msg]
    new_backends, new_states, patches, errors = receive_sync_messages_docs(
        handles, states, msgs, mirror=False, on_error='quarantine')

    assert errors[0] is None and errors[3] is None
    assert isinstance(errors[1].error, MalformedSyncMessage)
    assert errors[1].stage == 'decode'
    assert isinstance(errors[2].error, MalformedChange)
    assert new_states[1] is states[1] and new_states[2] is states[2]
    assert new_states[0]['theirHeads'] == host.get_heads(src_b)
    mats = materialize_docs(new_backends)
    assert mats[0] == {'x': 1} and mats[3] == {'x': 1}
    assert mats[1] == {} and mats[2] == {}

    # raise mode names the offender
    with pytest.raises(MalformedSyncMessage) as ei:
        receive_sync_messages_docs(handles, states, msgs, mirror=False)
    assert ei.value.doc_index == 1


def test_quarantine_on_host_backends_too():
    """The quarantining apply works over plain host backends (no fleet in
    the batch): containment is a seam property, not a device feature."""
    from automerge_tpu import backend as host
    handles = [host.init() for _ in range(3)]
    per_doc = [[_change(f'{i:02x}' * 16, f'k{i}', i)] for i in range(3)]
    per_doc[1] = [_flip(per_doc[1][0])]
    new_handles, patches, errors = \
        fleet_backend.apply_changes_docs(handles, per_doc, mirror=True,
                                         on_error='quarantine')
    assert isinstance(errors[1].error, MalformedChange)
    assert host.get_heads(new_handles[0]) and host.get_heads(new_handles[2])
    assert not host.get_heads(new_handles[1])
