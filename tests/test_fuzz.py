"""Model-based fuzz tests: random multi-actor editing histories are applied
through the real frontend+backend stack and, in parallel, to the Micromerge
oracle (tests/micromerge.py, the executable spec); every causally-valid
delivery permutation must converge to the oracle's state (ported strategy of
reference test/fuzz_test.js:139-190)."""

import random

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from micromerge import Micromerge, expand_ops


class TestMicromergeFixtures:
    """Deterministic scenarios fixing the oracle's own semantics (ported from
    the inline asserts of test/fuzz_test.js:146-190)."""

    def test_convergence_both_orders(self):
        change1 = {'actor': '1234', 'seq': 1, 'deps': {}, 'startOp': 1, 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'title', 'insert': False,
             'value': 'Hello'},
            {'action': 'makeList', 'obj': '_root', 'key': 'tags',
             'insert': False},
            {'action': 'set', 'obj': '2@1234', 'key': '_head', 'insert': True,
             'value': 'foo'}]}
        change2 = {'actor': '1234', 'seq': 2, 'deps': {}, 'startOp': 4, 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'title', 'insert': False,
             'value': 'Hello 1'},
            {'action': 'set', 'obj': '2@1234', 'key': '3@1234', 'insert': True,
             'value': 'bar'},
            {'action': 'del', 'obj': '2@1234', 'key': '3@1234',
             'insert': False}]}
        change3 = {'actor': 'abcd', 'seq': 1, 'deps': {'1234': 1},
                   'startOp': 4, 'ops': [
            {'action': 'set', 'obj': '_root', 'key': 'title', 'insert': False,
             'value': 'Hello 2'},
            {'action': 'set', 'obj': '2@1234', 'key': '3@1234', 'insert': True,
             'value': 'baz'}]}
        doc1, doc2 = Micromerge(), Micromerge()
        for c in [change1, change2, change3]:
            doc1.apply_change(c)
        for c in [change1, change3, change2]:
            doc2.apply_change(c)
        assert doc1.root == {'title': 'Hello 2', 'tags': ['baz', 'bar']}
        assert doc2.root == {'title': 'Hello 2', 'tags': ['baz', 'bar']}

    def test_list_deletion_and_reinsertion(self):
        doc = Micromerge()
        doc.apply_change({'actor': '2345', 'seq': 1, 'deps': {}, 'startOp': 1,
                          'ops': [
            {'action': 'makeList', 'obj': '_root', 'key': 'todos',
             'insert': False},
            {'action': 'set', 'obj': '1@2345', 'key': '_head', 'insert': True,
             'value': 'Task 1'},
            {'action': 'set', 'obj': '1@2345', 'key': '2@2345', 'insert': True,
             'value': 'Task 2'}]})
        assert doc.root == {'todos': ['Task 1', 'Task 2']}
        doc.apply_change({'actor': '2345', 'seq': 2, 'deps': {}, 'startOp': 4,
                          'ops': [
            {'action': 'del', 'obj': '1@2345', 'key': '2@2345',
             'insert': False},
            {'action': 'set', 'obj': '1@2345', 'key': '3@2345', 'insert': True,
             'value': 'Task 3'}]})
        assert doc.root == {'todos': ['Task 2', 'Task 3']}
        doc.apply_change({'actor': '2345', 'seq': 3, 'deps': {}, 'startOp': 6,
                          'ops': [
            {'action': 'del', 'obj': '1@2345', 'key': '3@2345',
             'insert': False},
            {'action': 'set', 'obj': '1@2345', 'key': '5@2345',
             'insert': False, 'value': 'Task 3b'},
            {'action': 'set', 'obj': '1@2345', 'key': '5@2345', 'insert': True,
             'value': 'Task 4'}]})
        assert doc.root == {'todos': ['Task 3b', 'Task 4']}

    def test_seq_and_dep_errors(self):
        doc = Micromerge()
        with pytest.raises(ValueError, match='Expected sequence number 1'):
            doc.apply_change({'actor': 'x', 'seq': 2, 'deps': {},
                              'startOp': 1, 'ops': []})
        with pytest.raises(ValueError, match='Missing dependency'):
            doc.apply_change({'actor': 'x', 'seq': 1, 'deps': {'y': 1},
                              'startOp': 1, 'ops': []})


def random_mutation(rnd, doc, deletes=True):
    """One random mutation through the real proxy API; stays within the
    oracle's supported types (maps, lists, primitives, LWW). With
    `deletes=False` the history is delete-free: the Micromerge oracle
    resolves concurrent delete-vs-set by pure LWW opId order (its documented
    simplification, ref test/fuzz_test.js:6-7), whereas the real CRDT only
    deletes the set ops named in `pred`, so concurrent sets survive — the two
    models agree exactly only on delete-free histories."""
    keys = 'abcdefg'

    def mutate(d):
        for _ in range(rnd.randrange(1, 4)):
            # Collect current list paths
            lists = [k for k in d.keys() if isinstance(
                d[k], am.frontend.proxies.ListProxy)]
            choice = rnd.random()
            if choice < 0.35 or not lists:
                k = rnd.choice(keys)
                if rnd.random() < 0.2:
                    d[k] = [rnd.randrange(100)]
                elif deletes and rnd.random() < 0.15 and k in d:
                    del d[k]
                else:
                    d[k] = rnd.randrange(1000)
            else:
                lst = d[rnd.choice(lists)]
                r = rnd.random()
                if r < 0.5 or len(lst) == 0:
                    lst.insert(rnd.randrange(len(lst) + 1), rnd.randrange(100))
                elif r < 0.75 or not deletes:
                    lst[rnd.randrange(len(lst))] = rnd.randrange(100)
                else:
                    del lst[rnd.randrange(len(lst))]
    return mutate


def to_plain(doc):
    return doc.to_py()


@pytest.mark.parametrize('seed', [1, 2, 3, 4, 5])
def test_fuzz_backend_matches_oracle(seed):
    """Random 3-actor history: every actor's changes go through the real
    stack; the same change requests (with vector-clock deps) drive the
    oracle; random causally-valid delivery orders must converge to the
    oracle state on every replica."""
    rnd = random.Random(seed)
    actors = ['aa01', 'bb02', 'cc03']
    docs = {a: am.init(a) for a in actors}
    history = []   # (actor, seq, vc_deps, change_request, binary)

    for round_ in range(12):
        actor = rnd.choice(actors)
        doc = docs[actor]
        vc = dict(doc._state['clock'])
        new_doc, req = Frontend.change(doc,
                                       random_mutation(rnd, doc, deletes=False))
        if req is None:
            continue
        docs[actor] = new_doc
        binary = Frontend.get_last_local_change(new_doc)
        history.append((actor, req['seq'], vc, req, binary))
        # Randomly propagate changes between actors
        if rnd.random() < 0.6:
            src, dst = rnd.sample(actors, 2)
            if docs[src]._state['clock'] != docs[dst]._state['clock']:
                changes = am.get_all_changes(docs[src])
                docs[dst], _ = am.apply_changes(docs[dst], changes)

    # Full sync of the real docs
    all_changes = []
    for a in actors:
        all_changes.extend(am.get_all_changes(docs[a]))
    final = {}
    for a in actors:
        merged, _ = am.apply_changes(docs[a], all_changes)
        final[a] = to_plain(merged)
    assert final[actors[0]] == final[actors[1]] == final[actors[2]]

    # Oracle: random causally-valid linear extensions
    for trial in range(3):
        oracle = Micromerge()
        pending = list(history)
        rnd.shuffle(pending)
        applied = {a: 0 for a in actors}
        while pending:
            progress = False
            for item in list(pending):
                actor, seq, vc, req, _bin = item
                if applied[actor] == seq - 1 and \
                        all(applied[a] >= s for a, s in vc.items()):
                    oracle.apply_change(expand_ops(
                        {'actor': actor, 'seq': seq, 'deps': vc,
                         'startOp': req['startOp'], 'ops': req['ops']}))
                    applied[actor] = seq
                    pending.remove(item)
                    progress = True
            assert progress, 'deadlock in causal order'
        assert oracle.root == final[actors[0]], \
            f'oracle diverged from backend (seed={seed}, trial={trial})'


@pytest.mark.parametrize('seed', [11, 12, 13])
def test_fuzz_delivery_order_independence(seed):
    """The real backend converges to the same state no matter the order
    binary changes are delivered in (causally-premature ones queue)."""
    rnd = random.Random(seed)
    actors = ['aa01', 'bb02']
    docs = {a: am.init(a) for a in actors}
    binaries = []
    for _ in range(10):
        actor = rnd.choice(actors)
        new_doc, req = Frontend.change(docs[actor],
                                       random_mutation(rnd, docs[actor]))
        if req is None:
            continue
        docs[actor] = new_doc
        binaries.append(Frontend.get_last_local_change(new_doc))
        if rnd.random() < 0.5:
            src, dst = rnd.sample(actors, 2)
            docs[dst], _ = am.apply_changes(docs[dst],
                                            am.get_all_changes(docs[src]))

    results = []
    for trial in range(4):
        order = list(binaries)
        rnd.shuffle(order)
        fresh, _ = am.apply_changes(am.init('dd04'), order)
        results.append(to_plain(fresh))
    assert all(r == results[0] for r in results)


@pytest.mark.parametrize('seed', [11, 12, 13])
def test_fuzz_fleet_backend_matches_host(seed):
    """The wasm.js differential pattern under fuzz: the same random 3-actor
    history drives the host backend and the device-routed fleet backend
    (installed via set_default_backend); every replica's converged state and
    serialized document must be identical across backends."""
    from automerge_tpu import backend as host_backend
    from automerge_tpu.fleet.backend import DocFleet, FleetBackend

    def run(seed):
        rnd = random.Random(seed)
        actors = ['aa01', 'bb02', 'cc03']
        docs = {a: am.init(a) for a in actors}
        for round_ in range(12):
            actor = rnd.choice(actors)
            new_doc, req = Frontend.change(
                docs[actor], {'time': 0},
                random_mutation(rnd, docs[actor], deletes=False))
            if req is not None:
                docs[actor] = new_doc
            if rnd.random() < 0.6:
                src, dst = rnd.sample(actors, 2)
                if docs[src]._state['clock'] != docs[dst]._state['clock']:
                    changes = am.get_all_changes(docs[src])
                    docs[dst], _ = am.apply_changes(docs[dst], changes)
        all_changes = []
        for a in actors:
            all_changes.extend(am.get_all_changes(docs[a]))
        out = {}
        for a in actors:
            merged, _ = am.apply_changes(docs[a], all_changes)
            out[a] = (to_plain(merged), bytes(am.save(merged)))
        return out

    host_out = run(seed)
    am.set_default_backend(FleetBackend(DocFleet(doc_capacity=4,
                                                 key_capacity=4)))
    try:
        fleet_out = run(seed)
    finally:
        am.set_default_backend(host_backend)
    assert host_out == fleet_out
