"""Observable conformance tests (ported semantics of reference
test/observable_test.js: per-object subscriptions, before/after states,
remote changes, tables, text, multiple observers)."""

import pytest

import automerge_tpu as am
from automerge_tpu.frontend import Observable, Table, Text


class TestObservable:
    def test_callback_on_root(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        actor = am.get_actor_id(doc)
        calls = []
        observable.observe(doc, lambda diff, before, after, local, changes:
                           calls.append((diff, before, after, local)))
        doc2 = am.change(doc, lambda d: d.update({'bird': 'Goldfinch'}))
        assert len(calls) == 1
        diff, before, after, local = calls[0]
        assert diff['props'] == {'bird': {f'1@{actor}': {
            'type': 'value', 'value': 'Goldfinch'}}}
        assert dict(before) == {}
        assert dict(after) == {'bird': 'Goldfinch'}
        assert local is True

    def test_callback_on_text(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        doc = am.change(doc, lambda d: d.update({'text': Text('hello')}))
        calls = []
        observable.observe(doc['text'],
                           lambda diff, before, after, local, changes:
                           calls.append((diff, before, after)))
        doc2 = am.change(doc, lambda d: d['text'].delete_at(0, 5))
        assert len(calls) == 1
        diff, before, after = calls[0]
        assert diff['edits'] == [{'action': 'remove', 'index': 0, 'count': 5}]
        assert str(before) == 'hello'
        assert str(after) == ''

    def test_callback_on_remote_changes(self):
        observable = Observable()
        local = am.init({'observable': observable})
        local = am.change(local, lambda d: d.update({'bird': 'Goldfinch'}))
        calls = []
        observable.observe(local, lambda diff, before, after, local_, changes:
                           calls.append((after, local_)))
        remote, _ = am.apply_changes(am.init(), am.get_all_changes(local))
        remote = am.change(remote, lambda d: d.update({'fish': 'Herring'}))
        local2, _patch = am.apply_changes(local,
                                          am.get_all_changes(remote)[1:])
        assert len(calls) == 1
        after, was_local = calls[0]
        assert dict(after) == {'bird': 'Goldfinch', 'fish': 'Herring'}
        assert was_local is False

    def test_observe_nested_in_list(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        doc = am.change(doc, lambda d: d.update(
            {'birds': [{'species': 'Goldfinch', 'count': 3}]}))
        calls = []
        observable.observe(doc['birds'][0],
                           lambda diff, before, after, local, changes:
                           calls.append((before, after)))
        doc2 = am.change(doc, lambda d: d['birds'][0].update({'count': 4}))
        assert len(calls) == 1
        before, after = calls[0]
        assert before == {'species': 'Goldfinch', 'count': 3}
        assert after == {'species': 'Goldfinch', 'count': 4}

    def test_before_after_with_shifted_list_indexes(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        doc = am.change(doc, lambda d: d.update(
            {'birds': [{'species': 'Goldfinch', 'count': 3}]}))
        calls = []
        observable.observe(doc['birds'][0],
                           lambda diff, before, after, local, changes:
                           calls.append((before, after)))

        def edit(d):
            d['birds'].insert_at(0, {'species': 'Chaffinch', 'count': 1})
            d['birds'][1]['count'] = 4
        doc2 = am.change(doc, edit)
        assert len(calls) == 1
        before, after = calls[0]
        assert before == {'species': 'Goldfinch', 'count': 3}
        assert after == {'species': 'Goldfinch', 'count': 4}

    def test_observe_table_rows(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        holder = {}

        def setup(d):
            d['books'] = Table()
            holder['id'] = d['books'].add({'title': 'old'})
        doc = am.change(doc, setup)
        calls = []
        observable.observe(doc['books'].by_id(holder['id']),
                           lambda diff, before, after, local, changes:
                           calls.append((before, after)))
        doc2 = am.change(
            doc, lambda d: d['books'].by_id(holder['id']).update(
                {'title': 'new'}))
        assert len(calls) == 1
        before, after = calls[0]
        assert before['title'] == 'old'
        assert after['title'] == 'new'

    def test_observe_nested_object_inside_text(self):
        observable = Observable()
        doc = am.init({'observable': observable})

        def setup(d):
            d['text'] = Text('ab')
            d['text'].insert_at(1, {'attribute': 'bold'})
        doc = am.change(doc, setup)
        calls = []
        observable.observe(doc['text'][1],
                           lambda diff, before, after, local, changes:
                           calls.append((before, after)))
        doc2 = am.change(doc,
                         lambda d: d['text'][1].update({'attribute': 'italic'}))
        assert len(calls) == 1
        before, after = calls[0]
        assert before == {'attribute': 'bold'}
        assert after == {'attribute': 'italic'}

    def test_rejects_non_document_objects(self):
        observable = Observable()
        with pytest.raises(TypeError):
            observable.observe({'not': 'a doc object'}, lambda *a: None)

    def test_multiple_observers(self):
        observable = Observable()
        doc = am.init({'observable': observable})
        calls_a, calls_b = [], []
        observable.observe(doc, lambda *a: calls_a.append(a))
        observable.observe(doc, lambda *a: calls_b.append(a))
        am.change(doc, lambda d: d.update({'x': 1}))
        assert len(calls_a) == 1 and len(calls_b) == 1
