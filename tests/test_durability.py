"""Crash-safe durability: CRC journal framing, fleet checkpoints,
torn-write/bit-rot recovery, compaction, and the seam hooks
(fleet/durability.py + the backend.py mutation-seam journaling).

The full crash-injection matrix lives in tools/crashtest.py (run
standalone or via the slow-marked test below); tier-1 keeps a seeded
smoke dose so the fast suite exercises recovery on every run."""

import glob
import os
import random
import sys

import pytest

import automerge_tpu as A
from automerge_tpu import native
from automerge_tpu.columnar import encode_change
from automerge_tpu.errors import (AutomergeError, MalformedJournal,
                                  MalformedSnapshot, TornTail)
from automerge_tpu.fleet import backend as fb
from automerge_tpu.fleet import durability as D
from automerge_tpu.fleet.durability import (ChangeJournal, DurableFleet,
                                            encode_frame,
                                            parse_journal_bytes,
                                            parse_manifest_bytes,
                                            parse_snapshot_bytes)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))


def _change(actor, seq, deps, value, start=1, key='k'):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': value, 'datatype': 'int', 'pred': []}]})


def _grow(mgr, handles, round_no, n=None):
    """One linear change per doc; returns new handles."""
    n = n if n is not None else len(handles)
    per_doc = []
    for i, h in enumerate(handles[:n]):
        per_doc.append([_change(f'{i:02x}' * 16, round_no,
                                fb.get_heads(h), round_no * 100 + i,
                                start=round_no)])
    per_doc += [[] for _ in handles[n:]]
    out, _patches, errors = mgr.apply_changes(handles, per_doc)
    assert not any(errors)
    return out


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def test_frame_roundtrip():
    rng = random.Random(0)
    frames = [(D.KIND_CHANGE, rng.randrange(1 << 31),
               bytes(rng.randrange(256) for _ in range(rng.randrange(200))))
              for _ in range(20)]
    blob = b''.join(encode_frame(k, d, p) for k, d, p in frames)
    records, info = parse_journal_bytes(blob)
    assert records == frames
    assert info['torn_tail_bytes'] == 0 and not info['rotted']
    assert info['valid_end'] == len(blob)


def test_torn_tail_truncates_at_first_bad_frame():
    blob = b''.join(encode_frame(D.KIND_CHANGE, i, b'x' * 40)
                    for i in range(4))
    cut = blob[:len(blob) - 11]            # torn mid final frame
    records, info = parse_journal_bytes(cut)
    assert [d for _k, d, _p in records] == [0, 1, 2]
    assert info['torn_tail_bytes'] > 0
    assert info['valid_end'] == len(cut) - info['torn_tail_bytes']
    with pytest.raises(TornTail):
        parse_journal_bytes(cut, strict=True)


def test_mid_stream_rot_attributes_one_doc_and_resyncs():
    blob = b''.join(encode_frame(D.KIND_CHANGE, i, bytes([i]) * 30)
                    for i in range(5))
    # payload rot in doc 2's frame: header stays valid -> attributed
    frame_len = len(encode_frame(D.KIND_CHANGE, 0, b'\0' * 30))
    rot = bytearray(blob)
    rot[2 * frame_len + 20] ^= 0x40
    records, info = parse_journal_bytes(bytes(rot))
    assert [d for _k, d, _p in records] == [0, 1, 3, 4]
    assert [(d, i) for d, _at, i in info['rotted']] == [(2, 2)]
    with pytest.raises(MalformedJournal):
        parse_journal_bytes(bytes(rot), strict=True)
    # header rot: attribution lost (None) but the stream resyncs
    rot2 = bytearray(blob)
    rot2[2 * frame_len + 3] ^= 0x01        # inside doc_id field
    records2, info2 = parse_journal_bytes(bytes(rot2))
    assert [d for _k, d, _p in records2] == [0, 1, 3, 4]
    assert [d for d, _at, _i in info2['rotted']] == [None]


def test_snapshot_and_manifest_structural_checks():
    body = encode_frame(D.KIND_DOC, 0, b'doc0') + \
        encode_frame(D.KIND_END, 0, D._U32.pack(1))
    docs, queued, errors, meta = parse_snapshot_bytes(D.SNAP_MAGIC + body)
    assert docs == {0: b'doc0'} and not queued and not errors
    assert meta.get('base', True)    # no SMETA frame reads as a base
    with pytest.raises(MalformedSnapshot):
        parse_snapshot_bytes(b'NOPE' + body)
    with pytest.raises(MalformedSnapshot):           # missing END
        parse_snapshot_bytes(D.SNAP_MAGIC +
                             encode_frame(D.KIND_DOC, 0, b'doc0'))
    with pytest.raises(MalformedSnapshot):
        parse_manifest_bytes(b'garbage')


# ---------------------------------------------------------------------------
# journal group commit / accounting
# ---------------------------------------------------------------------------


def test_group_commit_fsync_batching(tmp_path):
    j = ChangeJournal(str(tmp_path / 'j.log'), fsync_bytes=1 << 20)
    j.append(0, b'a' * 100)
    assert j.buffered_bytes > 0 and j.written_bytes == 0
    j.commit()
    # under the byte threshold: written but NOT yet fsynced
    assert j.buffered_bytes == 0
    assert j.pending_fsync_bytes > 0
    before = D.durability_stats()['journal_fsyncs']
    j.sync()
    assert j.pending_fsync_bytes == 0
    assert D.durability_stats()['journal_fsyncs'] == before + 1
    j.close()


def test_memory_stats_reports_journal_accounting(tmp_path):
    mgr = DurableFleet(str(tmp_path / 'dur'), fsync_bytes=1 << 20)
    handles = mgr.init_docs(2)
    _grow(mgr, handles, 1)
    stats = mgr.fleet.memory_stats()
    assert 'journal' in stats
    assert set(stats['journal']) >= {'buffered_bytes',
                                     'pending_fsync_bytes',
                                     'durable_bytes', 'records'}
    assert stats['journal']['records'] >= 2
    # the loss window is visible while fsyncs batch
    assert stats['journal']['pending_fsync_bytes'] > 0
    mgr.journal.sync()
    assert mgr.fleet.memory_stats()['journal']['pending_fsync_bytes'] == 0
    mgr.close()


# ---------------------------------------------------------------------------
# checkpoint + recovery
# ---------------------------------------------------------------------------


def test_checkpoint_recover_byte_identical(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(3)
    handles = _grow(mgr, handles, 1)
    mgr.checkpoint()
    handles = _grow(mgr, handles, 2)       # journal suffix past snapshot
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    mgr2, rec, report = DurableFleet.recover(path)
    assert sorted(rec) == [0, 1, 2]
    assert [bytes(fb.save(rec[i])) for i in range(3)] == pre
    assert report.snapshot_docs == 3 and report.replayed_records == 3
    assert report.ok
    # recovered docs keep accepting journaled changes
    h3 = _grow(mgr2, [rec[i] for i in range(3)], 3)
    assert all(len(fb.get_heads(h)) == 1 for h in h3)
    mgr2.close()


def test_recover_refuses_fresh_dir_reuse(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    mgr.close()
    with pytest.raises(ValueError):
        DurableFleet(path)


def test_sync_seam_journals_received_changes(tmp_path):
    """Changes arriving through the sync protocol (receive path -> the
    apply seam) must be crash-durable without any explicit journaling."""
    peer = A.change(A.init('aa' * 16), {'time': 0},
                    lambda d: d.update({'x': 1, 'y': 'hello'}))
    peer_backend = A.Frontend.get_backend_state(peer, 'sync')
    mgr = DurableFleet(str(tmp_path / 'dur'))
    handle = mgr.init_docs(1)[0]
    s1, s2 = A.init_sync_state(), A.init_sync_state()
    from automerge_tpu import backend as host_backend
    for _ in range(8):
        s2, msg = host_backend.generate_sync_message(peer_backend, s2)
        if msg is not None:
            handle, s1, _ = fb.receive_sync_message(handle, s1, msg)
        s1, msg2 = fb.generate_sync_message(handle, s1)
        if msg2 is not None:
            peer_backend, s2, _ = host_backend.receive_sync_message(
                peer_backend, s2, msg2)
        if msg is None and msg2 is None:
            break
    pre = bytes(fb.save(handle))
    mgr.close()
    _mgr2, rec, report = DurableFleet.recover(str(tmp_path / 'dur'))
    assert bytes(fb.save(rec[0])) == pre
    assert report.replayed_records >= 1
    _mgr2.close()


def test_queued_changes_survive_checkpoint(tmp_path):
    """A causally held-back change (missing dep) is journaled, rides the
    snapshot's QUEUED frames across a checkpoint, and drains after
    recovery once the dep arrives."""
    actor = 'aa' * 16
    c1 = _change(actor, 1, [], 1, start=1)
    import hashlib
    from automerge_tpu.columnar import decode_change_meta
    h1 = decode_change_meta(c1, True)['hash']
    c2 = _change(actor, 2, [h1], 2, start=2)
    mgr = DurableFleet(str(tmp_path / 'dur'))
    handle = mgr.init_docs(1)[0]
    out, _p, errs = mgr.apply_changes([handle], [[c2]])   # dep missing
    assert not any(errs)
    handle = out[0]
    assert handle['state'].queue
    mgr.checkpoint()                       # QUEUED frame in the snapshot
    mgr.close()
    mgr2, rec, _report = DurableFleet.recover(str(tmp_path / 'dur'))
    handle = rec[0]
    assert handle['state'].queue           # still held back
    out, _p, errs = mgr2.apply_changes([handle], [[c1]])  # dep arrives
    assert not any(errs)
    assert len(fb.get_heads(out[0])) == 1  # c1+c2 both applied
    mgr2.close()


def test_checkpoint_preserves_successor_journal_until_snapshot_durable(
        tmp_path):
    """A stale successor journal (the generation a fallback recovery
    just consumed) holds real fsynced records; checkpoint() must not
    destroy it before the snapshot superseding those records is durable
    on disk — dying mid-snapshot would otherwise lose them."""
    from automerge_tpu.fleet.durability import encode_frame
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(1)
    _grow(mgr, handles, 1)
    stale = os.path.join(path, 'journal-00000001.log')
    blob = encode_frame(D.KIND_INIT, 7, b'')
    with open(stale, 'wb') as f:
        f.write(blob)

    class _Die(Exception):
        pass

    orig = DurableFleet._fault
    DurableFleet._fault = lambda self, point: (_ for _ in ()).throw(
        _Die()) if point == 'snapshot-temp-written' else None
    try:
        with pytest.raises(_Die):
            mgr.checkpoint()
    finally:
        DurableFleet._fault = orig
    assert open(stale, 'rb').read() == blob, \
        'successor journal destroyed before the snapshot was durable'
    mgr.checkpoint()                 # completes: now safely superseded
    assert open(stale, 'rb').read() != blob
    mgr.close()


def test_clone_queue_survives_crash(tmp_path):
    """A clone of a doc with causally-held-back queue entries must carry
    its own journaled copies — the original's queue records live under
    the original's durable id."""
    from automerge_tpu.columnar import decode_change_meta
    actor = 'aa' * 16
    c1 = _change(actor, 1, [], 1, start=1)
    h1 = decode_change_meta(c1, True)['hash']
    c2 = _change(actor, 2, [h1], 2, start=2)
    mgr = DurableFleet(str(tmp_path / 'dur'))
    handle = mgr.init_docs(1)[0]
    out, _p, errs = mgr.apply_changes([handle], [[c2]])   # queues
    assert not any(errs) and out[0]['state'].queue
    clone = fb.clone(out[0])
    clone_id = clone['state']._dur_id
    mgr.close()
    mgr2, rec, _report = DurableFleet.recover(str(tmp_path / 'dur'))
    assert rec[clone_id]['state'].queue, 'clone queue lost across crash'
    out, _p, errs = mgr2.apply_changes([rec[clone_id]], [[c1]])
    assert not any(errs)
    assert len(fb.get_heads(out[0])) == 1     # dep arrived, queue drained
    mgr2.close()


def test_clone_is_journaled(tmp_path):
    mgr = DurableFleet(str(tmp_path / 'dur'))
    handles = mgr.init_docs(1)
    handles = _grow(mgr, handles, 1)
    clone = fb.clone(handles[0])
    pre = bytes(fb.save(clone))
    mgr.close()
    _mgr2, rec, _report = DurableFleet.recover(str(tmp_path / 'dur'))
    assert len(rec) == 2
    saves = sorted(bytes(fb.save(h)) for h in rec.values())
    assert pre in saves
    _mgr2.close()


# ---------------------------------------------------------------------------
# satellite: freed / never-used slots across checkpoint + recover
# ---------------------------------------------------------------------------


def test_freed_and_never_used_slots_roundtrip(tmp_path):
    """alloc -> free -> checkpoint -> recover: freed docs stay freed,
    never-edited docs survive as empty, slot reuse does not alias."""
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(4)             # doc 3 never edited
    handles = _grow(mgr, handles, 1, n=3)
    freed_slot = handles[1]['state']._impl.slot
    fb.free_docs([handles[1]])             # journaled FREE
    # slot reuse: the recycled fleet slot must not alias doc 1's id
    reused = mgr.init_docs(1)[0]
    assert reused['state']._impl.slot == freed_slot
    reused = _grow(mgr, [reused], 1)[0]
    mgr.checkpoint()
    pre = {0: bytes(fb.save(handles[0])), 2: bytes(fb.save(handles[2])),
           4: bytes(fb.save(reused))}
    mgr.close()

    mgr2, rec, report = DurableFleet.recover(path)
    assert sorted(rec) == [0, 2, 3, 4]     # doc 1 freed, 3 empty, 4 reused
    assert 1 in report.freed_docs or 1 not in rec
    for did, save in pre.items():
        assert bytes(fb.save(rec[did])) == save, f'doc {did}'
    assert fb.get_heads(rec[3]) == []      # never-used doc: empty, live
    grown = _grow(mgr2, [rec[3]], 1)
    assert len(fb.get_heads(grown[0])) == 1
    mgr2.close()


def test_rebuild_docs_keeps_durability(tmp_path):
    """backend.rebuild_docs (donation-failure recovery) must carry the
    journal + durable ids to the rebuilt fleet: post-rebuild changes
    journal, checkpoints snapshot the REBUILT states, and ids never
    recycle — the stale pre-rebuild states must not linger."""
    from automerge_tpu.fleet.backend import DocFleet
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(2)
    handles = _grow(mgr, handles, 1)
    old_fleet = mgr.fleet
    fresh = DocFleet(doc_capacity=4, key_capacity=64)
    rebuilt = fb.rebuild_docs(handles, fresh)
    mgr.adopt_fleet(fresh)
    assert old_fleet.journal is None and fresh.journal is mgr.journal
    assert [h['state']._dur_id for h in rebuilt] == [0, 1]
    rebuilt = _grow(mgr, rebuilt, 2)       # post-rebuild change journals
    mgr.checkpoint()                       # snapshots the REBUILT states
    pre = [bytes(fb.save(h)) for h in rebuilt]
    mgr.close()
    _mgr2, rec, report = DurableFleet.recover(path)
    assert [bytes(fb.save(rec[i])) for i in range(2)] == pre
    _mgr2.close()


def test_recovery_never_recycles_freed_doc_ids(tmp_path):
    """Durable ids are monotonic forever: a doc freed after the last
    checkpoint (id known only from journal records) must still fence
    the id allocator across recovery."""
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(3)
    handles = _grow(mgr, handles, 1)
    fb.free_docs([handles[2]])             # top id, post-checkpoint FREE
    mgr.close()
    mgr2, rec, _report = DurableFleet.recover(path)
    fresh = mgr2.init_docs(1)[0]
    assert fresh['state']._dur_id >= 3, \
        f"freed doc's id recycled: {fresh['state']._dur_id}"
    mgr2.close()


def test_free_before_any_checkpoint(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(2)
    handles = _grow(mgr, handles, 1)
    fb.free_docs([handles[0]])
    mgr.close()
    _mgr2, rec, report = DurableFleet.recover(path)
    assert sorted(rec) == [1]
    assert report.freed_docs == [0]
    _mgr2.close()


# ---------------------------------------------------------------------------
# containment: rot quarantines one doc, torn tails truncate
# ---------------------------------------------------------------------------


def _journal_path(path):
    names = sorted(glob.glob(os.path.join(path, 'journal-*.log')))
    assert names
    return names[-1]


def test_rotted_record_quarantines_exactly_one_doc(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(3)
    handles = _grow(mgr, handles, 1)
    handles = _grow(mgr, handles, 2)
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    jp = _journal_path(path)
    data = bytearray(open(jp, 'rb').read())
    # rot doc 1's round-2 payload: walk frames to find it
    off, target = 0, None
    seen = {}
    while off < len(data):
        kind, did, _p, end, status = D._frame_at(bytes(data), off)
        assert status == 'ok'
        if kind == D.KIND_CHANGE:
            seen[did] = seen.get(did, 0) + 1
            if did == 1 and seen[did] == 2:
                target = (off, end)
        off = end
    data[target[0] + 20] ^= 0x08
    open(jp, 'wb').write(bytes(data))

    before = D.durability_stats()
    _mgr2, rec, report = DurableFleet.recover(path)
    after = D.durability_stats()
    assert sorted(report.quarantined) == [1]
    assert isinstance(report.quarantined[1].error, AutomergeError)
    assert after['rotted_records'] == before['rotted_records'] + 1
    # docs 0 and 2: byte-identical; doc 1: exactly its pre-rot prefix
    assert bytes(fb.save(rec[0])) == pre[0]
    assert bytes(fb.save(rec[2])) == pre[2]
    assert len(fb.get_heads(rec[1])) == 1      # round-1 survived
    assert bytes(fb.save(rec[1])) != pre[1]
    _mgr2.close()


def test_torn_tail_counter_and_truncation(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(2)
    handles = _grow(mgr, handles, 1)
    mgr.close()
    jp = _journal_path(path)
    data = open(jp, 'rb').read()
    open(jp, 'wb').write(data[:-5])
    before = D.durability_stats()
    _mgr2, rec, report = DurableFleet.recover(path)
    assert report.torn_tail_bytes > 0
    assert D.durability_stats()['journal_truncations'] == \
        before['journal_truncations'] + 1
    # doc 1's final change was torn off; doc 0 intact
    assert len(fb.get_heads(rec[0])) == 1
    assert fb.get_heads(rec[1]) == []
    _mgr2.close()


def test_newest_snapshot_structural_rot_falls_back_a_generation(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(2)
    handles = _grow(mgr, handles, 1)
    mgr.checkpoint()
    handles = _grow(mgr, handles, 2)
    mgr.checkpoint()
    handles = _grow(mgr, handles, 3)
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    snaps = sorted(glob.glob(os.path.join(path, 'snapshot-*.snap')))
    assert len(snaps) == 2                   # retain=2 generations
    blob = bytearray(open(snaps[-1], 'rb').read())
    blob[0] ^= 0xFF                          # kill the newest magic
    open(snaps[-1], 'wb').write(bytes(blob))
    _mgr2, rec, report = DurableFleet.recover(path)
    assert report.used_fallback_manifest
    assert [bytes(fb.save(rec[i])) for i in range(2)] == pre
    _mgr2.close()


# ---------------------------------------------------------------------------
# compaction
# ---------------------------------------------------------------------------


def test_cost_triggered_compaction(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path, compact_bytes=400)
    handles = mgr.init_docs(2)
    before = D.durability_stats()['compactions']
    for r in range(1, 5):
        handles = _grow(mgr, handles, r)
    assert D.durability_stats()['compactions'] > before
    assert mgr.replay_debt()['bytes'] < 400 + 200   # debt reset by rotation
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    _mgr2, rec, _report = DurableFleet.recover(path)
    assert [bytes(fb.save(rec[i])) for i in range(2)] == pre
    _mgr2.close()


def test_incremental_compaction_work_tracks_churn(tmp_path):
    """The O(K) pin: after touching K of N docs, a forced compaction
    writes EXACTLY K doc frames (counter-based — `segment_docs` grows by
    K, not N), and recovery through the segment chain is byte-identical
    to the live fleet."""
    n, k = 40, 3
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path, compact_bytes=1 << 40,
                       compact_records=1 << 40)
    handles = mgr.init_docs(n)
    handles = _grow(mgr, handles, 1)
    mgr.checkpoint()                       # base snapshot, chain reset
    # touch exactly K docs
    per_doc = [[] for _ in range(n)]
    for i in range(k):
        per_doc[i] = [_change(f'{i:02x}' * 16, 2, fb.get_heads(handles[i]),
                              999 + i, start=2)]
    handles, _p, errs = mgr.apply_changes(handles, per_doc)
    assert not any(errs)
    before = D.durability_stats()
    assert mgr.maybe_compact(force=True)
    after = D.durability_stats()
    assert after['segments'] == before['segments'] + 1
    assert after['segment_docs'] == before['segment_docs'] + k
    assert len(mgr.chain) == 2             # base + one segment
    # idle compaction is a no-op (zero churn -> zero work), and a forced
    # maybe_compact reports it honestly (no phantom 'compactions' count)
    assert mgr.compact() is False
    c0 = D.durability_stats()['compactions']
    assert mgr.maybe_compact(force=True) is False
    assert D.durability_stats()['compactions'] == c0
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    mgr2, rec, report = DurableFleet.recover(path)
    assert report.ok
    assert [bytes(fb.save(rec[i])) for i in range(n)] == pre
    mgr2.close()


@pytest.mark.parametrize('exact_device,mirror', [(False, False),
                                                 (False, True),
                                                 (True, False)])
def test_segment_chain_recovery_byte_identical(tmp_path, exact_device,
                                               mirror):
    """Per-doc generations stitch back byte-identically across host +
    both device modes (the acceptance matrix), including a freed doc's
    tombstone (no resurrection from an older segment copy)."""
    path = str(tmp_path / f'dur-{exact_device}-{mirror}')
    mgr = DurableFleet(path, exact_device=exact_device)
    handles = mgr.init_docs(6)
    handles = _grow(mgr, handles, 1)
    mgr.checkpoint()
    seqs = [1] * len(handles)              # per-doc seq from round 1
    for r in (2, 3, 4):
        # each round touches a sliding window of docs, then compacts —
        # every doc's newest copy ends up in a DIFFERENT segment
        per_doc = [[] for _ in handles]
        for i in range(r - 2, r + 1):
            seqs[i] += 1
            per_doc[i] = [_change(f'{i:02x}' * 16, seqs[i],
                                  fb.get_heads(handles[i]), r * 10 + i,
                                  start=seqs[i])]
        handles, _p, errs = mgr.apply_changes(handles, per_doc,
                                              mirror=mirror)
        assert not any(errs)
        assert mgr.maybe_compact(force=True)
    fb.free_docs([handles[5]])
    assert mgr.maybe_compact(force=True)   # tombstone segment
    assert len(mgr.chain) >= 4
    pre = {i: bytes(fb.save(handles[i])) for i in range(5)}
    mgr.close()
    mgr2, rec, report = DurableFleet.recover(path, exact_device=exact_device,
                                             mirror=mirror)
    assert report.ok
    assert sorted(rec) == sorted(pre)      # doc 5 did NOT resurrect
    for i, want in pre.items():
        assert bytes(fb.save(rec[i])) == want
    mgr2.close()


def test_first_compaction_without_checkpoint_cuts_a_base(tmp_path):
    """Review find: a fleet that only ever compacts (the service path —
    nothing calls checkpoint() directly) must still get a BASE snapshot,
    or the manifest-rot fallback scan has no chain start and retention
    eventually strands records in deleted journals."""
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(3)
    handles = _grow(mgr, handles, 1)
    assert mgr.chain == []
    assert mgr.maybe_compact(force=True) is True
    assert len(mgr.chain) == 1                  # escalated to a base
    handles = _grow(mgr, handles, 2)
    assert mgr.maybe_compact(force=True) is True
    assert len(mgr.chain) == 2                  # now segments may follow
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    # manifest rot -> fallback scan must find the base and stitch
    mpath = os.path.join(path, 'MANIFEST')
    data = bytearray(open(mpath, 'rb').read())
    data[8] ^= 0xff
    open(mpath, 'wb').write(bytes(data))
    mgr2, rec, report = DurableFleet.recover(path)
    assert report.used_fallback_manifest
    assert [bytes(fb.save(rec[i])) for i in range(3)] == pre
    mgr2.close()


def test_chain_escalates_to_full_checkpoint(tmp_path):
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path, max_chain=3)
    handles = mgr.init_docs(2)
    for r in range(1, 8):
        handles = _grow(mgr, handles, r)
        mgr.maybe_compact(force=True)
        assert len(mgr.chain) <= 3
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    mgr2, rec, report = DurableFleet.recover(path)
    assert report.ok
    assert [bytes(fb.save(rec[i])) for i in range(2)] == pre
    mgr2.close()


def test_recovery_rejournals_instead_of_resnapshotting(tmp_path):
    """Recovery's closing persist is O(replayed), not O(fleet): a clean
    recovery with a journal suffix writes NO new snapshot (the chain is
    reused; the replayed records land in the fresh journal generation),
    and an immediate second recovery reproduces the same states."""
    path = str(tmp_path / 'dur')
    mgr = DurableFleet(path)
    handles = mgr.init_docs(8)
    handles = _grow(mgr, handles, 1)
    mgr.checkpoint()
    handles = _grow(mgr, handles, 2)       # journal suffix over snapshot
    pre = [bytes(fb.save(h)) for h in handles]
    mgr.close()
    snaps_before = set(glob.glob(os.path.join(path, 'snapshot-*.snap')))
    ckpt_count = D.durability_stats()['checkpoints']
    mgr2, rec, report = DurableFleet.recover(path)
    assert report.ok and report.replayed_records == 8
    assert D.durability_stats()['checkpoints'] == ckpt_count
    assert set(glob.glob(os.path.join(path, 'snapshot-*.snap'))) == \
        snaps_before
    assert [bytes(fb.save(rec[i])) for i in range(8)] == pre
    mgr2.close()
    mgr3, rec3, report3 = DurableFleet.recover(path)
    assert report3.ok
    assert [bytes(fb.save(rec3[i])) for i in range(8)] == pre
    mgr3.close()


# ---------------------------------------------------------------------------
# crash-injection doses (tools/crashtest.py)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_crashtest_smoke():
    """Seeded smoke dose of the crash matrix in tier-1: a few kill
    offsets, the torn final frame, journal + snapshot rot, the
    checkpoint-protocol crash points, AND the incremental-compaction
    legs (segment-chain recovery, truncation over a chain, newest-
    segment rot falling back a generation, compaction-protocol crash
    points), on the turbo path."""
    from crashtest import run_crashtest
    stats = run_crashtest(n_seeds=1, n_points=2, modes=['lww'])
    assert stats['failures'] == [], stats['failures'][:5]
    assert stats['cases'] >= 8


@pytest.mark.slow
@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_crashtest_full_matrix():
    """The full matrix: every mode (turbo, host-exact mirror replay,
    exact-device registers) x seeds x fault classes."""
    from crashtest import run_crashtest
    stats = run_crashtest(n_seeds=3, n_points=6,
                          modes=['lww', 'lww-mirror', 'exact'])
    assert stats['failures'] == [], stats['failures'][:10]
