"""Cross-shard sync transport (fleet/exchange.py): payload matrices ride one
all_to_all over the mesh, and full sync-protocol rounds between sharded
backends converge using the device as the transport."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet.exchange import (
    drive_pairwise_sync, drive_pairwise_sync_multihost, exchange_changes,
    pack_outboxes, sync_round_multihost, unpack_inbox)

N_SHARDS = 4


def seed_backend(i):
    """One host backend holding shard i's private change (key ki=i)."""
    b = Backend.init()
    b, _ = Backend.apply_changes(b, [encode_change({
        'actor': f'{i:02x}' * 16, 'seq': 1, 'startOp': 1, 'time': 0,
        'deps': [], 'ops': [{'action': 'set', 'obj': '_root',
                             'key': f'k{i}', 'value': i,
                             'datatype': 'int', 'pred': []}]})])
    return b


@pytest.fixture
def mesh():
    devices = jax.devices()[:N_SHARDS]
    if len(devices) < N_SHARDS:
        pytest.skip(f'needs {N_SHARDS} devices')
    return Mesh(np.array(devices), ('peers',))


def test_all_to_all_transpose(mesh):
    """Shard i's payload-for-j must arrive as shard j's payload-from-i."""
    payload = lambda i, j: bytes(f'msg {i}->{j}', 'ascii') * (i + j + 1)
    rows, row_lens = [], []
    for i in range(N_SHARDS):
        data, lens = pack_outboxes([payload(i, j) for j in range(N_SHARDS)],
                                   max_len=128)
        rows.append(data)
        row_lens.append(lens)
    outboxes = np.stack(rows)
    lens = np.stack(row_lens)
    inboxes, in_lens = exchange_changes(mesh, 'peers', outboxes, lens)
    inboxes = np.asarray(jax.device_get(inboxes))
    in_lens = np.asarray(jax.device_get(in_lens))
    for j in range(N_SHARDS):
        received = unpack_inbox(inboxes[j], in_lens[j])
        assert received == [payload(i, j) for i in range(N_SHARDS)]


def test_sharded_sync_convergence(mesh):
    """One backend per shard, each with a private change; repeated
    all_to_all-transported sync rounds must converge every shard to every
    change (the sync_test.js driver loop, with ICI as the wire)."""
    backends = [seed_backend(i) for i in range(N_SHARDS)]
    drive_pairwise_sync(mesh, 'peers', backends, Backend)
    heads = [tuple(Backend.get_heads(b)) for b in backends]
    assert len(set(heads)) == 1
    assert len(heads[0]) == N_SHARDS


def test_sharded_fleet_backend_sync_convergence(mesh):
    """The REAL backend seam run multi-chip (VERDICT round-3 item 6): one
    FleetBackend per shard over ONE mesh-sharded DocFleet, initial changes
    applied through the turbo seam (apply_changes_docs(mirror=False), the
    merge dispatch running SPMD over the docs axis), then sync rounds whose
    transport is the all_to_all — not host backends standing in."""
    from automerge_tpu.fleet import backend as fleet_backend
    from automerge_tpu.fleet.backend import DocFleet

    actors = [f'{i:02x}' * 16 for i in range(N_SHARDS)]
    fleet = DocFleet(doc_capacity=N_SHARDS, key_capacity=4,
                     mesh=Mesh(np.array(jax.devices()[:N_SHARDS]).reshape(
                         N_SHARDS, 1), ('docs', 'keys')))
    backends = fleet_backend.init_docs(N_SHARDS, fleet)
    per_doc = [[encode_change({
        'actor': actors[i], 'seq': 1, 'startOp': 1, 'time': 0, 'message': '',
        'deps': [], 'ops': [{'action': 'set', 'obj': '_root',
                             'key': f'k{i}', 'value': i,
                             'datatype': 'int', 'pred': []}]})]
        for i in range(N_SHARDS)]
    backends, _ = fleet_backend.apply_changes_docs(backends, per_doc,
                                                   mirror=False)
    assert fleet.metrics.turbo_calls == 1
    assert fleet.state.winners.sharding.spec[0] == 'docs'

    drive_pairwise_sync(mesh, 'peers', backends, fleet_backend)
    heads = [tuple(fleet_backend.get_heads(b)) for b in backends]
    assert len(set(heads)) == 1
    assert len(heads[0]) == N_SHARDS
    # Every shard stayed fleet-resident and converged to the same state
    assert all(b['state'].is_fleet for b in backends)
    assert fleet.metrics.promotions == 0
    from automerge_tpu.fleet.backend import materialize_docs
    mats = materialize_docs(backends)
    want = {f'k{i}': i for i in range(N_SHARDS)}
    assert all(m == want for m in mats), mats


def test_multihost_driver_single_controller(mesh):
    """drive_pairwise_sync_multihost on a single-controller mesh (all
    shards local): same convergence as drive_pairwise_sync, via the
    multi-controller code path — process-local outbox rows, the
    agreement allgather, the lock-step convergence break (the loop must
    stop well before the 2n bound once a round moves nothing)."""
    local_docs = {i: seed_backend(i) for i in range(N_SHARDS)}
    rounds = drive_pairwise_sync_multihost(mesh, 'peers', local_docs,
                                           Backend)
    assert rounds < 2 * N_SHARDS       # the convergence vote broke early
    heads = [tuple(Backend.get_heads(local_docs[i]))
             for i in range(N_SHARDS)]
    assert len(set(heads)) == 1
    assert len(heads[0]) == N_SHARDS


def test_multihost_round_oversize_chunks_and_reassembles(mesh):
    """A payload over max_msg no longer kills the round: it splits across
    ceil(max/len) fixed-width sub-rounds and reassembles byte-exact at the
    receiver, with the extra sub-rounds visible in the sync_retries
    health counter."""
    from automerge_tpu.fleet.exchange import _sync_stats

    def payload(src, dst):
        # different sizes per pair, some multi-chunk, some sub-chunk
        return bytes([src * 16 + dst]) * (40 + 97 * src + 311 * dst)

    def generate(src, dst):
        return payload(src, dst)

    got = {}
    retries_before = _sync_stats['sync_retries']
    sent = sync_round_multihost(mesh, 'peers', generate,
                                lambda dst, src, p: got.__setitem__(
                                    (dst, src), p),
                                max_msg=128)
    assert sent == N_SHARDS * (N_SHARDS - 1)
    for dst in range(N_SHARDS):
        for src in range(N_SHARDS):
            if src != dst:
                assert got[(dst, src)] == payload(src, dst)
    assert _sync_stats['sync_retries'] > retries_before


def test_multihost_round_hard_overflow_raises_typed(mesh):
    """Beyond max_msg * max_chunks the round must still fail — with a
    typed SyncOverflow during the agreement phase (every controller
    together, never inside the padded exchange), carrying the sizes and
    the locally-determinable offending pairs."""
    from automerge_tpu.errors import SyncOverflow

    def generate(src, dst):
        return b'x' * 300

    with pytest.raises(SyncOverflow, match='exceeds max_msg') as ei:
        sync_round_multihost(mesh, 'peers', generate,
                             lambda *a: None, max_msg=128, max_chunks=2)
    assert ei.value.global_max == 300
    assert ei.value.max_msg == 128
    assert (0, 1) in ei.value.pairs
    # SyncOverflow subclasses ValueError: pre-typed call sites still catch
    assert isinstance(ei.value, ValueError)
