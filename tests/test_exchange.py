"""Cross-shard sync transport (fleet/exchange.py): payload matrices ride one
all_to_all over the mesh, and full sync-protocol rounds between sharded
backends converge using the device as the transport."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

import automerge_tpu as am
from automerge_tpu import backend as Backend
from automerge_tpu.columnar import encode_change
from automerge_tpu.fleet.exchange import (
    exchange_changes, pack_outboxes, sync_round_sharded, unpack_inbox)

N_SHARDS = 4


@pytest.fixture
def mesh():
    devices = jax.devices()[:N_SHARDS]
    if len(devices) < N_SHARDS:
        pytest.skip(f'needs {N_SHARDS} devices')
    return Mesh(np.array(devices), ('peers',))


def test_all_to_all_transpose(mesh):
    """Shard i's payload-for-j must arrive as shard j's payload-from-i."""
    payload = lambda i, j: bytes(f'msg {i}->{j}', 'ascii') * (i + j + 1)
    rows, row_lens = [], []
    for i in range(N_SHARDS):
        data, lens = pack_outboxes([payload(i, j) for j in range(N_SHARDS)],
                                   max_len=128)
        rows.append(data)
        row_lens.append(lens)
    outboxes = np.stack(rows)
    lens = np.stack(row_lens)
    inboxes, in_lens = exchange_changes(mesh, 'peers', outboxes, lens)
    inboxes = np.asarray(jax.device_get(inboxes))
    in_lens = np.asarray(jax.device_get(in_lens))
    for j in range(N_SHARDS):
        received = unpack_inbox(inboxes[j], in_lens[j])
        assert received == [payload(i, j) for i in range(N_SHARDS)]


def test_sharded_sync_convergence(mesh):
    """One backend per shard, each with a private change; repeated
    all_to_all-transported sync rounds must converge every shard to every
    change (the sync_test.js driver loop, with ICI as the wire)."""
    actors = [f'{i:02x}' * 16 for i in range(N_SHARDS)]
    backends = []
    for i in range(N_SHARDS):
        b = Backend.init()
        b, _ = Backend.apply_changes(b, [encode_change({
            'actor': actors[i], 'seq': 1, 'startOp': 1, 'time': 0,
            'deps': [], 'ops': [{'action': 'set', 'obj': '_root',
                                 'key': f'k{i}', 'value': i,
                                 'datatype': 'int', 'pred': []}]})])
        backends.append(b)
    sync_states = {(i, j): Backend.init_sync_state()
                   for i in range(N_SHARDS) for j in range(N_SHARDS) if i != j}

    def generate(src, dst):
        state, msg = Backend.generate_sync_message(backends[src],
                                                   sync_states[(src, dst)])
        sync_states[(src, dst)] = state
        return msg

    def receive(dst, src, payload):
        b, state, _patch = Backend.receive_sync_message(
            backends[dst], sync_states[(dst, src)], payload)
        backends[dst] = b
        sync_states[(dst, src)] = state

    for round_ in range(8):
        moved = sync_round_sharded(mesh, 'peers', backends, sync_states,
                                   generate, receive)
        if moved == 0:
            break
    heads = [tuple(Backend.get_heads(b)) for b in backends]
    assert len(set(heads)) == 1
    assert len(heads[0]) == N_SHARDS
