"""Port of the reference public-API suite, part 1 (ref test/test.js:8-574):
initialization, sequential use, the changes section, emptyChange, root
object semantics, and nested maps. Parts 2/3 live in test_test_js2.py /
test_test_js3.py; a first subset was ported earlier in test_integration.py.
"""

import datetime
import re

import pytest

import automerge_tpu as A
from automerge_tpu.backend import get_heads, get_missing_deps
from automerge_tpu.frontend import get_backend_state

OPID_PATTERN = re.compile(r'^[0-9]+@[0-9a-f]+$')


def assert_equals_one_of(actual, *expected):
    assert any(A.equals(actual, e) for e in expected), \
        f'{actual!r} not equal to any of {expected!r}'


class TestInitialization:
    """ref test/test.js:10-60"""

    def test_initially_an_empty_map(self):
        assert A.equals(A.init(), {})

    def test_instantiating_from_existing_object(self):
        initial = {'birds': {'wrens': 3, 'magpies': 4}}
        assert A.equals(A.from_(initial), initial)

    def test_merging_of_object_initialized_with_from(self):
        doc1 = A.from_({'cards': []})
        doc2 = A.merge(A.init(), doc1)
        assert A.equals(doc2, {'cards': []})

    def test_actor_id_when_instantiating_from_object(self):
        doc = A.from_({'foo': 1}, '1234')
        assert A.get_actor_id(doc) == '1234'

    def test_accepts_empty_object_as_initial_state(self):
        assert A.equals(A.from_({}), {})

    def test_accepts_array_as_initial_state_converted_to_object(self):
        doc = A.from_(['a', 'b', 'c'])
        assert A.equals(doc, {'0': 'a', '1': 'b', '2': 'c'})

    def test_accepts_strings_as_array_of_characters(self):
        doc = A.from_('abc')
        assert A.equals(doc, {'0': 'a', '1': 'b', '2': 'c'})

    def test_ignores_numbers_as_initial_values(self):
        assert A.equals(A.from_(123), {})

    def test_ignores_booleans_as_initial_values(self):
        assert A.equals(A.from_(False), {})
        assert A.equals(A.from_(True), {})

    def test_frontend_from_shares_initial_state_semantics(self):
        assert A.equals(A.Frontend.from_(['a', 'b']), {'0': 'a', '1': 'b'})
        assert A.equals(A.Frontend.from_(7), {})

    def test_rejects_non_mapping_rich_initial_state(self):
        with pytest.raises(TypeError, match='Unsupported initial state'):
            A.from_(A.Text('abc'))


class TestSequentialUse:
    """ref test/test.js:62-93"""

    def test_should_not_mutate_objects(self):
        s1 = A.init()
        s2 = A.change(s1, lambda d: d.update({'foo': 'bar'}))
        assert 'foo' not in s1
        assert s2['foo'] == 'bar'

    def test_changes_should_be_retrievable(self):
        s1 = A.init()
        assert A.get_last_local_change(s1) is None
        s2 = A.change(s1, lambda d: d.update({'foo': 'bar'}))
        change = A.decode_change(A.get_last_local_change(s2))
        assert change['deps'] == []
        assert change['seq'] == 1
        assert change['startOp'] == 1
        assert change['message'] == ''
        assert change['ops'] == [
            {'obj': '_root', 'key': 'foo', 'action': 'set', 'insert': False,
             'value': 'bar', 'pred': []}]

    def test_no_conflicts_on_repeated_assignment(self):
        s1 = A.init()
        assert A.get_conflicts(s1, 'foo') is None
        s1 = A.change(s1, 'change', lambda d: d.update({'foo': 'one'}))
        assert A.get_conflicts(s1, 'foo') is None
        s1 = A.change(s1, 'change', lambda d: d.update({'foo': 'two'}))
        assert A.get_conflicts(s1, 'foo') is None


class TestChanges:
    """ref test/test.js:95-333"""

    def test_should_group_several_changes(self):
        s1 = A.init()

        def cb(doc):
            doc['first'] = 'one'
            assert doc['first'] == 'one'
            doc['second'] = 'two'
            assert dict(doc) == {'first': 'one', 'second': 'two'}

        s2 = A.change(s1, 'change message', cb)
        assert A.equals(s1, {})
        assert A.equals(s2, {'first': 'one', 'second': 'two'})

    def test_repeated_reading_and_writing_of_values(self):
        s1 = A.init()

        def cb(doc):
            doc['value'] = 'a'
            assert doc['value'] == 'a'
            doc['value'] = 'b'
            doc['value'] = 'c'
            assert doc['value'] == 'c'

        s2 = A.change(s1, 'change message', cb)
        assert A.equals(s1, {})
        assert A.equals(s2, {'value': 'c'})

    def test_no_conflicts_writing_same_field_multiple_times_in_one_change(self):
        def cb(doc):
            doc['value'] = 'a'
            doc['value'] = 'b'
            doc['value'] = 'c'
        s1 = A.change(A.init(), 'change message', cb)
        assert s1['value'] == 'c'
        assert A.get_conflicts(s1, 'value') is None

    def test_returns_unchanged_state_object_if_nothing_changed(self):
        s1 = A.init()
        assert A.change(s1, lambda d: None) is s1

    def test_ignores_field_updates_that_write_existing_value(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 123}))
        s2 = A.change(s1, lambda d: d.update({'field': 123}))
        assert s2 is s1

    def test_does_not_ignore_updates_that_resolve_a_conflict(self):
        s1 = A.init()
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d.update({'field': 123}))
        s2 = A.change(s2, lambda d: d.update({'field': 321}))
        s1 = A.merge(s1, s2)
        assert len(A.get_conflicts(s1, 'field')) == 2
        resolved = A.change(s1, lambda d: d.update({'field': s1['field']}))
        assert resolved is not s1
        assert A.equals(resolved, {'field': s1['field']})
        assert A.get_conflicts(resolved, 'field') is None

    def test_ignores_list_element_updates_that_write_existing_value(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': [123]}))
        s2 = A.change(s1, lambda d: d['list'].__setitem__(0, 123))
        assert s2 is s1

    def test_does_not_ignore_list_updates_that_resolve_a_conflict(self):
        s1 = A.change(A.init(), lambda d: d.update({'list': [1]}))
        s2 = A.merge(A.init(), s1)
        s1 = A.change(s1, lambda d: d['list'].__setitem__(0, 123))
        s2 = A.change(s2, lambda d: d['list'].__setitem__(0, 321))
        s1 = A.merge(s1, s2)
        assert A.get_conflicts(s1['list'], 0) == {
            f'3@{A.get_actor_id(s1)}': 123,
            f'3@{A.get_actor_id(s2)}': 321,
        }
        resolved = A.change(s1, lambda d: d['list'].__setitem__(0, s1['list'][0]))
        assert A.equals(resolved, s1)
        assert resolved is not s1
        assert A.get_conflicts(resolved['list'], 0) is None

    def test_sanity_checks_arguments(self):
        s1 = A.change(A.init(), lambda d: d.update({'nested': {}}))
        with pytest.raises(Exception, match='document root'):
            A.change({}, lambda d: d.update({'foo': 'bar'}))
        with pytest.raises(Exception, match='document root'):
            A.change(s1['nested'], lambda d: d.update({'foo': 'bar'}))

    def test_does_not_allow_nested_change_blocks(self):
        s1 = A.init()
        with pytest.raises(Exception, match='nested'):
            A.change(s1, lambda d1: A.change(d1, lambda d2: d2.update({'foo': 'bar'})))

    def test_same_base_document_cannot_be_used_for_multiple_changes(self):
        s1 = A.init()
        A.change(s1, lambda d: d.update({'one': 1}))
        with pytest.raises(Exception, match='outdated'):
            A.change(s1, lambda d: d.update({'two': 2}))

    def test_allows_document_to_be_cloned(self):
        s1 = A.change(A.init(), lambda d: d.update({'zero': 0}))
        s2 = A.clone(s1)
        s1 = A.change(s1, lambda d: d.update({'one': 1}))
        s2 = A.change(s2, lambda d: d.update({'two': 2}))
        assert A.equals(s1, {'zero': 0, 'one': 1})
        assert A.equals(s2, {'zero': 0, 'two': 2})
        A.free(s1)
        A.free(s2)

    def test_applies_changes_to_a_clone(self):
        s1 = A.change(A.init(), lambda d: d.update({'x': 1}))
        s1 = A.change(s1, lambda d: d.update({'x': 2}))
        changes = A.get_all_changes(s1)
        s2 = A.clone(A.load(A.save(s1)))
        s2, _ = A.apply_changes(s2, changes)
        assert s2['x'] == 2

    def test_object_assign_style_merges(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'stuff': {'foo': 'bar', 'baz': 'blur'}}))
        s1 = A.change(s1, lambda d: d.update(
            {'stuff': dict(d['stuff'], baz='updated!')}))
        assert A.equals(s1, {'stuff': {'foo': 'bar', 'baz': 'updated!'}})

    def test_date_objects_in_maps(self):
        now = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        s1 = A.change(A.init(), lambda d: d.update({'now': now}))
        s2, _ = A.apply_changes(A.init(), A.get_all_changes(s1))
        assert isinstance(s2['now'], datetime.datetime)
        assert s2['now'] == now

    def test_date_objects_in_lists(self):
        now = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        s1 = A.change(A.init(), lambda d: d.update({'list': [now]}))
        s2, _ = A.apply_changes(A.init(), A.get_all_changes(s1))
        assert isinstance(s2['list'][0], datetime.datetime)
        assert s2['list'][0] == now

    def test_many_date_objects_in_lists(self):
        base = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        nows = [base + datetime.timedelta(seconds=i) for i in range(3)]
        s1 = A.change(A.init(), lambda d: d.update({'list': list(nows)}))
        s2, _ = A.apply_changes(A.init(), A.get_all_changes(s1))
        for i in range(3):
            assert isinstance(s2['list'][i], datetime.datetime)
            assert s2['list'][i] == nows[i]

    def test_calls_patch_callback_if_supplied(self):
        s1 = A.init()
        callbacks = []
        actor = A.get_actor_id(s1)
        s2 = A.change(
            s1,
            {'patchCallback': lambda patch, before, after, local, changes:
                callbacks.append((patch, before, after, local))},
            lambda d: d.update({'birds': ['Goldfinch']}))
        assert len(callbacks) == 1
        patch, before, after, local = callbacks[0]
        assert patch == {
            'actor': actor, 'seq': 1, 'maxOp': 2, 'deps': [],
            'clock': {actor: 1}, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'birds': {f'1@{actor}': {
                    'objectId': f'1@{actor}', 'type': 'list', 'edits': [
                        {'action': 'insert', 'index': 0,
                         'elemId': f'2@{actor}', 'opId': f'2@{actor}',
                         'value': {'type': 'value', 'value': 'Goldfinch'}}]}}}},
        }
        assert before is s1
        assert after is s2
        assert local is True

    def test_calls_patch_callback_set_up_on_initialisation(self):
        callbacks = []
        s1 = A.init({'patchCallback':
                     lambda patch, before, after, local, changes:
                     callbacks.append((patch, before, after, local))})
        s2 = A.change(s1, lambda d: d.update({'bird': 'Goldfinch'}))
        actor = A.get_actor_id(s1)
        assert len(callbacks) == 1
        patch, before, after, local = callbacks[0]
        assert patch == {
            'actor': actor, 'seq': 1, 'maxOp': 1, 'deps': [],
            'clock': {actor: 1}, 'pendingChanges': 0,
            'diffs': {'objectId': '_root', 'type': 'map', 'props': {
                'bird': {f'1@{actor}': {'type': 'value',
                                        'value': 'Goldfinch'}}}},
        }
        assert before is s1
        assert after is s2
        assert local is True


class TestEmptyChange:
    """ref test/test.js:333-365"""

    def test_appends_an_empty_change_to_history(self):
        s1 = A.change(A.init(), 'first change', lambda d: d.update({'field': 123}))
        s2 = A.empty_change(s1, 'empty change')
        assert s2 is not s1
        assert A.equals(s2, s1)
        assert [h.change['message'] for h in A.get_history(s2)] == \
            ['first change', 'empty change']

    def test_references_dependencies(self):
        s1 = A.change(A.init(), lambda d: d.update({'field': 123}))
        s2 = A.merge(A.init(), s1)
        s2 = A.change(s2, lambda d: d.update({'other': 'hello'}))
        s1 = A.empty_change(A.merge(s1, s2))
        history = A.get_history(s1)
        empty_change = history[2].change
        assert empty_change['deps'] == sorted(
            [history[0].change['hash'], history[1].change['hash']])
        assert empty_change['ops'] == []

    def test_empty_change_encodes_and_decodes(self):
        s1 = A.empty_change(A.init())
        s1 = A.change(s1, lambda d: d.update({'z': 1}))
        s1 = A.change(s1, lambda d: d.update({'z': 1000}))
        changes = A.get_all_changes(A.load(A.save(s1)))
        s2, _ = A.apply_changes(A.init(), changes)
        assert get_heads(get_backend_state(s1)) == \
            get_heads(get_backend_state(s2))
        assert A.equals(s1, s2)


class TestRootObject:
    """ref test/test.js:367-440"""

    def test_single_property_assignment(self):
        s1 = A.change(A.init(), 'set bar', lambda d: d.update({'foo': 'bar'}))
        s1 = A.change(s1, 'set zap', lambda d: d.update({'zip': 'zap'}))
        assert s1['foo'] == 'bar'
        assert s1['zip'] == 'zap'
        assert A.equals(s1, {'foo': 'bar', 'zip': 'zap'})

    def test_allows_floating_point_values(self):
        s1 = A.change(A.init(), lambda d: d.update({'number': 1589032171.1}))
        assert s1['number'] == 1589032171.1

    def test_multi_property_assignment(self):
        s1 = A.change(A.init(), 'multi-assign',
                      lambda d: d.update({'foo': 'bar', 'answer': 42}))
        assert s1['foo'] == 'bar'
        assert s1['answer'] == 42
        assert A.equals(s1, {'foo': 'bar', 'answer': 42})

    def test_root_property_deletion(self):
        def set_cb(doc):
            doc['foo'] = 'bar'
            doc['something'] = None
        s1 = A.change(A.init(), 'set foo', set_cb)
        s1 = A.change(s1, 'del foo', lambda d: d.__delitem__('foo'))
        assert 'foo' not in s1
        assert s1['something'] is None
        assert A.equals(s1, {'something': None})

    def test_allows_type_of_property_to_be_changed(self):
        s1 = A.change(A.init(), 'set number', lambda d: d.update({'prop': 123}))
        assert s1['prop'] == 123
        s1 = A.change(s1, 'set string', lambda d: d.update({'prop': '123'}))
        assert s1['prop'] == '123'
        s1 = A.change(s1, 'set null', lambda d: d.update({'prop': None}))
        assert s1['prop'] is None
        s1 = A.change(s1, 'set bool', lambda d: d.update({'prop': True}))
        assert s1['prop'] is True

    def test_requires_property_names_to_be_valid(self):
        with pytest.raises(Exception, match='empty string'):
            A.change(A.init(), 'foo', lambda d: d.update({'': 'x'}))

    def test_does_not_allow_unsupported_datatypes(self):
        s1 = A.init()
        with pytest.raises(Exception, match='[Uu]nsupported'):
            A.change(s1, lambda d: d.update({'foo': object()}))
        s1 = A.init()
        with pytest.raises(Exception, match='[Uu]nsupported'):
            A.change(s1, lambda d: d.update({'foo': lambda: None}))


class TestNestedMaps:
    """ref test/test.js:441-574"""

    def test_assigns_object_id_to_nested_maps(self):
        s1 = A.change(A.init(), lambda d: d.update({'nested': {}}))
        assert OPID_PATTERN.match(A.get_object_id(s1['nested']))
        assert A.get_object_id(s1['nested']) != '_root'

    def test_assignment_of_nested_property(self):
        def cb(doc):
            doc['nested'] = {}
            doc['nested']['foo'] = 'bar'
        s1 = A.change(A.init(), 'first change', cb)
        s1 = A.change(s1, 'second change',
                      lambda d: d['nested'].update({'one': 1}))
        assert A.equals(s1, {'nested': {'foo': 'bar', 'one': 1}})
        assert A.equals(s1['nested'], {'foo': 'bar', 'one': 1})
        assert s1['nested']['foo'] == 'bar'
        assert s1['nested']['one'] == 1

    def test_assignment_of_object_literal(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'textStyle': {'bold': False, 'fontSize': 12}}))
        assert A.equals(s1, {'textStyle': {'bold': False, 'fontSize': 12}})
        assert s1['textStyle']['bold'] is False
        assert s1['textStyle']['fontSize'] == 12

    def test_assignment_of_multiple_nested_properties(self):
        def cb(doc):
            doc['textStyle'] = {'bold': False, 'fontSize': 12}
            doc['textStyle'].update({'typeface': 'Optima', 'fontSize': 14})
        s1 = A.change(A.init(), cb)
        assert s1['textStyle']['typeface'] == 'Optima'
        assert s1['textStyle']['bold'] is False
        assert s1['textStyle']['fontSize'] == 14
        assert A.equals(s1['textStyle'],
                        {'typeface': 'Optima', 'bold': False, 'fontSize': 14})

    def test_arbitrary_depth_nesting(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'a': {'b': {'c': {'d': {'e': {'f': {'g': 'h'}}}}}}}))
        s1 = A.change(s1, lambda d:
                      d['a']['b']['c']['d']['e']['f'].update({'i': 'j'}))
        assert A.equals(s1, {'a': {'b': {'c': {'d': {'e': {'f':
                        {'g': 'h', 'i': 'j'}}}}}}})
        assert s1['a']['b']['c']['d']['e']['f']['g'] == 'h'
        assert s1['a']['b']['c']['d']['e']['f']['i'] == 'j'

    def test_allows_old_object_to_be_replaced_with_new_one(self):
        s1 = A.change(A.init(), 'change 1', lambda d: d.update(
            {'myPet': {'species': 'dog', 'legs': 4, 'breed': 'dachshund'}}))
        s2 = A.change(s1, 'change 2', lambda d: d.update(
            {'myPet': {'species': 'koi', 'variety': '紅白',
                       'colors': {'red': True, 'white': True, 'black': False}}}))
        assert A.equals(s1['myPet'],
                        {'species': 'dog', 'legs': 4, 'breed': 'dachshund'})
        assert s1['myPet']['breed'] == 'dachshund'
        assert A.equals(s2['myPet'],
                        {'species': 'koi', 'variety': '紅白',
                         'colors': {'red': True, 'white': True, 'black': False}})
        assert 'breed' not in s2['myPet']
        assert s2['myPet']['variety'] == '紅白'

    def test_allows_fields_to_change_between_primitive_and_nested_map(self):
        s1 = A.change(A.init(), lambda d: d.update({'color': '#ff7f00'}))
        assert s1['color'] == '#ff7f00'
        s1 = A.change(s1, lambda d: d.update(
            {'color': {'red': 255, 'green': 127, 'blue': 0}}))
        assert A.equals(s1['color'], {'red': 255, 'green': 127, 'blue': 0})
        s1 = A.change(s1, lambda d: d.update({'color': '#ff7f00'}))
        assert s1['color'] == '#ff7f00'

    def test_does_not_allow_several_references_to_same_map_object(self):
        s1 = A.change(A.init(), lambda d: d.update({'object': {}}))
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, lambda d: d.update({'x': d['object']}))
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, lambda d: d.update({'x': s1['object']}))

        def copy_cb(doc):
            doc['x'] = {}
            doc['y'] = doc['x']
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, copy_cb)

    def test_does_not_allow_object_copying_idioms(self):
        s1 = A.change(A.init(), lambda d: d.update(
            {'items': [{'id': 'id1', 'name': 'one'},
                       {'id': 'id2', 'name': 'two'}]}))
        with pytest.raises(Exception, match='reference to an existing'):
            A.change(s1, lambda d: d.update(
                {'items': list(d['items']) + [{'id': 'id3', 'name': 'three'}]}))

    def test_deletion_of_properties_within_a_map(self):
        s1 = A.change(A.init(), 'set style', lambda d: d.update(
            {'textStyle': {'typeface': 'Optima', 'bold': False,
                           'fontSize': 12}}))
        s1 = A.change(s1, 'non-bold',
                      lambda d: d['textStyle'].__delitem__('bold'))
        assert 'bold' not in s1['textStyle']
        assert A.equals(s1['textStyle'], {'typeface': 'Optima', 'fontSize': 12})

    def test_deletion_of_references_to_a_map(self):
        s1 = A.change(A.init(), 'make rich text doc', lambda d: d.update(
            {'title': 'Hello',
             'textStyle': {'typeface': 'Optima', 'fontSize': 12}}))
        s1 = A.change(s1, lambda d: d.__delitem__('textStyle'))
        assert 'textStyle' not in s1
        assert A.equals(s1, {'title': 'Hello'})

    def test_validates_field_names(self):
        s1 = A.change(A.init(), lambda d: d.update({'nested': {}}))
        with pytest.raises(Exception, match='empty string'):
            A.change(s1, lambda d: d['nested'].update({'': 'x'}))
        with pytest.raises(Exception, match='empty string'):
            A.change(s1, lambda d: d.update({'nested': {'': 'x'}}))
