"""Integration tests for the public API, modeled on reference test/test.js:
init/change semantics, lists, nested maps, counters, concurrent use and
convergence, save/load round trips, history, and the changes API."""

import datetime

import pytest

import automerge_tpu as A


def assert_equals_one_of(actual, *expected):
    assert any(A.equals(actual, e) for e in expected), \
        f'{actual!r} not equal to any of {expected!r}'


class TestInitAndChange:
    def test_init_empty(self):
        doc = A.init()
        assert A.equals(doc, {})

    def test_no_change_returns_same_doc(self):
        doc = A.init()
        doc2 = A.change(doc, 'empty', lambda d: None)
        assert doc2 is doc

    def test_set_root_key(self):
        doc = A.change(A.init('aabbcc'), lambda d: d.update({'bird': 'magpie'}))
        assert dict(doc) == {'bird': 'magpie'}

    def test_from_initial_state(self):
        doc = A.from_({'birds': {'wrens': 3, 'sparrows': 15}})
        assert A.equals(doc, {'birds': {'wrens': 3, 'sparrows': 15}})
        history = A.get_history(doc)
        assert len(history) == 1
        assert history[0].change['message'] == 'Initialization'

    def test_delete_key(self):
        doc = A.from_({'a': 1, 'b': 2})
        doc = A.change(doc, lambda d: d.__delitem__('a'))
        assert A.equals(doc, {'b': 2})

    def test_nested_maps(self):
        doc = A.change(A.init(), lambda d: d.update(
            {'outer': {'inner': {'deep': 'value'}}}))
        assert doc['outer']['inner']['deep'] == 'value'
        doc = A.change(doc, lambda d: d['outer']['inner'].update({'deep': 'new'}))
        assert doc['outer']['inner']['deep'] == 'new'

    def test_types(self):
        now = datetime.datetime.now(datetime.timezone.utc).replace(microsecond=0)
        doc = A.from_({'str': 's', 'int': 42, 'float': 1.5, 'bool': True,
                       'none': None, 'when': now})
        doc2 = A.load(A.save(doc))
        assert doc2['str'] == 's'
        assert doc2['int'] == 42
        assert doc2['float'] == 1.5
        assert doc2['bool'] is True
        assert doc2['none'] is None
        assert doc2['when'] == now

    def test_int_uint_float_wrappers(self):
        doc = A.from_({'i': A.Int(-5), 'u': A.Uint(5), 'f': A.Float64(2.0)})
        doc2 = A.load(A.save(doc))
        assert doc2['i'] == -5
        assert doc2['u'] == 5
        assert doc2['f'] == 2.0

    def test_nested_change_raises(self):
        doc = A.init()
        with pytest.raises(TypeError, match='cannot be nested'):
            A.change(doc, lambda d: A.change(d, lambda d2: None))

    def test_empty_change(self):
        doc = A.from_({'a': 1})
        doc2 = A.empty_change(doc, 'ack')
        changes = A.get_all_changes(doc2)
        assert len(changes) == 2
        assert A.decode_change(changes[1])['message'] == 'ack'
        assert A.decode_change(changes[1])['ops'] == []


class TestLists:
    def test_create_and_read(self):
        doc = A.from_({'birds': ['chaffinch', 'goldfinch']})
        assert list(doc['birds']) == ['chaffinch', 'goldfinch']
        assert len(doc['birds']) == 2

    def test_append_insert_delete(self):
        doc = A.from_({'list': [1]})
        doc = A.change(doc, lambda d: d['list'].append(2, 3))
        assert list(doc['list']) == [1, 2, 3]
        doc = A.change(doc, lambda d: d['list'].insert(0, 0))
        assert list(doc['list']) == [0, 1, 2, 3]
        doc = A.change(doc, lambda d: d['list'].delete_at(1, 2))
        assert list(doc['list']) == [0, 3]

    def test_set_index(self):
        doc = A.from_({'list': ['a', 'b', 'c']})
        doc = A.change(doc, lambda d: d['list'].__setitem__(1, 'B'))
        assert list(doc['list']) == ['a', 'B', 'c']

    def test_assign_past_end_pads_with_none(self):
        doc = A.from_({'list': ['a']})
        doc = A.change(doc, lambda d: d['list'].__setitem__(3, 'd'))
        assert list(doc['list']) == ['a', None, None, 'd']

    def test_nested_objects_in_lists(self):
        doc = A.from_({'todos': [{'title': 'one', 'done': False}]})
        doc = A.change(doc, lambda d: d['todos'][0].update({'done': True}))
        assert doc['todos'][0]['done'] is True

    def test_element_ids_stable(self):
        doc = A.from_({'list': ['a', 'b']}, 'aa')
        ids1 = A.Frontend.get_element_ids(doc['list'])
        doc = A.change(doc, lambda d: d['list'].insert(1, 'x'))
        ids2 = A.Frontend.get_element_ids(doc['list'])
        assert ids2[0] == ids1[0]
        assert ids2[2] == ids1[1]

    def test_multi_insert_positions(self):
        doc = A.from_({'list': []})
        doc = A.change(doc, lambda d: d['list'].extend([1, 2, 3, 4, 5]))
        doc = A.change(doc, lambda d: d['list'].insert_at(2, 'a', 'b'))
        assert list(doc['list']) == [1, 2, 'a', 'b', 3, 4, 5]
        doc2 = A.load(A.save(doc))
        assert list(doc2['list']) == [1, 2, 'a', 'b', 3, 4, 5]


class TestConcurrentUse:
    def test_concurrent_map_updates_converge(self):
        s1 = A.from_({'k': 'init'}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d.update({'k': 'one'}))
        s2 = A.change(s2, lambda d: d.update({'k': 'two'}))
        m1 = A.merge(s1, s2)
        m2 = A.merge(s2, m1)
        assert A.equals(m1, m2)
        # higher actor wins LWW
        assert m1['k'] == 'two'
        assert A.get_conflicts(m1, 'k') == {'2@111111': 'one', '2@222222': 'two'}

    def test_concurrent_different_keys(self):
        s1 = A.from_({'a': 1}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d.update({'b': 2}))
        s2 = A.change(s2, lambda d: d.update({'c': 3}))
        m1 = A.merge(s1, s2)
        assert A.equals(m1, {'a': 1, 'b': 2, 'c': 3})

    def test_concurrent_list_inserts_converge(self):
        s1 = A.from_({'list': ['m']}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d['list'].insert(0, 'a1'))
        s2 = A.change(s2, lambda d: d['list'].insert(0, 'a2'))
        m1 = A.merge(s1, s2)
        m2 = A.merge(s2, m1)
        assert A.equals(m1, m2)
        assert_equals_one_of(list(m1['list']),
                             ['a1', 'a2', 'm'], ['a2', 'a1', 'm'])

    def test_concurrent_delete_and_update(self):
        s1 = A.from_({'list': ['a', 'b', 'c']}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d['list'].delete_at(1))
        s2 = A.change(s2, lambda d: d['list'].__setitem__(1, 'B'))
        m1 = A.merge(s1, s2)
        m2 = A.merge(s2, m1)
        assert A.equals(m1, m2)
        # The concurrent update resurrects the deleted element
        assert list(m1['list']) == ['a', 'B', 'c']

    def test_three_way_convergence(self):
        base = A.from_({'seen': []}, 'aa0011')
        docs = [A.merge(A.init(actor), base) for actor in ('bb0011', 'cc0011')]
        docs.insert(0, base)
        for i, doc in enumerate(docs):
            docs[i] = A.change(doc, lambda d, i=i: d['seen'].append(f'actor{i}'))
        merged = docs[0]
        for other in docs[1:]:
            merged = A.merge(merged, other)
        final0 = A.merge(docs[1], merged)
        final1 = A.merge(docs[2], final0)
        assert A.equals(final0, final1)
        assert sorted(final1['seen']) == ['actor0', 'actor1', 'actor2']


class TestCounters:
    def test_counter_in_map(self):
        doc = A.from_({'n': A.Counter(0)}, '111111')
        doc = A.change(doc, lambda d: d['n'].increment())
        doc = A.change(doc, lambda d: d['n'].increment(3))
        doc = A.change(doc, lambda d: d['n'].decrement(2))
        assert doc['n'].value == 2

    def test_concurrent_counter_increments_add(self):
        s1 = A.from_({'n': A.Counter(0)}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d['n'].increment(2))
        s2 = A.change(s2, lambda d: d['n'].increment(3))
        m1 = A.merge(s1, s2)
        m2 = A.merge(s2, m1)
        assert A.equals(m1, m2)
        assert m1['n'].value == 5

    def test_counter_overwrite_rejected(self):
        doc = A.from_({'n': A.Counter(1)})
        with pytest.raises(ValueError, match='Cannot overwrite a Counter'):
            A.change(doc, lambda d: d.update({'n': 5}))

    def test_counter_round_trip(self):
        doc = A.from_({'n': A.Counter(10)})
        doc = A.change(doc, lambda d: d['n'].increment(5))
        doc2 = A.load(A.save(doc))
        assert doc2['n'].value == 15


class TestSaveLoad:
    def test_round_trip_complex(self):
        doc = A.from_({
            'map': {'nested': {'deep': [1, 2, {'x': 'y'}]}},
            'list': ['a', 1, True, None],
            'text': A.Text('hello'),
            'counter': A.Counter(5),
        }, 'abcdef')
        doc2 = A.load(A.save(doc))
        assert A.equals(doc, doc2)
        assert str(doc2['text']) == 'hello'
        assert doc2['counter'].value == 5

    def test_incremental_via_changes(self):
        doc = A.from_({'a': 1}, '111111')
        changes = A.get_all_changes(doc)
        doc = A.change(doc, lambda d: d.update({'b': 2}))
        incremental = A.get_all_changes(doc)[len(changes):]
        other = A.init('222222')
        other, _ = A.apply_changes(other, changes + incremental)
        assert A.equals(other, {'a': 1, 'b': 2})

    def test_get_last_local_change(self):
        doc = A.from_({'a': 1})
        last = A.get_last_local_change(doc)
        assert last is not None
        assert A.decode_change(last)['message'] == 'Initialization'

    def test_save_load_preserves_conflicts(self):
        s1 = A.from_({'k': 'init'}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d.update({'k': 'one'}))
        s2 = A.change(s2, lambda d: d.update({'k': 'two'}))
        m = A.merge(s1, s2)
        loaded = A.load(A.save(m))
        assert A.get_conflicts(loaded, 'k') == {'2@111111': 'one', '2@222222': 'two'}


class TestHistory:
    def test_history_snapshots(self):
        doc = A.from_({'n': 1}, 'aa')
        doc = A.change(doc, 'two', lambda d: d.update({'n': 2}))
        doc = A.change(doc, 'three', lambda d: d.update({'n': 3}))
        history = A.get_history(doc)
        assert len(history) == 3
        assert [h.change['message'] for h in history] == \
            ['Initialization', 'two', 'three']
        assert [h.snapshot['n'] for h in history] == [1, 2, 3]


class TestChangesAPI:
    def test_get_changes_between_docs(self):
        doc1 = A.from_({'a': 1}, '111111')
        doc2 = A.change(doc1, lambda d: d.update({'b': 2}))
        changes = A.get_changes(doc1, doc2)
        assert len(changes) == 1
        assert A.decode_change(changes[0])['ops'][0]['key'] == 'b'

    def test_patch_callback(self):
        calls = []

        def cb(patch, before, after, local, changes):
            calls.append((patch, local, len(changes)))
        doc = A.init({'actorId': 'aabb', 'patchCallback': cb})
        doc = A.change(doc, lambda d: d.update({'bird': 'magpie'}))
        assert len(calls) == 1
        patch, local, n = calls[0]
        assert local is True and n == 1
        assert patch['diffs']['props']['bird']

    def test_observable(self):
        observed = []
        observable = A.Observable()
        doc = A.init({'actorId': 'aabb', 'observable': observable})
        doc = A.change(doc, lambda d: d.update({'bird': 'magpie'}))
        observable.observe(doc, lambda diff, before, after, local, changes:
                           observed.append((diff, local)))
        doc = A.change(doc, lambda d: d.update({'bird': 'jay'}))
        assert len(observed) == 1
        assert observed[0][1] is True

    def test_uuid_factory(self):
        counter = [0]

        def factory():
            counter[0] += 1
            return f'{counter[0]:04d}' * 8
        A.set_uuid_factory(factory)
        try:
            doc = A.init()
            assert A.get_actor_id(doc) == '0001' * 8
        finally:
            A.set_uuid_factory(None)


class TestText:
    def test_text_editing(self):
        doc = A.from_({'text': A.Text()}, 'aa')
        doc = A.change(doc, lambda d: d['text'].insert_at(0, 'h', 'i'))
        assert str(doc['text']) == 'hi'
        doc = A.change(doc, lambda d: d['text'].insert_at(0, 'H', 'I', ' '))
        assert str(doc['text']) == 'HI hi'
        doc = A.change(doc, lambda d: d['text'].delete_at(3, 2))
        assert str(doc['text']) == 'HI '

    def test_text_set(self):
        doc = A.from_({'text': A.Text('abc')})
        doc = A.change(doc, lambda d: d['text'].set(1, 'B'))
        assert str(doc['text']) == 'aBc'

    def test_text_spans(self):
        doc = A.from_({'text': A.Text('ab')}, 'aa')
        doc = A.change(doc, lambda d: d['text'].insert_at(2, {'type': 'em'}))
        doc = A.change(doc, lambda d: d['text'].insert_at(3, 'c', 'd'))
        spans = doc['text'].to_spans()
        assert spans[0] == 'ab'
        assert dict(spans[1]) == {'type': 'em'}
        assert spans[2] == 'cd'

    def test_concurrent_text_editing_converges(self):
        s1 = A.from_({'text': A.Text('abc')}, '111111')
        s2 = A.merge(A.init('222222'), s1)
        s1 = A.change(s1, lambda d: d['text'].insert_at(0, '1'))
        s2 = A.change(s2, lambda d: d['text'].insert_at(3, '2'))
        m1 = A.merge(s1, s2)
        m2 = A.merge(s2, m1)
        assert A.equals(m1, m2)
        assert str(m1['text']) == '1abc2'


class TestTable:
    def test_table_add_query_remove(self):
        doc = A.from_({'books': A.Table()}, 'aa')
        row_id = []
        doc = A.change(doc, lambda d: row_id.append(d['books'].add(
            {'authors': 'Kleppmann', 'title': 'DDIA'})))
        assert doc['books'].count == 1
        row = doc['books'].by_id(row_id[0])
        assert row['title'] == 'DDIA'
        assert row['id'] == row_id[0]
        rows = doc['books'].filter(lambda r: r['title'] == 'DDIA')
        assert len(rows) == 1
        doc = A.change(doc, lambda d: d['books'].remove(row_id[0]))
        assert doc['books'].count == 0

    def test_table_round_trip(self):
        doc = A.from_({'t': A.Table()}, 'aa')
        doc = A.change(doc, lambda d: d['t'].add({'n': 1}))
        doc = A.change(doc, lambda d: d['t'].add({'n': 2}))
        doc2 = A.load(A.save(doc))
        assert doc2['t'].count == 2
        assert sorted(r['n'] for r in doc2['t'].rows) == [1, 2]


class TestFrontendRequestQueue:
    """Backend-less frontend mode: change requests are queued and patches
    applied asynchronously (ref test/frontend_test.js:241-300)."""

    def test_request_queue_roundtrip(self):
        from automerge_tpu import frontend as Frontend
        from automerge_tpu import backend as Backend

        doc = Frontend.init({'actorId': 'aabb', 'deferActorId': False})
        doc, req = Frontend.change(doc, lambda d: d.update({'bird': 'magpie'}))
        assert req['ops'][0]['key'] == 'bird'
        assert dict(doc) == {'bird': 'magpie'}  # optimistically applied

        # Round-trip the request through a separate backend
        b = Backend.init()
        b, patch, binary = Backend.apply_local_change(b, req)
        doc2 = Frontend.apply_patch(doc, patch)
        assert dict(doc2) == {'bird': 'magpie'}

    def test_concurrent_local_requests_rebase(self):
        from automerge_tpu import frontend as Frontend
        from automerge_tpu import backend as Backend

        doc = Frontend.init({'actorId': 'aabb'})
        doc, req1 = Frontend.change(doc, lambda d: d.update({'a': 1}))
        doc, req2 = Frontend.change(doc, lambda d: d.update({'b': 2}))
        assert dict(doc) == {'a': 1, 'b': 2}

        b = Backend.init()
        b, patch1, _ = Backend.apply_local_change(b, req1)
        doc = Frontend.apply_patch(doc, patch1)
        assert dict(doc) == {'a': 1, 'b': 2}
        b, patch2, _ = Backend.apply_local_change(b, req2)
        doc = Frontend.apply_patch(doc, patch2)
        assert dict(doc) == {'a': 1, 'b': 2}
