"""Seeded chaos universe for the query engine (ISSUE 9 acceptance): a
population of documents under random edits, park/revive churn, and
poisoned-change quarantines, followed by subscribers presenting honest,
stale, replayed, bogus, and cross-document cursors.

THE AUDIT, held after every push: the patch sequence folded onto the
subscriber's shadow copy is byte-identical to the server document
materialized at the pushed heads — across the host backend and both
fleet device modes. Stale/bogus cursors are rejected or resynced typed;
a subscriber is NEVER sent a wrong patch (the fold either reproduces the
server state exactly or the event was a typed resync that does).
"""

import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import automerge_tpu.backend as host_backend                     # noqa: E402
from automerge_tpu.columnar import (                             # noqa: E402
    decode_change_meta, encode_change)
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet.backend import DocFleet, init_docs      # noqa: E402
from automerge_tpu.fleet.storage import StorageEngine            # noqa: E402
from automerge_tpu.query import SubscriptionHub, materialize_at  # noqa: E402

N_SEEDS = int(os.environ.get('QUERY_CHAOS_SEEDS', '2'))
N_STEPS = int(os.environ.get('QUERY_CHAOS_STEPS', '25'))
N_DOCS = 4
SUBS_PER_DOC = 3


class _Shadow:
    """A subscriber's client-side replica: fold patches, rebuild on
    resync."""

    def __init__(self):
        self.doc = host_backend.init()

    def fold(self, event):
        if event['kind'] == 'resync':
            self.doc = host_backend.init()
        if event['changes']:
            self.doc, _ = host_backend.apply_changes(
                self.doc, [bytes(c) for c in event['changes']])
        assert host_backend.get_heads(self.doc) == \
            sorted(event['heads']), 'fold did not reach the pushed heads'

    def save(self):
        return bytes(host_backend.save(self.doc))


class _Universe:
    """One backend mode's server-side population."""

    def __init__(self, mode, rng):
        self.mode = mode
        self.rng = rng
        if mode == 'host':
            self.fleet = DocFleet()          # replay target for audits
            self.docs = [host_backend.init() for _ in range(N_DOCS)]
        else:
            self.fleet = DocFleet(exact_device=(mode == 'exact'))
            self.docs = init_docs(N_DOCS, self.fleet)
        self.engine = StorageEngine(self.fleet)
        self.parked = {}                     # doc index -> parked id
        self.seq = [0] * N_DOCS
        self.frontier_log = [[[]] for _ in range(N_DOCS)]
        self.quarantines = 0

    def source(self, d):
        if d in self.parked:
            return (self.engine, self.parked[d])
        return self.docs[d]

    def heads(self, d):
        if d in self.parked:
            return self.engine.heads(self.parked[d])
        return sorted(self.docs[d]['state'].heads)

    def _revive(self, d):
        if d in self.parked:
            self.docs[d] = self.engine.revive([self.parked.pop(d)])[0]

    def edit(self, d):
        self._revive(d)
        state = self.docs[d]['state']
        self.seq[d] += 1
        buf = encode_change({
            'actor': f'{d:02x}' * 16, 'seq': self.seq[d],
            'startOp': state.max_op + 1, 'time': 0, 'message': '',
            'deps': sorted(state.heads),
            'ops': [{'action': 'set', 'obj': '_root',
                     'key': f'k{self.rng.randrange(6)}',
                     'value': self.rng.randrange(1000),
                     'datatype': 'int', 'pred': []}]})
        if self.mode == 'host':
            self.docs[d], _ = host_backend.apply_changes(self.docs[d],
                                                         [buf])
        else:
            out, _ = fleet_backend.apply_changes_docs(
                [self.docs[d]], [[buf]], mirror=False)
            self.docs[d] = out[0]
        self.frontier_log[d].append(self.heads(d))

    def poison(self, d):
        """A corrupt change mid-subscription: quarantined typed, the doc
        (and every subscriber's view of it) untouched."""
        self._revive(d)
        mutant = bytearray(encode_change({
            'actor': 'dd' * 16, 'seq': 1, 'startOp': 999, 'time': 0,
            'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'x',
                     'value': 1, 'datatype': 'int', 'pred': []}]}))
        mutant[self.rng.randrange(8, len(mutant))] ^= \
            1 << self.rng.randrange(8)
        mutant = bytes(mutant)
        before = self.heads(d)
        if self.mode == 'host':
            try:
                self.docs[d], _ = host_backend.apply_changes(
                    self.docs[d], [mutant])
            except ValueError:
                self.quarantines += 1
        else:
            out, _patches, errors = fleet_backend.apply_changes_docs(
                [self.docs[d]], [[mutant]], mirror=False,
                on_error='quarantine')
            self.docs[d] = out[0]
            if errors[0] is not None:
                self.quarantines += 1
        assert self.heads(d) == before, 'poison must not corrupt the doc'

    def park(self, d):
        if self.mode == 'host' or d in self.parked:
            return False
        ids = self.engine.park([self.docs[d]])
        if ids[0] is None:
            return False
        self.parked[d] = ids[0]
        return True


@pytest.mark.parametrize('mode', ['host', 'lww', 'exact'])
def test_subscription_chaos_universe(mode):
    total_resyncs = 0
    total_quarantines = 0
    for seed in range(N_SEEDS):
        rng = random.Random(1000 + seed)
        universe = _Universe(mode, rng)
        hub = SubscriptionHub()
        shadows = {}
        for d in range(N_DOCS):
            hub.register(d, universe.source(d))
            for _ in range(SUBS_PER_DOC):
                sub = hub.subscribe(d)
                shadows[sub.id] = (_Shadow(), sub)

        def rebind():
            for d in range(N_DOCS):
                hub.update_source(d, universe.source(d))

        resyncs = 0
        for _step in range(N_STEPS):
            roll = rng.random()
            d = rng.randrange(N_DOCS)
            if roll < 0.45:
                universe.edit(d)
            elif roll < 0.55:
                universe.poison(d)
            elif roll < 0.65:
                universe.park(d)
            elif roll < 0.75:
                universe._revive(d)
            elif roll < 0.85 and shadows:
                # cursor tampering: bogus, cross-doc, or replayed-stale
                shadow, sub = rng.choice(list(shadows.values()))
                tamper = rng.random()
                if tamper < 0.4:
                    hub.resubscribe(sub, [bytes(rng.randrange(256)
                                                for _ in range(32)).hex()])
                elif tamper < 0.7:
                    other = (sub.key + 1) % N_DOCS
                    frontiers = universe.frontier_log[other]
                    hub.resubscribe(sub, rng.choice(frontiers))
                else:
                    frontiers = universe.frontier_log[sub.key]
                    hub.resubscribe(sub, rng.choice(frontiers))
            rebind()
            events = hub.tick()
            for sid, event in events.items():
                if event['kind'] == 'closed':
                    continue
                if event['kind'] == 'resync':
                    resyncs += 1
                shadow, sub = shadows[sid]
                shadow.fold(event)
                # THE AUDIT: the folded shadow is byte-identical to the
                # server doc materialized at the pushed heads
                at_heads = materialize_at(universe.source(sub.key),
                                          event['heads'],
                                          fleet=universe.fleet)
                assert shadow.save() == bytes(at_heads['state'].save()), \
                    f'seed {seed} step {_step} sub {sid}'
                fleet_backend.free_docs([at_heads])
                if event['heads'] == universe.heads(sub.key):
                    # ...and to the live server doc when fully caught up
                    src = universe.source(sub.key)
                    server = src[0].chunk(src[1]) if isinstance(src, tuple) \
                        else src['state'].save()
                    assert shadow.save() == bytes(server)

        # drain: one final quiet round leaves every subscriber at the
        # server frontier with a byte-identical shadow
        rebind()
        for event_round in range(2):
            events = hub.tick()
            for sid, event in events.items():
                if event['kind'] != 'closed':
                    shadows[sid][0].fold(event)
        for sid, (shadow, sub) in shadows.items():
            assert host_backend.get_heads(shadow.doc) == \
                universe.heads(sub.key)
        total_resyncs += resyncs
        total_quarantines += universe.quarantines
    # the hostile legs must actually have run: bogus/cross-doc cursors
    # hit the typed resync path, poisoned changes were quarantined
    assert total_resyncs >= 1
    assert total_quarantines >= 1
