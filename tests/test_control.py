"""Self-driving control plane (ISSUE-20): the observatory closes the loop.

The control plane rides the existing pumps (``DocService(control=...)``
/ ``ShardRouter(control=...)``) and is pinned here layer by layer:

- SIGNALS: ``SignalBus`` hands back per-window DELTAS over the same
  monotonic counters the dashboards read — deltas reset every sample
  and CLAMP at zero when a dead shard takes its counters out of the
  sum (no negative movement, ever).
- POLICIES: pure decision functions over one sample plus ``_Alert``
  hysteresis — N consecutive windows to arm, N at half-threshold to
  clear, midband noise resets both streaks. A signal hovering at a
  boundary cannot flap an actuator.
- ACTUATORS: existing seams only — ``set_tenant_rate`` retargets the
  live bucket in place, the ``ClockDemote`` pin lane exempts handles
  from demotion, ``rehome_tenant`` guards its inputs and rides the
  standard migration machinery.
- LEDGER: every decision (active AND shadow) carries the input signal
  snapshot and trace ids; shadow mode produces the byte-for-byte same
  decision sequence as active while touching nothing.
- CONVERGENCE: steady load reaches a FIXED POINT (>= 5 consecutive
  decision-free windows, zero reversals); the kill-one-of-four chaos
  leg settles within a pinned tick budget with zero acked-write loss
  and the heal lane doing the post-revive placement work the loadgen
  used to hardcode.
"""

import json
import os
import sys
import types

import pytest

from automerge_tpu import native
from automerge_tpu.columnar import encode_change
from automerge_tpu.control import (AdmissionRatePolicy, Controller,
                                   PinResidentPolicy, ShardBalancePolicy,
                                   SignalBus)
from automerge_tpu.control.controller import _is_reversal
from automerge_tpu.errors import AutomergeError
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, init_docs
from automerge_tpu.fleet.storage import StorageEngine
from automerge_tpu.fleet.tiering import ClockDemote
from automerge_tpu.service.admission import AdmissionController
from automerge_tpu.service.core import DocService

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

from loadgen import run_shard_leg                # noqa: E402


# --- synthetic signals ------------------------------------------------------

def _sig(tick=0, tenants=None, admission=None, shards=None,
         misplaced=(), shard_tenants=None, pump_mean_s=0.0,
         watermark=None):
    sig = {'tick': tick,
           'admission': {'admitted_d': 0, 'overloaded_d': 0,
                         'throttled_d': 0, 'reject_frac': 0.0,
                         'queue_pressure': 0.0},
           'tenants': tenants or {},
           'perf': {'max_drift': 0.0, 'alerts': 0},
           'watermark': {'pressure': watermark},
           'tiering': {'fire': 0, 'defer': 0}}
    if admission:
        sig['admission'].update(admission)
    if shards is not None:
        sig['shards'] = shards
        sig['shard_tenants'] = shard_tenants or {}
        sig['pump_mean_s'] = pump_mean_s
        sig['misplaced'] = sorted(misplaced)
        sig['migrating'] = 0
    return sig


def _tenant(admitted_d=0, throttled_d=0, rate=2.0, base_rate=2.0,
            fresh_burn=0.0, fresh_alert=0, lag=0):
    return {'admitted_d': admitted_d, 'throttled_d': throttled_d,
            'rate': rate, 'base_rate': base_rate,
            'throttled_burn': 0.0, 'fresh_burn': fresh_burn,
            'fresh_alert': fresh_alert, 'lag': lag}


def _shard(alive=True, ewma=0.0, tenants=1):
    return {'alive': alive, 'last_pump_s': ewma, 'pump_ewma_s': ewma,
            'slipped_d': 0, 'tenants': tenants}


# --- SignalBus --------------------------------------------------------------

class TestSignalBus:
    def test_deltas_reset_each_sample(self):
        svc = DocService(tenant_rate=2.0, tenant_burst=4.0)
        bus = SignalBus(service=svc)
        s = svc.open_session('t')
        for _ in range(10):
            try:
                svc.submit(s, 'sync', None)
            except AutomergeError:
                pass
        svc.pump(0.0)
        sig1 = bus.sample(1)
        assert sig1['admission']['admitted_d'] == 4      # burst tokens
        assert sig1['admission']['throttled_d'] == 6
        assert sig1['admission']['reject_frac'] == pytest.approx(0.6)
        assert sig1['tenants']['t']['throttled_d'] == 6
        assert sig1['tenants']['t']['rate'] == pytest.approx(2.0)
        # no new traffic: the next sample's movement is zero
        sig2 = bus.sample(2)
        assert sig2['admission']['admitted_d'] == 0
        assert sig2['admission']['throttled_d'] == 0
        assert sig2['tenants']['t']['throttled_d'] == 0

    def test_dead_service_counters_clamp_at_zero(self):
        bus = SignalBus()
        a, b = AdmissionController(), AdmissionController()
        a.stats['admitted'] = 100
        b.stats['admitted'] = 50
        two = [(0, types.SimpleNamespace(admission=a)),
               (1, types.SimpleNamespace(admission=b))]
        bus._sample_admission(two)
        # shard 1 dies: the summed monotonic counter DROPS by 50, which
        # must read as "no events", never as negative movement
        out = bus._sample_admission(two[:1])
        assert out['admitted_d'] == 0
        a.stats['admitted'] = 130
        out = bus._sample_admission(two[:1])
        assert out['admitted_d'] == 30


# --- policies (hysteresis over synthetic signals) ---------------------------

class TestAdmissionRatePolicy:
    def test_raise_needs_consecutive_windows_then_caps(self):
        p = AdmissionRatePolicy()
        hot = lambda: _sig(tenants={'t': _tenant(admitted_d=1,   # noqa: E731
                                                 throttled_d=9)})
        assert p.decide(hot()) == []             # window 1: arming
        acts = p.decide(hot())                   # window 2: fires
        assert [a['action'] for a in acts] == ['set_rate']
        assert acts[0]['direction'] == 'up'
        assert acts[0]['rate'] == pytest.approx(3.0)     # 2.0 * 1.5
        rates = [acts[0]['rate']]
        for _ in range(8):
            rates += [a['rate'] for a in p.decide(hot())]
        # capped at max_mult x base, then the policy goes quiet
        assert max(rates) == pytest.approx(8.0)
        assert p.decide(hot()) == []
        assert p.active() == {'tenant:t': 4.0}

    def test_midband_noise_never_fires(self):
        p = AdmissionRatePolicy()
        hot = _sig(tenants={'t': _tenant(admitted_d=1, throttled_d=9)})
        mid = _sig(tenants={'t': _tenant(admitted_d=9, throttled_d=1)})
        for _ in range(4):                       # alternating: no streak
            assert p.decide(hot) == []
            assert p.decide(mid) == []

    def test_overload_walks_boosts_back_to_base(self):
        p = AdmissionRatePolicy()
        hot = _sig(tenants={'t': _tenant(admitted_d=1, throttled_d=9)})
        p.decide(hot)
        p.decide(hot)                            # boosted to 1.5x
        assert p.active() == {'tenant:t': 1.5}
        over = _sig(tenants={'t': _tenant(admitted_d=5)},
                    admission={'queue_pressure': 0.8})
        assert p.decide(over) == []              # overload alert arming
        acts = p.decide(over)
        assert acts[0]['direction'] == 'down'
        # cut toward base, never below: 1.5 * 0.5 floors at 1.0x
        assert acts[0]['rate'] == pytest.approx(2.0)
        assert p.active() == {}


class TestPinResidentPolicy:
    def test_pin_fires_and_clears_hysteretically(self):
        p = PinResidentPolicy()
        hot = _sig(tenants={'t': _tenant(fresh_burn=2.0, lag=7)})
        cold = _sig(tenants={'t': _tenant()})
        assert p.decide(hot) == []
        acts = p.decide(hot)
        assert [a['action'] for a in acts] == ['pin']
        assert p.pinned == {'t'}
        assert p.decide(cold) == []              # clear streak 1
        acts = p.decide(cold)
        assert [a['action'] for a in acts] == ['unpin']
        assert p.pinned == set()

    def test_watermark_lane_tightens_and_relaxes(self):
        p = PinResidentPolicy()
        high = _sig(watermark=1.5)
        low = _sig(watermark=0.3)
        assert p.decide(high) == []
        acts = p.decide(high)
        assert acts == [{'policy': 'pin_resident',
                         'action': 'pressure_factor',
                         'direction': 'down', 'target': 'demote_clock',
                         'value': 0.75, 'detail': {'pressure': 1.5}}]
        assert p.decide(low) == []
        acts = p.decide(low)
        assert acts[0]['value'] == 1.0 and acts[0]['direction'] == 'up'


class TestShardBalancePolicy:
    def test_heal_lane_rehomes_misplaced(self):
        p = ShardBalancePolicy()
        sig = lambda: _sig(shards={'s0': _shard(), 's1': _shard()},  # noqa: E731
                           misplaced=['a', 'b'])
        assert p.decide(sig()) == []             # heal_up_windows=2
        acts = p.decide(sig())
        assert sorted(a['tenant'] for a in acts) == ['a', 'b']
        assert all(a['action'] == 'rehome' and a['dst'] is None and
                   a['direction'] == 'heal' for a in acts)

    def test_relief_moves_one_and_heal_never_tugs_it_back(self):
        p = ShardBalancePolicy(up_windows=2)
        hot = lambda: _sig(                                      # noqa: E731
            shards={'s0': _shard(ewma=0.04, tenants=2),
                    's1': _shard(ewma=0.002, tenants=1)},
            shard_tenants={'s0': ['x', 'y']}, pump_mean_s=0.01)
        assert p.decide(hot()) == []             # arming
        acts = p.decide(hot())
        assert len(acts) == 1
        assert acts[0]['tenant'] == 'x' and acts[0]['dst'] == 's1'
        assert acts[0]['direction'] == 's0->s1'
        assert 'x' in p.owned
        # the moved tenant is now off its ring primary, but the heal
        # lane OWNS that: no tug-of-war rehome back
        cool = lambda: _sig(                                     # noqa: E731
            shards={'s0': _shard(ewma=0.002), 's1': _shard(ewma=0.002)},
            misplaced=['x'], pump_mean_s=0.002)
        for _ in range(4):
            assert p.decide(cool()) == []


def test_reversal_semantics():
    assert _is_reversal('up', 'down') and _is_reversal('down', 'up')
    assert not _is_reversal(None, 'up')
    assert not _is_reversal('up', 'up')
    assert _is_reversal('s0->s1', 's1->s0')
    assert not _is_reversal('s0->s1', 's0->s1')
    assert not _is_reversal('s0->s1', 's1->s2')
    assert not _is_reversal('heal', 'heal')


# --- actuator seams ---------------------------------------------------------

def test_set_tenant_rate_retargets_bucket_in_place():
    adm = AdmissionController(rate=2.0, burst=10.0)
    bucket = adm.tenant('t').bucket
    adm.set_tenant_rate('t', rate=5.0, burst=4.0)
    assert adm.tenant('t').bucket is bucket      # same object, mid-flight
    assert bucket.rate == 5.0 and bucket.burst == 4.0
    assert bucket.tokens == 4.0                  # clamped to new burst


def _parked_docs(n):
    fleet = DocFleet()
    eng = StorageEngine(fleet)
    handles = init_docs(n, fleet)
    per = [[encode_change(
        {'actor': f'{d:04x}' * 4, 'seq': 1, 'startOp': 1, 'time': 0,
         'message': '', 'deps': [],
         'ops': [{'action': 'set', 'obj': '_root', 'key': 'k',
                  'value': d, 'datatype': 'int', 'pred': []}]})]
        for d in range(n)]
    handles, _ = fleet_backend.apply_changes_docs(handles, per,
                                                  mirror=False)
    return eng, handles


def test_clock_pin_lane_and_pressure_factor():
    eng, handles = _parked_docs(8)
    resident = {'n': 8}
    clock = ClockDemote(eng, budget_bytes=2,
                        source=lambda: resident['n'], batch=8)
    clock.register(handles)
    pinned = handles[:2]
    clock.pin(pinned)
    parked_total = []
    for _ in range(6):
        parked_total.extend(clock.tick())
        resident['n'] = 8 - len(parked_total)
    # every unpinned doc demoted; the pinned two never did, however
    # cold they looked to the hand
    assert len(parked_total) == 6
    assert all(not h.get('frozen') for h in pinned)
    assert clock.pinned_count() == 2
    # pressure_factor scales the effective budget
    assert clock.pressure() == pytest.approx(1.0)        # 2 / 2
    clock.pressure_factor = 0.5
    assert clock.pressure() == pytest.approx(2.0)        # 2 / 1
    # unpin: the exemption lifts and the tightened budget demotes them
    clock.unpin(pinned)
    parked = clock.tick()
    assert len(parked) == 2
    assert clock.pinned_count() == 0


def test_rehome_tenant_guards():
    from automerge_tpu.shard import ShardRouter
    router = ShardRouter(n_shards=2, clock=lambda: 0.0)
    try:
        router.open_tenant('t')
        home = router.tenant_record('t').home
        other = next(s for s in router.ring.shard_ids() if s != home)
        assert not router.rehome_tenant('nope', other)   # unknown tenant
        assert not router.rehome_tenant('t', home)       # no-op move
        assert not router.rehome_tenant('t', 'zz')       # unknown shard
        assert router.rehome_tenant('t', other)
        assert router.tenant_record('t').migrating is not None
        assert not router.rehome_tenant('t', home)       # mid-migration
    finally:
        router.close()


# --- the closed loop --------------------------------------------------------

_FLOODED = {}


def _flooded(mode):
    """One deterministic flooded-service episode per mode, memoized:
    two tenants submitting 20 syncs/tick against a 2/s base rate for
    120 ticks, controller on a 5-tick window."""
    if mode in _FLOODED:
        return _FLOODED[mode]
    ctrl = Controller(mode=mode, window=5)
    svc = DocService(control=ctrl, tenant_rate=2.0, tenant_burst=4.0)
    sessions = [svc.open_session(t) for t in ('alice', 'bob')]
    now = 0.0
    for _ in range(120):
        for s in sessions:
            for _i in range(20):
                try:
                    svc.submit(s, 'sync', None)
                except AutomergeError:
                    pass
        svc.pump(now)
        now += 0.1
    _FLOODED[mode] = (ctrl, svc)
    return ctrl, svc


def test_active_mode_actuates_and_reaches_fixed_point():
    ctrl, svc = _flooded('active')
    log = ctrl.decision_log()
    assert log, 'the controller never acted on a flooded service'
    assert all(e['action'] == 'set_rate' and e['applied'] for e in log)
    # actuated through the live admission seam, capped at 4x base
    for tenant in ('alice', 'bob'):
        assert svc.admission.tenants[tenant].bucket.rate == \
            pytest.approx(8.0)
    g = ctrl.gauges()
    assert g['reversals'] == {}
    assert g['active'][('admission_rate', 'tenant:alice')] == 4.0
    # FIXED POINT: under steady load the tail of the run is >= 5
    # consecutive windows with zero decisions
    last_window = g['last_decision_tick'] // g['window']
    assert g['windows'] - last_window >= 5, g


def test_shadow_mode_decides_identically_and_touches_nothing():
    active_ctrl, _ = _flooded('active')
    shadow_ctrl, shadow_svc = _flooded('shadow')
    # shadow NEVER actuated: rates still at base
    for tenant in ('alice', 'bob'):
        assert shadow_svc.admission.tenants[tenant].bucket.rate == \
            pytest.approx(2.0)
    # ...yet the decision sequence is byte-for-byte the active one
    # (the parity that makes a shadow deployment's graphs trustworthy)
    def strip(ctrl):
        return [(e['tick'], e['policy'], e['action'], e['target'],
                 e['direction'], e['rate'], e['mult'])
                for e in ctrl.decision_log()]
    assert strip(shadow_ctrl) == strip(active_ctrl)
    assert all(e['mode'] == 'shadow' and not e['applied']
               for e in shadow_ctrl.decision_log())


def test_ledger_entries_carry_signal_snapshot_and_traces():
    ctrl, _ = _flooded('active')
    for e in ctrl.decision_log():
        assert e['signals']['tick'] == e['tick']
        assert 'admission' in e['signals']
        assert 'watermark' in e['signals']
        assert e['signals']['tenant']['base_rate'] == pytest.approx(2.0)
        assert isinstance(e['traces'], list)
        assert e['detail']['throttled_frac'] > 0
    # the same decisions landed in the flight recorder ring
    from automerge_tpu.observability import recorder
    flight = [e for e in recorder.recent_events()
              if e['kind'] == 'control_decision']
    assert flight
    assert all('signals' in e and 'traces' in e for e in flight)


def test_dump_round_trips_and_obs_report_renders(tmp_path, capsys):
    ctrl, _ = _flooded('active')
    path = str(tmp_path / 'control_ledger.json')
    report = ctrl.dump_decisions(path)
    assert report['kind'] == 'control_ledger'
    with open(path) as f:
        assert json.load(f)['decisions']         # valid JSON on disk
    import obs_report
    assert obs_report.render_control(path) == 0
    out = capsys.readouterr().out
    assert '# control plane:' in out and 'set_rate' in out
    assert 'signals:' in out
    # --json: stdout is ONE machine-readable object (pipe discipline)
    assert obs_report.render_control(path, json_out=True) == 0
    data = json.loads(capsys.readouterr().out)
    assert data['kind'] == 'control_report'
    assert data['per_policy'].get('admission_rate/set_rate', 0) >= 1


# --- chaos: the self-driving episode ----------------------------------------

@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_kill_one_of_four_settles_under_active_control():
    """The acceptance episode: kill one of four shards under chaos
    links with the controller driving recovery placement (the leg's
    hardcoded rebalance-after-revive is OFF under active control).
    Pinned: zero acked-write loss, byte-identical convergence, <= 2
    reversals per policy, the last decision within 300 ticks of the
    revive, and a decision-free CONVERGENCE HOLD — 10 quiet decision
    windows pumped after the drain with zero further decisions."""
    report = run_shard_leg(
        'control_kill', n_shards=4, tenants=16, requests=600,
        chaos=True, seed=2, kills=((25, 0, 50),),
        control='active', settle_bound=300)
    assert report['ok'], report
    assert report['untyped_escapes'] == 0
    assert report['final_audit']['acked_lost'] == 0
    assert report['final_audit']['replica_mismatches'] == 0
    ctl = report['control']
    # the heal lane did the post-revive placement work
    assert ctl['decisions'].get('shard_balance', 0) >= 1
    assert all(n <= 2 for n in ctl['reversals'].values())
    assert ctl['fixed_point'] is True
    assert ctl['settle_ticks'] is not None
    assert ctl['settle_ticks'] <= 300
