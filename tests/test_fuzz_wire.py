"""Tier-1 smoke dose of the wire fuzzer (tools/fuzz_wire.py): hostile
bytes into every decode entry point must raise only TYPED errors
(AutomergeError subclasses) — no bare IndexError/KeyError/AssertionError,
no hang — and batched entry points must never let a poisoned input
perturb a healthy neighbour. CHAOS-style env scaling: FUZZ_SEEDS /
FUZZ_CASES raise the dose for offline runs (tools/fuzz_wire.py standalone
defaults to ~10x this smoke dose)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

from fuzz_wire import build_corpus, mutate, run_fuzz   # noqa: E402

N_SEEDS = int(os.environ.get('FUZZ_SEEDS', '2'))
N_CASES = int(os.environ.get('FUZZ_CASES', '20'))


def test_fuzz_wire_smoke():
    stats = run_fuzz(n_seeds=N_SEEDS, n_cases=N_CASES)
    assert stats['escaped'] == [], \
        f"untyped errors escaped the decoders: {stats['escaped'][:10]}"
    # the dose genuinely exercised hostile inputs, not just clean echoes
    assert stats['rejected'] > 0
    assert stats['cases'] > N_SEEDS * N_CASES


def test_fuzz_corpus_registered():
    """The corpus size lands in the health roll-up so bench/CI can see
    the fuzz surface."""
    from automerge_tpu.observability import health_counts
    build_corpus()
    counts = health_counts()
    assert counts.get('fuzz_corpus_size', 0) > 0


def test_mutator_determinism():
    """Same seed, same mutants — the fuzz trace must be reproducible."""
    import random
    corpus = build_corpus()
    base = corpus['change'][0]
    a = [mutate(random.Random(7), base) for _ in range(5)]
    b = [mutate(random.Random(7), base) for _ in range(5)]
    # each Random(7) instance replays the identical draw sequence
    assert a[0] == b[0]
