"""Tier-1 smoke dose of the wire fuzzer (tools/fuzz_wire.py): hostile
bytes into every decode entry point must raise only TYPED errors
(AutomergeError subclasses) — no bare IndexError/KeyError/AssertionError,
no hang — and batched entry points must never let a poisoned input
perturb a healthy neighbour. CHAOS-style env scaling: FUZZ_SEEDS /
FUZZ_CASES raise the dose for offline runs (tools/fuzz_wire.py standalone
defaults to ~10x this smoke dose)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

from fuzz_wire import build_corpus, mutate, run_fuzz   # noqa: E402

N_SEEDS = int(os.environ.get('FUZZ_SEEDS', '2'))
N_CASES = int(os.environ.get('FUZZ_CASES', '20'))


def test_fuzz_wire_smoke():
    stats = run_fuzz(n_seeds=N_SEEDS, n_cases=N_CASES)
    assert stats['escaped'] == [], \
        f"untyped errors escaped the decoders: {stats['escaped'][:10]}"
    # the dose genuinely exercised hostile inputs, not just clean echoes
    assert stats['rejected'] > 0
    assert stats['cases'] > N_SEEDS * N_CASES


def test_fuzz_corpus_registered():
    """The corpus size lands in the health roll-up so bench/CI can see
    the fuzz surface."""
    from automerge_tpu.observability import health_counts
    build_corpus()
    counts = health_counts()
    assert counts.get('fuzz_corpus_size', 0) > 0


def test_durability_decoders_in_fuzz_surface():
    """The journal/snapshot/manifest frame decoders are first-class fuzz
    targets with corpus entries of their own (hostile DISK bytes get the
    same typed envelope as hostile wire bytes)."""
    from fuzz_wire import _targets
    corpus = build_corpus()
    assert {'journal', 'snapshot', 'manifest'} <= set(corpus)
    names = {name for name, _fn in _targets()}
    assert {'journal_strict', 'journal_lenient', 'snapshot_frames',
            'manifest'} <= names
    # the lenient scan consumes arbitrary garbage without raising
    import random
    from automerge_tpu.fleet.durability import parse_journal_bytes
    rng = random.Random(3)
    for _ in range(20):
        blob = mutate(rng, corpus['journal'][0])
        records, info = parse_journal_bytes(blob)
        assert isinstance(records, list)


def test_native_column_count_bombs_are_typed():
    """Regression (found by the widened fuzz corpus): RLE/boolean run
    counts are attacker-controlled expansion factors. A boolean run
    near 2^64 used to overflow the int64 capacity check in
    codec.cpp:am_decode_boolean and smash the heap (SIGSEGV); an RLE
    column can declare 2^40+ values in a dozen bytes and turn the
    caller's allocation into a DoS. Both must be TYPED rejections."""
    from automerge_tpu import native
    from automerge_tpu.errors import WireCorruption
    if not native.available():
        pytest.skip('native codec unavailable')

    huge_uleb = b'\xff' * 9 + b'\x01'          # run count with bit 63 set
    with pytest.raises(WireCorruption):
        native.decode_boolean_column(huge_uleb)

    def leb(v):
        out = bytearray()
        while True:
            byte = v & 0x7f
            v >>= 7
            if v:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                return bytes(out)

    bomb = leb(1 << 40) + leb(7)               # "2^40 copies of 7"
    with pytest.raises(WireCorruption):
        native.decode_rle_column(bomb)
    with pytest.raises(WireCorruption):
        native.decode_delta_column(bomb)


def test_mutator_determinism():
    """Same seed, same mutants — the fuzz trace must be reproducible."""
    import random
    corpus = build_corpus()
    base = corpus['change'][0]
    a = [mutate(random.Random(7), base) for _ in range(5)]
    b = [mutate(random.Random(7), base) for _ in range(5)]
    # each Random(7) instance replays the identical draw sequence
    assert a[0] == b[0]
