"""Packaged-artifact smoke test — the reference re-runs its suite against
the webpack bundle (TEST_DIST=1, ref .github/workflows/automerge-ci.yml:24-31
and the src-vs-dist header of every test file, test/test.js:2). The Python
analogue: the library must work imported from a zip archive, where the C++
codec cannot build next to its source — so this doubles as the graceful-
degradation test for native.available() == False (pure-Python codecs,
host-mirror fleet paths)."""

import os
import subprocess
import sys
import zipfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCENARIO = r"""
import sys
zip_path, = sys.argv[1:]
sys.path.insert(0, zip_path)
import automerge_tpu as am
from automerge_tpu import native
assert __import__('automerge_tpu').__file__.startswith(zip_path), \
    'loaded from the wrong place'
assert not native.available(), 'zip import must not see a native codec'

# end-to-end: concurrent edits, merge convergence, save/load, sync round
d1 = am.init('aa' * 4)
d1 = am.change(d1, lambda d: d.update(
    {'rows': [{'n': 1}], 't': am.Text('hi'), 'c': am.Counter(2)}))
d2 = am.merge(am.init('bb' * 4), d1)
d1 = am.change(d1, lambda d: d['c'].increment(3))
d2 = am.change(d2, lambda d: d['rows'][0].update({'n': 9}))
m1, m2 = am.merge(am.clone(d1), d2), am.merge(am.clone(d2), d1)
assert int(m1['c']) == int(m2['c']) == 5
assert m1['rows'][0]['n'] == m2['rows'][0]['n'] == 9
loaded = am.load(am.save(m1))
assert str(loaded['t']) == 'hi'

s1, s2 = am.init_sync_state(), am.init_sync_state()
peer = am.init('cc' * 4)
for _ in range(10):
    s1, msg = am.generate_sync_message(m1, s1)
    if msg is not None:
        peer, s2, _ = am.receive_sync_message(peer, s2, msg)
    s2, msg2 = am.generate_sync_message(peer, s2)
    if msg2 is not None:
        m1, s1, _ = am.receive_sync_message(m1, s1, msg2)
    if msg is None and msg2 is None:
        break
assert peer['rows'][0]['n'] == 9
print('ZIP-PACKAGED OK')
"""


def test_runs_from_zip_without_native_codec(tmp_path):
    zip_path = str(tmp_path / 'automerge_tpu.zip')
    pkg = os.path.join(ROOT, 'automerge_tpu')
    with zipfile.ZipFile(zip_path, 'w') as zf:
        for dirpath, _dirs, files in os.walk(pkg):
            for name in files:
                if name.endswith(('.py', '.cpp')):
                    full = os.path.join(dirpath, name)
                    zf.write(full, os.path.relpath(full, ROOT))
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PYTHONPATH', None)
    scenario = str(tmp_path / 'scenario.py')
    with open(scenario, 'w') as f:
        f.write(_SCENARIO)
    proc = subprocess.run(
        [sys.executable, scenario, zip_path],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr
    assert 'ZIP-PACKAGED OK' in proc.stdout
