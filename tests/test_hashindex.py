"""Device-resident frontier index (fleet/hashindex.py): the
open-addressing table must answer EXACTLY like a Python-set oracle in
both storage modes, survive collision-chain fills and grow-by-migration
byte-identically, and its fleet wiring (commit staging, slot-free space
release, batched sync probes, incoming-change dedup, the quiet-tick
frontier compare) must never disagree with the hash-graph dicts it
replaces.
"""

import hashlib
import os
import random
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu.backend import init_sync_state                # noqa: E402
from automerge_tpu.columnar import decode_change_meta, encode_change  # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet import hashindex as hashindex           # noqa: E402
from automerge_tpu.fleet.backend import (                        # noqa: E402
    DocFleet, apply_changes_docs, free_docs, init_docs)
from automerge_tpu.fleet.hashindex import (                      # noqa: E402
    HashIndex, frontier_compare, hashes_to_rows)
from automerge_tpu.fleet.sync_driver import (                    # noqa: E402
    generate_sync_messages_docs, receive_sync_messages_docs)
from automerge_tpu import native                                 # noqa: E402


def _h(i):
    return hashlib.sha256(f'key-{i}'.encode()).hexdigest()


def _colliding_rows(n, cap, pos=3):
    """n distinct 32-byte keys whose first uint32 word is congruent mod
    `cap` — every one of them lands on probe slot `pos` first, forcing
    an n-long collision chain."""
    rows = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        word = pos + cap * (i + 1)
        rows[i, :4] = np.frombuffer(
            np.uint32(word).tobytes(), dtype=np.uint8)
        rows[i, 4:12] = np.frombuffer(
            hashlib.sha256(str(i).encode()).digest()[:8], dtype=np.uint8)
    return rows


class TestHashIndexCore:
    def test_host_and_device_modes_answer_identically(self):
        traces = []
        rng = random.Random(7)
        for step in range(600):
            traces.append((rng.randrange(4), _h(rng.randrange(120)),
                           rng.random() < 0.5))
        answers = []
        for device_min in (10 ** 9, 1):     # forever-host vs device-now
            ix = HashIndex(capacity=16, device_min=device_min)
            sids = [ix.new_space() for _ in range(4)]
            out = []
            for s, h, is_insert in traces:
                if is_insert:
                    ix.insert(sids[s], [h])
                else:
                    out.append(bool(ix.probe(sids[s], [h])[0]))
            answers.append((ix.mode, out))
        assert answers[0][0] == 'host' and answers[1][0] == 'device'
        assert answers[0][1] == answers[1][1]

    def test_collision_chain_fill_to_load_factor(self):
        ix = HashIndex(capacity=64, device_min=1, load_max=0.6)
        sid = ix.new_space()
        rows = _colliding_rows(38, 64)      # just under 0.6 * 64
        assert ix.insert(sid, rows) == 38
        assert ix.mode == 'device'
        assert ix.probe(sid, rows).all()
        # absent keys sharing the same chain still answer False
        absent = _colliding_rows(10, 64)
        absent[:, 20] ^= 0xFF
        assert not ix.probe(sid, absent).any()
        # idempotent re-insert: no new keys (capacity MAY grow — the
        # sizing is conservative, it cannot know a batch is all dups)
        assert ix.insert(sid, rows) == 0
        assert ix.n_keys == 38 and ix.occupancy == 38
        assert ix.probe(sid, rows).all()

    def test_grow_by_migration_matches_oracle(self):
        rng = random.Random(3)
        ix = HashIndex(capacity=8, device_min=1, load_max=0.5)
        oracle = {}
        sids = [ix.new_space() for _ in range(6)]
        for sid in sids:
            oracle[sid] = set()
        for i in range(800):
            sid = rng.choice(sids)
            h = _h(i)
            ix.insert(sid, [h])
            oracle[sid].add(h)
        assert ix.grows >= 3            # 8 -> ... with load_max 0.5
        # release two spaces, then force one more migration: dead keys
        # must be reclaimed AND stay invisible
        for sid in sids[:2]:
            ix.release_space(sid)
            oracle[sid] = set()
        occ_with_dead = ix.occupancy
        more = [_h(10_000 + i) for i in range(600)]
        ix.insert(sids[2], more)
        oracle[sids[2]].update(more)
        assert ix.occupancy < occ_with_dead + 600   # garbage reclaimed
        for sid in sids:
            universe = [_h(i) for i in range(0, 800, 7)] + more[:50]
            got = ix.probe(sid, universe).tolist()
            want = [h in oracle[sid] for h in universe]
            assert got == want, f'space {sid} diverged from oracle'

    def test_in_batch_duplicates_land_once(self):
        ix = HashIndex(capacity=16, device_min=1)
        sid = ix.new_space()
        batch = [_h(1)] * 5 + [_h(2)] * 3 + [_h(3)]
        assert ix.insert(sid, batch) == 3
        assert ix.n_keys == 3
        assert ix.probe(sid, [_h(1), _h(2), _h(3), _h(4)]).tolist() == \
            [True, True, True, False]

    def test_spaces_are_disjoint_and_dead_spaces_answer_false(self):
        ix = HashIndex(capacity=16, device_min=1)
        a, b = ix.new_space(), ix.new_space()
        ix.insert(a, [_h(1)])
        assert ix.probe(b, [_h(1)]).tolist() == [False]
        ix.release_space(a)
        # dead space: probes mask it even before any migration
        assert ix.probe(a, [_h(1)]).tolist() == [False]
        # unknown space ids never crash, never match
        assert ix.probe(np.array([999], dtype=np.int32),
                        [_h(1)]).tolist() == [False]

    def test_probe_is_one_dispatch_in_device_mode(self):
        ix = HashIndex(capacity=128, device_min=1)
        sid = ix.new_space()
        ix.insert(sid, [_h(i) for i in range(20)])
        n0 = hashindex.dispatch_count()
        ix.probe(sid, [_h(i) for i in range(80)])
        assert hashindex.dispatch_count() - n0 == 1
        n0 = hashindex.dispatch_count()
        ix.insert(sid, [_h(i) for i in range(20)])   # pure duplicates
        assert hashindex.dispatch_count() - n0 == 1

    def test_differential_fuzz_trace(self):
        # the tools/fuzz_wire.py hashindex target's tier-1 dose: random
        # insert/probe traces with space churn, table vs set oracle
        rng = random.Random(0xF00D)
        ix = HashIndex(capacity=8, device_min=64, load_max=0.7)
        oracle, live = {}, []
        for step in range(1500):
            op = rng.random()
            if op < 0.05 or not live:
                sid = ix.new_space()
                oracle[sid] = set()
                live.append(sid)
            elif op < 0.08 and len(live) > 1:
                sid = live.pop(rng.randrange(len(live)))
                ix.release_space(sid)
                oracle[sid] = set()
            elif op < 0.55:
                sid = rng.choice(live)
                hs = [_h(rng.randrange(400))
                      for _ in range(rng.randrange(1, 8))]
                ix.insert(sid, hs)
                oracle[sid].update(hs)
            else:
                sid = rng.choice(live)
                hs = [_h(rng.randrange(400))
                      for _ in range(rng.randrange(1, 8))]
                got = ix.probe(sid, hs).tolist()
                assert got == [h in oracle[sid] for h in hs], f'step {step}'
        assert ix.mode == 'device'   # the trace must cross the threshold


class TestFrontierCompare:
    def test_compare_semantics(self):
        rng = np.random.default_rng(1)
        cur = rng.integers(0, 256, (6, 32)).astype(np.uint8)
        doc = cur.copy()
        doc[2] ^= 1                      # byte-diverged single head
        cur_n = np.array([1, 0, 1, 1, 2, 0], np.int32)
        doc_n = np.array([1, 0, 1, 0, 2, 1], np.int32)
        out = frontier_compare(cur, cur_n, doc, doc_n)
        # [eq-1head, both-empty, diverged, count-mismatch, multi-head
        #  (never quiet on device), count-mismatch]
        assert out.tolist() == [True, True, False, False, False, False]

    def test_compare_is_one_dispatch_and_pads_safely(self):
        cur = np.zeros((3, 32), dtype=np.uint8)
        doc = np.zeros((3, 32), dtype=np.uint8)
        n = np.zeros(3, np.int32)
        n0 = hashindex.dispatch_count()
        out = frontier_compare(cur, n, doc, n)
        assert hashindex.dispatch_count() - n0 == 1
        assert out.shape == (3,) and out.all()


needs_native = pytest.mark.skipif(
    not native.available(), reason='fleet wiring tests ride the turbo path')


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _grow_docs(handles, fleet, rounds, tag='k', start_seq=1):
    """Apply `rounds` turbo chains to every doc; returns (handles,
    per-doc head hash lists per round)."""
    n = len(handles)
    frontiers = [list(h['heads']) for h in handles]
    history = [[] for _ in range(n)]
    for r in range(rounds):
        seq = start_seq + r
        per_doc = []
        for d in range(n):
            buf = _change(f'{d % 99:02x}' * 8, seq, seq,
                          frontiers[d], f'{tag}{r}', d * 100 + r)
            frontiers[d] = [decode_change_meta(buf, True)['hash']]
            history[d].append(frontiers[d][0])
            per_doc.append([buf])
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
    return handles, history


@needs_native
class TestFleetWiring:
    @pytest.mark.parametrize('exact', [False, True],
                             ids=['lww', 'exact'])
    def test_index_matches_graph_dicts_over_churn(self, exact):
        fleet = DocFleet(exact_device=exact)
        handles = init_docs(6, fleet)
        handles, history = _grow_docs(handles, fleet, 5)
        ix = fleet.frontier_index()
        engines = [h['state']._impl for h in handles]
        # registration backfill + staged commits: every applied hash
        # answers True, foreign hashes False — exactly get_change_by_hash
        for d, engine in enumerate(engines):
            probes = history[d] + history[(d + 1) % 6][:2] + [_h(d)]
            flags = ix.probe_pairs([engine] * len(probes), probes)
            want = [engine.get_change_by_hash(h) is not None
                    for h in probes]
            assert flags.tolist() == want
        # more commits AFTER registration ride the staging hook
        handles, history2 = _grow_docs(handles, fleet, 3, tag='m',
                                       start_seq=6)
        engines = [h['state']._impl for h in handles]
        for d, engine in enumerate(engines):
            flags = ix.probe_pairs([engine] * 3, history2[d])
            assert flags.all()

    def test_freed_slots_release_their_space(self):
        fleet = DocFleet()
        handles = init_docs(3, fleet)
        handles, history = _grow_docs(handles, fleet, 3)
        ix = fleet.frontier_index()
        engines = [h['state']._impl for h in handles]
        assert ix.probe_pairs([engines[1]], [history[1][0]]).all()
        victim_slot = engines[1].slot
        free_docs([handles[1]])
        assert victim_slot not in ix._spaces
        # a recycled slot's fresh doc never inherits the old tenant
        fresh = init_docs(1, fleet)
        fresh, fresh_hist = _grow_docs(fresh, fleet, 1)
        engine = fresh[0]['state']._impl
        assert engine.slot == victim_slot
        flags = ix.probe_pairs([engine, engine],
                               [history[1][0], fresh_hist[0][0]])
        assert flags.tolist() == [False, True]

    def test_drop_slots_purges_staged_batches_per_row(self):
        # regression (round-18 review): staged COMMIT batches carry an
        # ndarray of slots per entry — freeing a slot while its rows
        # await flush must neither crash nor drop OTHER slots' rows from
        # the same batch
        fleet = DocFleet()
        handles = init_docs(3, fleet)
        ix = fleet.frontier_index()       # index on BEFORE the commits
        engines = [h['state']._impl for h in handles]
        for e in engines:
            ix.space_of(e)                # register (empty backfill)
        handles, history = _grow_docs(handles, fleet, 2)
        assert ix._staged                 # commit rows await flush
        victim = handles[1]['state']._impl.slot
        free_docs([handles[1]])           # purges victim rows, keeps rest
        e0 = handles[0]['state']._impl
        e2 = handles[2]['state']._impl
        flags = ix.probe_pairs([e0] * len(history[0]) +
                               [e2] * len(history[2]),
                               history[0] + history[2])
        assert flags.all()
        assert victim not in ix._spaces
        assert all((int(s) != victim) for arr, _ in ix._staged
                   for s in arr)

    def test_sync_round_probes_are_batched_dispatches(self):
        fleet = DocFleet()
        handles = init_docs(8, fleet)
        handles, history = _grow_docs(handles, fleet, 4)
        states = [init_sync_state() for _ in handles]
        # a peer that synced at depth 2: lastSync/theirHeads at round 2
        for d, state in enumerate(states):
            state['theirHeads'] = [history[d][1]]
            state['theirHave'] = [{'lastSync': [history[d][1]],
                                   'bloom': b''}]
            state['theirNeed'] = []
        ix = fleet.frontier_index(device_min=1)   # force the device table
        engines = [h['state']._impl for h in handles]
        for e in engines:
            ix.space_of(e)          # warm registration outside the pin
        ix.flush()
        n0 = hashindex.dispatch_count()
        new_states, messages = generate_sync_messages_docs(handles, states)
        used = hashindex.dispatch_count() - n0
        # our_need candidates + theirHave reconciliation ride ONE merged
        # probe — a flat dispatch count regardless of doc count
        assert 1 <= used <= 2, f'{used} index dispatches for the round'
        assert all(m is not None for m in messages)

    def test_reset_branch_agrees_with_host_dicts(self):
        fleet = DocFleet()
        handles = init_docs(2, fleet)
        handles, history = _grow_docs(handles, fleet, 3)
        states = [init_sync_state() for _ in handles]
        # doc 0: peer lastSync we HOLD -> no reset; doc 1: unknown
        # lastSync -> full-resync reset message
        states[0]['theirHeads'] = [history[0][-1]]
        states[0]['theirHave'] = [{'lastSync': [history[0][0]],
                                   'bloom': b''}]
        states[0]['theirNeed'] = []
        states[1]['theirHeads'] = [_h('bogus')]
        states[1]['theirHave'] = [{'lastSync': [_h('bogus')],
                                   'bloom': b''}]
        states[1]['theirNeed'] = []
        _states, messages = generate_sync_messages_docs(handles, states)
        from automerge_tpu.backend.sync import decode_sync_message
        m1 = decode_sync_message(messages[1])
        # the reset frame: empty lastSync, EMPTY bloom, no changes
        assert m1['have'] == [{'lastSync': [], 'bloom': b''}]
        assert m1['changes'] == []
        # the known-lastSync doc runs a normal round: real filter bytes
        # and the resend the peer's empty bloom solicits
        m0 = decode_sync_message(messages[0])
        assert bytes(m0['have'][0]['bloom']) != b''
        # candidates = changes past the peer's lastSync (depth 1 of 3):
        # the empty peer bloom solicits both of them
        assert len(m0['changes']) == 2

    def test_receive_dedups_known_changes_byte_identically(self):
        # a resent known change (Bloom false negative / replayed wire)
        # must be dropped by the batched index probe BEFORE the apply —
        # committed state byte-identical, and the turbo fast path keeps
        # its zero-fallback property instead of demoting to the general
        # gate
        results = {}
        for dedup in (True, False):
            fleet = DocFleet()
            handles = init_docs(2, fleet)
            handles, history = _grow_docs(handles, fleet, 3)
            if dedup:
                fleet.frontier_index()   # index on: dedup engages
            new_b1 = _change('ee' * 16, 1, 50, list(handles[0]['heads']),
                             'fresh', 7)
            from automerge_tpu.backend.sync import encode_sync_message
            msg0 = encode_sync_message({
                'heads': [decode_change_meta(new_b1, True)['hash']],
                'need': [], 'have': [],
                'changes': [  # one known (resent) + one genuinely new
                    handles[0]['state'].get_change_by_hash(history[0][0]),
                    new_b1]})
            states = [init_sync_state() for _ in handles]
            out = receive_sync_messages_docs(
                handles, states, [msg0, None])
            new_handles = out[0]
            results[dedup] = (
                sorted(new_handles[0]['heads']),
                bytes(new_handles[0]['state'].save()),
                fleet.metrics.turbo_commit_fallback_docs,
            )
        assert results[True][0] == results[False][0]
        assert results[True][1] == results[False][1]
        # with dedup the resent change never reaches the gate, so the
        # turbo fast path holds (no per-doc fallback iterations)
        assert results[True][2] == 0

    def test_mid_round_promotion_contained(self):
        # regression (round-18 review): a received change with a
        # fleet-unsupported op (inc delta past int32) PROMOTES its doc
        # to the host engine mid-round, freeing the slot — the
        # post-apply received-heads probe must re-derive from the
        # post-apply backends instead of crashing on the stale engine,
        # and the healthy neighbour's sync state must still advance
        from automerge_tpu.backend.sync import encode_sync_message
        fleet = DocFleet()
        handles = init_docs(2, fleet)
        handles, history = _grow_docs(handles, fleet, 2)
        fleet.frontier_index()
        # warm the index so the probe path is live
        _s, _m = generate_sync_messages_docs(
            handles, [init_sync_state() for _ in handles])
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        # a makeText past the counter-packing window: fleet-unsupported
        # (promotes), host-valid (applies cleanly after promotion)
        big_inc = encode_change({
            'actor': 'dd' * 16, 'seq': 1, 'startOp': CTR_LIMIT + 10,
            'time': 0, 'message': '', 'deps': list(handles[0]['heads']),
            'ops': [{'action': 'makeText', 'obj': '_root', 'key': 'deep',
                     'pred': []}]})
        plain = _change('ee' * 16, 1, 60, list(handles[1]['heads']),
                        'fresh', 5)
        msgs = [encode_sync_message({
                    'heads': [decode_change_meta(buf, True)['hash']],
                    'need': [], 'have': [], 'changes': [buf]})
                for buf in (big_inc, plain)]
        states = [init_sync_state() for _ in handles]
        new_handles, new_states, _p, errors = receive_sync_messages_docs(
            handles, states, msgs, on_error='quarantine')
        assert errors == [None, None]
        assert not new_handles[0]['state'].is_fleet     # promoted
        assert new_handles[1]['state'].is_fleet
        # both docs' sharedHeads advanced to the peer's (known) heads
        for i, buf in enumerate((big_inc, plain)):
            want = [decode_change_meta(buf, True)['hash']]
            assert new_states[i]['sharedHeads'] == want

    def test_frontier_toggle_covers_single_doc_path(self):
        # regression (round-18 review): AUTOMERGE_TPU_FRONTIER_INDEX=0 /
        # set_frontier_enabled(False) must pin the classic path on the
        # single-doc probe too, not just the batched driver
        from automerge_tpu.fleet.hashindex import set_frontier_enabled
        fleet = DocFleet()
        handles = init_docs(1, fleet)
        handles, history = _grow_docs(handles, fleet, 2)
        ix = fleet.frontier_index()
        ix.space_of(handles[0]['state']._impl)
        assert handles[0]['state'].probe_hashes(history[0]) is not None
        prev = set_frontier_enabled(False)
        try:
            assert handles[0]['state'].probe_hashes(history[0]) is None
            from automerge_tpu.fleet.sync_driver import _frontier_of
            assert _frontier_of(handles) is None
        finally:
            set_frontier_enabled(prev)

    def test_single_doc_protocol_rides_warm_index(self):
        from automerge_tpu.backend.sync import known_hash_flags
        fleet = DocFleet()
        handles = init_docs(2, fleet)
        handles, history = _grow_docs(handles, fleet, 3)
        # cold: no index space yet -> dict path (probe_hashes None)
        assert handles[0]['state'].probe_hashes([history[0][0]]) is None
        flags = known_hash_flags(handles[0], [history[0][0], _h(1)])
        assert flags == [True, False]
        # warm the index through the batched driver, then the single-doc
        # helper serves from it — identically
        ix = fleet.frontier_index()
        ix.space_of(handles[0]['state']._impl)
        probed = handles[0]['state'].probe_hashes([history[0][0], _h(1)])
        assert [bool(f) for f in probed] == [True, False]
        assert known_hash_flags(handles[0], [history[0][0], _h(1)]) == \
            [True, False]


@needs_native
class TestLazyHeads:
    def test_commit_fast_path_materializes_no_hex(self):
        fleet = DocFleet()
        handles = init_docs(4, fleet)
        handles, _ = _grow_docs(handles, fleet, 2)
        cols = fleet.doc_cols
        slots = [h['state']._impl.slot for h in handles]
        # the residual-floor pin: after a turbo fast-path commit the hex
        # memo columns are EMPTY — nothing hexed 4 head hashes nobody read
        assert all(cols.head_hex[s] is None for s in slots)
        assert all(cols.head_obj[s] is None for s in slots)
        # first genuine access materializes (and memoizes) exactly then
        heads = handles[0]['state'].heads
        assert len(heads) == 1 and len(heads[0]) == 64
        assert cols.head_hex[slots[0]] == heads[0]

    def test_stale_handle_answers_its_own_generation(self):
        fleet = DocFleet()
        handles = init_docs(1, fleet)
        handles, hist1 = _grow_docs(handles, fleet, 1)
        gen1 = handles[0]
        handles2, hist2 = _grow_docs(handles, fleet, 1, tag='z',
                                     start_seq=2)
        # the stale handle's lazy heads are the row captured at ITS
        # commit — not the engine's current frontier
        assert gen1['heads'] == [hist1[0][0]]
        assert handles2[0]['heads'] == [hist2[0][0]]
        assert gen1['heads'] != handles2[0]['heads']
