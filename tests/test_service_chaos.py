"""Chaos universe for the serving core (ISSUE-7, the PR 2/3 pattern).

The loadgen's chaos client (tools/loadgen.py) drives Zipf-tenant load —
corrupting payloads per transport attempt, violating deadlines,
replaying, flooding, disconnecting — against a live ``DocService``. The
pinned properties, tier-1 smoke dose here and the full 10k-session
matrix under ``-m slow``:

- ZERO UNTYPED ESCAPES: every rejected submit and every failed ticket
  carries an AutomergeError subclass, under shedding included.
- SHED NEVER CORRUPTS: every edit session's doc is byte-identical to an
  unloaded control fleet fed exactly the requests that committed, and
  every sync session's client replica reaches head-equality after a
  fault-free drain.
- DEADLINE ALL-OR-NOTHING: a DeadlineExceeded ticket's changes are
  absent from the doc (covered by the control audit: a partially
  applied request would diff the saves).
- DEVICE-MODE AGNOSTIC: the same deterministic (fake-clock, seeded)
  chaos script over the LWW and exact-device fleets commits the same
  requests and produces byte-identical session saves.
- OVERLOAD ENGAGES THE LADDER: the 2x-overload leg records brownout
  transitions in the health counters while staying convergent.
"""

import os
import sys

import pytest

from automerge_tpu import native

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

from loadgen import (run_leg, run_shard_leg,     # noqa: E402
                     run_standard_legs)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')

SMOKE = dict(sessions=32, tenants=8, requests=320, arrivals_per_tick=32,
             sync_fraction=0.3)


def assert_leg_ok(report):
    assert report['untyped_escapes'] == 0, report
    conv = report['convergence']
    assert conv['edit_mismatches'] == 0, report
    assert conv['sync_converged'] == conv['sync_drained'], report
    for key in report['rejections']:
        assert not key.startswith('UNTYPED'), report
    # the SLO audit (ISSUE-10): the registry's per-tenant outcome
    # tallies match the client-observed typed outcomes EXACTLY — a
    # double count or a missed reject under the chaos/quarantine storm
    # fails the leg
    audit = report['slo_audit']
    assert audit is not None and 'mismatches' in audit, report
    assert audit['mismatches'] == [], audit
    assert audit['pairs_checked'] > 0, audit


def test_service_chaos_smoke():
    report = run_leg('chaos', chaos=True, seed=11, tick_dt=0.02,
                     **SMOKE)
    assert_leg_ok(report)
    assert report['chaos_corrupted'] > 0          # the chaos actually bit
    assert report['completed_ok'] > 0


def test_service_overload_brownout_smoke():
    report = run_leg('overload', overload=True, seed=12, tick_dt=0.02,
                     **SMOKE)
    assert_leg_ok(report)
    # typed pushback happened AND the ladder engaged
    assert sum(report['rejections'].values()) > 0
    assert report['brownout_transitions'] > 0


def test_service_chaos_identical_across_device_modes():
    """The same seeded, fake-clock chaos script over both fleet modes:
    identical committed sets, byte-identical session saves."""
    saves = {}
    for mode in (False, True):
        report = run_leg('xmode', chaos=True, seed=13, tick_dt=0.02,
                         exact_device=mode, collect_saves=True,
                         sessions=24, tenants=6, requests=192,
                         arrivals_per_tick=24, sync_fraction=0.25)
        assert_leg_ok(report)
        saves[mode] = report['session_saves']
        assert report['session_saves'], 'empty save map'
    assert saves[False] == saves[True], \
        'device modes diverged under the identical chaos script'


def assert_shard_leg_ok(report):
    assert report['untyped_escapes'] == 0, report
    assert report['drained'], report
    for audit in report['audits']:
        # ZERO acknowledged-write loss and byte-identical home/replica
        # convergence at EVERY settle point, not just the end
        assert audit['acked_lost'] == 0, audit
        assert audit['replica_mismatches'] == 0, audit
        assert audit['replica_pairs'] > 0, audit
    assert report['ok'], report


def test_shard_kill_one_of_four_smoke():
    """The acceptance leg (ISSUE-11): kill one of 4 shards mid-workload
    under chaos links — zero acked-write loss, the dead shard's tenants
    served by their replicas within the lease window, post-quiet
    byte-identical convergence across the surviving shards."""
    report = run_shard_leg('kill_one_of_four', n_shards=4, tenants=12,
                           requests=240, arrivals_per_tick=8,
                           chaos=True, seed=5, kills=((12, 1, 40),),
                           mttr_bound=12)
    assert_shard_leg_ok(report)
    assert report['failovers'] == 1
    assert report['mttr_ticks'][0] is not None
    assert report['mttr_ticks'][0] <= report['lease_ticks'] + 9
    assert report['completed_ok'] > 0


def test_shard_kill_revive_cycles_same_shard():
    """The satellite: kill and revive ONE shard 3x mid-workload (with a
    rebalance pulling its tenants home each revive), asserting the
    byte-identical convergence audit after every round."""
    report = run_shard_leg(
        'kill_revive_3x', n_shards=3, tenants=9, requests=270,
        arrivals_per_tick=6, chaos=True, seed=7,
        kills=((10, 0, 30), (60, 0, 80), (110, 0, 130)))
    assert_shard_leg_ok(report)
    assert report['kills'] == 3
    # three settle audits (one per revive) plus the final one, each
    # byte-identical — checked in assert_shard_leg_ok
    assert len(report['audits']) == 4
    assert report['shard_health_delta'].get('shard_revives', 0) == 3


@pytest.mark.slow
def test_shard_kill_matrix_full():
    """Scaled kill schedule: two different victims plus a repeat kill,
    both device modes."""
    for mode in (False, True):
        report = run_shard_leg(
            'kill_matrix', n_shards=4, tenants=32, requests=1600,
            arrivals_per_tick=16, chaos=True, seed=19,
            exact_device=mode,
            kills=((20, 1, 60), (120, 3, 160), (220, 1, 260)))
        assert_shard_leg_ok(report)
        assert report['failovers'] == 3


@pytest.mark.slow
def test_service_full_matrix_10k():
    """The acceptance run: 10k concurrent sessions through all three
    legs, both device modes."""
    for mode in (False, True):
        for report in run_standard_legs(sessions=10_000, tenants=256,
                                        requests=20_000, seed=0,
                                        exact_device=mode):
            assert_leg_ok(report)
            if report['leg'] == 'overload':
                assert report['brownout_transitions'] > 0
