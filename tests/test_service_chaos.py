"""Chaos universe for the serving core (ISSUE-7, the PR 2/3 pattern).

The loadgen's chaos client (tools/loadgen.py) drives Zipf-tenant load —
corrupting payloads per transport attempt, violating deadlines,
replaying, flooding, disconnecting — against a live ``DocService``. The
pinned properties, tier-1 smoke dose here and the full 10k-session
matrix under ``-m slow``:

- ZERO UNTYPED ESCAPES: every rejected submit and every failed ticket
  carries an AutomergeError subclass, under shedding included.
- SHED NEVER CORRUPTS: every edit session's doc is byte-identical to an
  unloaded control fleet fed exactly the requests that committed, and
  every sync session's client replica reaches head-equality after a
  fault-free drain.
- DEADLINE ALL-OR-NOTHING: a DeadlineExceeded ticket's changes are
  absent from the doc (covered by the control audit: a partially
  applied request would diff the saves).
- DEVICE-MODE AGNOSTIC: the same deterministic (fake-clock, seeded)
  chaos script over the LWW and exact-device fleets commits the same
  requests and produces byte-identical session saves.
- OVERLOAD ENGAGES THE LADDER: the 2x-overload leg records brownout
  transitions in the health counters while staying convergent.
"""

import os
import sys

import pytest

from automerge_tpu import native

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))

from loadgen import run_leg, run_standard_legs   # noqa: E402

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native codec unavailable')

SMOKE = dict(sessions=32, tenants=8, requests=320, arrivals_per_tick=32,
             sync_fraction=0.3)


def assert_leg_ok(report):
    assert report['untyped_escapes'] == 0, report
    conv = report['convergence']
    assert conv['edit_mismatches'] == 0, report
    assert conv['sync_converged'] == conv['sync_drained'], report
    for key in report['rejections']:
        assert not key.startswith('UNTYPED'), report
    # the SLO audit (ISSUE-10): the registry's per-tenant outcome
    # tallies match the client-observed typed outcomes EXACTLY — a
    # double count or a missed reject under the chaos/quarantine storm
    # fails the leg
    audit = report['slo_audit']
    assert audit is not None and 'mismatches' in audit, report
    assert audit['mismatches'] == [], audit
    assert audit['pairs_checked'] > 0, audit


def test_service_chaos_smoke():
    report = run_leg('chaos', chaos=True, seed=11, tick_dt=0.02,
                     **SMOKE)
    assert_leg_ok(report)
    assert report['chaos_corrupted'] > 0          # the chaos actually bit
    assert report['completed_ok'] > 0


def test_service_overload_brownout_smoke():
    report = run_leg('overload', overload=True, seed=12, tick_dt=0.02,
                     **SMOKE)
    assert_leg_ok(report)
    # typed pushback happened AND the ladder engaged
    assert sum(report['rejections'].values()) > 0
    assert report['brownout_transitions'] > 0


def test_service_chaos_identical_across_device_modes():
    """The same seeded, fake-clock chaos script over both fleet modes:
    identical committed sets, byte-identical session saves."""
    saves = {}
    for mode in (False, True):
        report = run_leg('xmode', chaos=True, seed=13, tick_dt=0.02,
                         exact_device=mode, collect_saves=True,
                         sessions=24, tenants=6, requests=192,
                         arrivals_per_tick=24, sync_fraction=0.25)
        assert_leg_ok(report)
        saves[mode] = report['session_saves']
        assert report['session_saves'], 'empty save map'
    assert saves[False] == saves[True], \
        'device modes diverged under the identical chaos script'


@pytest.mark.slow
def test_service_full_matrix_10k():
    """The acceptance run: 10k concurrent sessions through all three
    legs, both device modes."""
    for mode in (False, True):
        for report in run_standard_legs(sessions=10_000, tenants=256,
                                        requests=20_000, seed=0,
                                        exact_device=mode):
            assert_leg_ok(report)
            if report['leg'] == 'overload':
                assert report['brownout_transitions'] > 0
