"""Proxy API conformance tests: the mutable document objects handed to change
callbacks behave like ordinary Python mappings/sequences (ported semantics of
reference test/proxies_test.js, whose ES6 Proxy list supports the full JS
Array API; here the Python MutableMapping/MutableSequence protocols)."""

import json

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend


class TestRootObject:
    def test_fixed_object_id(self):
        def check(doc):
            assert Frontend.get_object_id(doc._target()) == '_root'
        am.change(am.init(), check)

    def test_knows_actor_id(self):
        def check(doc):
            actor = am.get_actor_id(doc._target())
            assert isinstance(actor, str) and len(actor) > 0
        # a raw init doc also reports its actor
        assert am.get_actor_id(am.init('01234567')) == '01234567'

    def test_keys_as_properties_and_items(self):
        def check(doc):
            doc.magpies = 42
            assert doc.magpies == 42
            assert doc['magpies'] == 42
        am.change(am.init(), check)

    def test_unknown_property(self):
        def check(doc):
            with pytest.raises(AttributeError):
                doc.sparrows
            with pytest.raises(KeyError):
                doc['sparrows']
            assert doc.get('sparrows') is None
        am.change(am.init(), check)

    def test_in_operator_and_len(self):
        def check(doc):
            doc['key1'] = 'value1'
            doc['key2'] = 'value2'
            assert 'key1' in doc
            assert 'key3' not in doc
            assert len(doc) == 2
            assert sorted(doc.keys()) == ['key1', 'key2']
        am.change(am.init(), check)

    def test_bulk_assignment(self):
        # Python analogue of Object.assign()
        def check(doc):
            doc.update({'two': 2, 'three': 3})
        doc = am.change(am.init(), check)
        assert dict(doc) == {'two': 2, 'three': 3}

    def test_json_round_trip(self):
        def check(doc):
            doc['nested'] = {'a': [1, 2], 'b': 'x'}
        doc = am.change(am.init(), check)
        assert json.loads(json.dumps(doc.to_py())) == \
            {'nested': {'a': [1, 2], 'b': 'x'}}

    def test_access_by_object_id(self):
        doc = am.change(am.init(), lambda d: d.update({'deep': {'key': 'v'}}))
        obj_id = Frontend.get_object_id(doc['deep'])
        assert am.Frontend.get_object_by_id(doc, obj_id)['key'] == 'v'


def list_doc():
    return am.change(am.init(), lambda d: d.update(
        {'noble': ['silver', 'gold', 'platinum']}))


class TestListObject:
    def test_looks_like_a_sequence(self):
        def check(doc):
            lst = doc['noble']
            assert len(lst) == 3
            assert list(lst) == ['silver', 'gold', 'platinum']
            assert lst == ['silver', 'gold', 'platinum']
        am.change(list_doc(), check)

    def test_fetch_by_index(self):
        def check(doc):
            lst = doc['noble']
            assert lst[0] == 'silver'
            assert lst[-1] == 'platinum'
            assert lst[0:2] == ['silver', 'gold']
            with pytest.raises(IndexError):
                lst[10]
        am.change(list_doc(), check)

    def test_iteration_and_membership(self):
        def check(doc):
            lst = doc['noble']
            assert 'gold' in list(lst)
            assert [x for x in lst] == ['silver', 'gold', 'platinum']
            assert lst.index('gold') == 1
            assert lst.count('gold') == 1
        am.change(list_doc(), check)

    def test_readonly_style_operations(self):
        def check(doc):
            lst = doc['noble']
            # join / filter / map analogues
            assert ','.join(lst) == 'silver,gold,platinum'
            assert [x for x in lst if x.endswith('um')] == ['platinum']
            assert [x.upper() for x in lst] == ['SILVER', 'GOLD', 'PLATINUM']
            assert any(x == 'gold' for x in lst)
            assert not all(x == 'gold' for x in lst)
        am.change(list_doc(), check)

    def test_mutation_methods(self):
        doc = list_doc()

        def m1(d):
            d['noble'].append('copernicium')   # push
            d['noble'].insert(0, 'hydrogen')   # unshift
        doc = am.change(doc, m1)
        assert doc['noble'] == ['hydrogen', 'silver', 'gold', 'platinum',
                                'copernicium']

        def m2(d):
            assert d['noble'].pop() == 'copernicium'
            assert d['noble'].pop(0) == 'hydrogen'
        doc = am.change(doc, m2)
        assert doc['noble'] == ['silver', 'gold', 'platinum']

    def test_fill(self):
        doc = am.change(am.init(), lambda d: d.update({'xs': [1, 2, 3, 4]}))
        doc = am.change(doc, lambda d: d['xs'].fill(0, 1, 3))
        assert doc['xs'] == [1, 0, 0, 4]
        doc = am.change(doc, lambda d: d['xs'].fill(9))
        assert doc['xs'] == [9, 9, 9, 9]

    def test_insert_at_delete_at(self):
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].insert_at(1, 'a', 'b'))
        assert doc['noble'] == ['silver', 'a', 'b', 'gold', 'platinum']
        doc = am.change(doc, lambda d: d['noble'].delete_at(1, 2))
        assert doc['noble'] == ['silver', 'gold', 'platinum']

    def test_slice_assignment(self):
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].__setitem__(
            slice(0, 2), ['x']))
        assert doc['noble'] == ['x', 'platinum']

    def test_del_item_and_slice(self):
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].__delitem__(0))
        assert doc['noble'] == ['gold', 'platinum']
        doc = am.change(doc, lambda d: d['noble'].__delitem__(slice(0, 2)))
        assert doc['noble'] == []

    def test_length_extension_with_nulls(self):
        # JS `list.length = 5`-style extension: assigning past the end pads
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].__setitem__(4, 'iridium'))
        assert doc['noble'] == ['silver', 'gold', 'platinum', None, 'iridium']

    def test_nested_object_mutation_through_list(self):
        doc = am.change(am.init(), lambda d: d.update(
            {'rows': [{'n': 1}, {'n': 2}]}))

        def bump(d):
            for row in d['rows']:
                row['n'] = row['n'] + 10
        doc = am.change(doc, bump)
        assert doc['rows'] == [{'n': 11}, {'n': 12}]

    def test_extend_and_iadd(self):
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].extend(['pd', 'rh']))
        assert doc['noble'] == ['silver', 'gold', 'platinum', 'pd', 'rh']

    def test_remove_by_value(self):
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].remove('gold'))
        assert doc['noble'] == ['silver', 'platinum']

    def test_reverse_rejected_or_correct(self):
        # MutableSequence.reverse mutates in place via __setitem__
        doc = list_doc()
        doc = am.change(doc, lambda d: d['noble'].reverse())
        assert doc['noble'] == ['platinum', 'gold', 'silver']


def num_doc():
    """ref proxies_test.js:97-105 fixture: list [1,2,3] + empty + objects."""
    return am.change(am.init(), lambda d: d.update(
        {'list': [1, 2, 3], 'empty': [],
         'listObjects': [{'id': 'first'}, {'id': 'second'}]}))


class TestListReadOnlyMethods:
    """Pythonic equivalents of the reference's JS Array read-only method
    suite (ref proxies_test.js:181-392)."""

    def test_concat(self):
        def check(d):
            assert list(d['list']) + [4] == [1, 2, 3, 4]
            assert list(d['list']) + [4, 5, 6] == [1, 2, 3, 4, 5, 6]
        am.change(num_doc(), check)

    def test_entries(self):
        def check(d):
            assert list(enumerate(d['list'])) == [(0, 1), (1, 2), (2, 3)]
        am.change(num_doc(), check)

    def test_every(self):
        def check(d):
            assert all(x > 0 for x in d['list'])
            assert not all(x > 2 for x in d['list'])
        am.change(num_doc(), check)

    def test_filter(self):
        def check(d):
            assert [x for x in d['list'] if False] == []
            assert [x for x in d['list'] if x % 2 == 1] == [1, 3]
            assert [x for x in d['list'] if True] == [1, 2, 3]
        am.change(num_doc(), check)

    def test_find(self):
        def check(d):
            assert next((x for x in d['list'] if x >= 2), None) == 2
            assert next((x for x in d['list'] if x >= 4), None) is None
        am.change(num_doc(), check)

    def test_find_index(self):
        def check(d):
            assert next((i for i, x in enumerate(d['list']) if x >= 2),
                        -1) == 1
            assert next((i for i, x in enumerate(d['list']) if x >= 4),
                        -1) == -1
        am.change(num_doc(), check)

    def test_for_each(self):
        def check(d):
            copy = []
            for x in d['list']:
                copy.append(x)
            assert copy == [1, 2, 3]
        am.change(num_doc(), check)

    def test_includes(self):
        def check(d):
            assert 3 in list(d['list'])
            assert 0 not in list(d['list'])
        am.change(num_doc(), check)

    def test_index_of(self):
        def check(d):
            assert d['list'].index(2) == 1
            with pytest.raises(ValueError):
                d['list'].index(4)
        am.change(num_doc(), check)

    def test_index_of_with_objects(self):
        def check(d):
            objs = d['listObjects']
            assert [o['id'] for o in objs].index('second') == 1
        am.change(num_doc(), check)

    def test_join(self):
        def check(d):
            assert ','.join(str(x) for x in d['list']) == '1,2,3'
            assert ' '.join(str(x) for x in d['list']) == '1 2 3'
        am.change(num_doc(), check)

    def test_keys(self):
        def check(d):
            assert list(range(len(d['list']))) == [0, 1, 2]
        am.change(num_doc(), check)

    def test_last_index_of(self):
        doc = am.change(am.init(), lambda d: d.update({'list': [1, 2, 3, 2]}))

        def check(d):
            lst = list(d['list'])
            assert len(lst) - 1 - lst[::-1].index(2) == 3
        am.change(doc, check)

    def test_map(self):
        def check(d):
            assert [x * 2 for x in d['list']] == [2, 4, 6]
        am.change(num_doc(), check)

    def test_reduce(self):
        import functools
        def check(d):
            assert functools.reduce(lambda a, x: a + x, d['list'], 0) == 6
        am.change(num_doc(), check)

    def test_reduce_right(self):
        import functools
        def check(d):
            assert functools.reduce(lambda a, x: a + str(x),
                                    reversed(list(d['list'])), '') == '321'
        am.change(num_doc(), check)

    def test_slice(self):
        def check(d):
            assert d['list'][1:] == [2, 3]
            assert d['list'][:2] == [1, 2]
            assert d['list'][1:2] == [2]
        am.change(num_doc(), check)

    def test_some(self):
        def check(d):
            assert any(x == 2 for x in d['list'])
            assert not any(x == 9 for x in d['list'])
        am.change(num_doc(), check)

    def test_to_string(self):
        def check(d):
            assert str(list(d['list'])) == '[1, 2, 3]'
        am.change(num_doc(), check)

    def test_values(self):
        def check(d):
            assert list(iter(d['list'])) == [1, 2, 3]
        am.change(num_doc(), check)

    def test_mutation_of_objects_from_iteration(self):
        doc = num_doc()

        def mutate(d):
            for obj in d['listObjects']:
                if obj['id'] == 'first':
                    obj['id'] = 'FIRST'
        doc = am.change(doc, mutate)
        assert doc['listObjects'][0]['id'] == 'FIRST'

    def test_mutation_of_objects_from_readonly_lookup(self):
        doc = num_doc()

        def mutate(d):
            found = next(o for o in d['listObjects'] if o['id'] == 'second')
            found['id'] = 'SECOND'
        doc = am.change(doc, mutate)
        assert doc['listObjects'][1]['id'] == 'SECOND'


class TestListMutationMethods:
    """ref proxies_test.js:394-456"""

    def test_pop(self):
        doc = num_doc()

        def m(d):
            assert d['list'].pop() == 3
            assert d['list'].pop() == 2
            assert d['list'].pop() == 1
            with pytest.raises(IndexError):
                d['list'].pop()
        doc = am.change(doc, m)
        assert list(doc['list']) == []

    def test_push(self):
        doc = am.change(am.init(), lambda d: d.update({'noodles': []}))
        doc = am.change(doc, lambda d: d['noodles'].append('udon', 'soba'))
        doc = am.change(doc, lambda d: d['noodles'].append('ramen'))
        assert list(doc['noodles']) == ['udon', 'soba', 'ramen']
        assert len(doc['noodles']) == 3

    def test_shift(self):
        doc = num_doc()

        def m(d):
            assert d['list'].pop(0) == 1
            assert d['list'].pop(0) == 2
            assert d['list'].pop(0) == 3
            with pytest.raises(IndexError):
                d['list'].pop(0)
        doc = am.change(doc, m)
        assert list(doc['list']) == []

    def test_splice(self):
        doc = num_doc()
        doc = am.change(doc, lambda d: d['list'].delete_at(1, 2))
        assert list(doc['list']) == [1]
        doc = am.change(doc, lambda d: d['list'].insert_at(1, 'a', 'b'))
        assert list(doc['list']) == [1, 'a', 'b']

    def test_unshift(self):
        doc = am.change(am.init(), lambda d: d.update({'noodles': []}))
        doc = am.change(doc, lambda d: d['noodles'].insert_at(0, 'soba'))
        doc = am.change(doc, lambda d: d['noodles'].insert_at(0, 'udon'))
        assert list(doc['noodles']) == ['udon', 'soba']
        assert len(doc['noodles']) == 2
