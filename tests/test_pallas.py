"""Differential tests for the fused Pallas merge kernel against the jnp
reference path (fleet/apply.py): identical workloads through both, comparing
all real key columns (the jnp path's scratch column is excluded — it absorbs
masked scatter lanes by design and holds garbage)."""

import numpy as np
import pytest

import jax

from automerge_tpu.fleet import FleetState, OpBatch, apply_op_batch
from automerge_tpu.fleet.pallas_merge import pallas_apply_op_batch
from automerge_tpu.fleet.tensor_doc import ACTOR_BITS


def random_batch(rng, n_docs, n_keys, ops_per_doc, ctr0=1):
    shape = (n_docs, ops_per_doc)
    key_id = rng.integers(0, n_keys, shape, dtype=np.int32)
    actor = rng.integers(0, 4, shape, dtype=np.int32)
    ctrs = ctr0 + np.broadcast_to(np.arange(ops_per_doc, dtype=np.int32), shape)
    packed = (ctrs.astype(np.int32) << ACTOR_BITS) | actor
    value = rng.integers(-50, 1000, shape, dtype=np.int32)
    is_set = rng.random(shape) < 0.7
    valid = rng.random(shape) < 0.9
    return OpBatch(key_id, packed, value, is_set, ~is_set, valid)


def assert_states_match(a, b, n_keys):
    for name in ('winners', 'values', 'counters'):
        got = np.asarray(getattr(a, name))[:, :n_keys]
        want = np.asarray(getattr(b, name))[:, :n_keys]
        np.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize('variant', ['dense', 'loop'])
@pytest.mark.parametrize('n_docs,n_keys,p', [
    (8, 17, 12),      # everything unaligned -> exercises padding
    (128, 127, 32),   # exact doc tile
    (200, 300, 16),   # multiple key tiles
    (16, 40, 200),    # multi-chunk op axis (> OP_CHUNK=128): chunk carry
    (8, 130, 300),    # multi-chunk AND multiple key tiles
])
def test_matches_jnp_path(n_docs, n_keys, p, variant):
    rng = np.random.default_rng(n_docs + n_keys)
    state = FleetState.empty(n_docs, n_keys)
    ops = random_batch(rng, n_docs, n_keys, p)
    want, want_stats = apply_op_batch(state, ops)
    got, got_stats = pallas_apply_op_batch(state, ops, interpret=True,
                                           variant=variant)
    assert int(got_stats) == int(want_stats)
    assert_states_match(got, want, n_keys)


def test_duplicate_delivery_is_idempotent():
    """Redundant re-delivery of the same op (same packed opId, same value —
    the sync path can re-send) must select the winner value once, not sum it;
    both engines must agree. Spread across op chunks to exercise the
    cross-chunk take-if-greater carry."""
    rng = np.random.default_rng(42)
    n_docs, n_keys, p = 12, 23, 160   # p > OP_CHUNK: dups straddle chunks
    ops = random_batch(rng, n_docs, n_keys, p)
    cols = np.stack([ops.key_id, ops.packed, ops.value,
                     ops.is_set.astype(np.int32), ops.is_inc.astype(np.int32),
                     ops.valid.astype(np.int32)])
    src = rng.integers(0, p // 2, 30)
    dst = p - 1 - rng.permutation(30)   # mirror lanes into the other chunk
    cols[:, :, dst] = cols[:, :, src]
    dup = OpBatch(cols[0], cols[1], cols[2], cols[3] != 0, cols[4] != 0,
                  cols[5] != 0)
    state = FleetState.empty(n_docs, n_keys)
    want, _ = apply_op_batch(state, dup)
    got, _ = pallas_apply_op_batch(state, dup, interpret=True)
    assert_states_match(got, want, n_keys)


def test_multiple_rounds_carry_state():
    rng = np.random.default_rng(7)
    n_docs, n_keys = 16, 33
    state_a = FleetState.empty(n_docs, n_keys)
    state_b = FleetState.empty(n_docs, n_keys)
    for r in range(3):
        ops = random_batch(rng, n_docs, n_keys, 8, ctr0=1 + 8 * r)
        state_a, _ = apply_op_batch(state_a, ops)
        state_b, _ = pallas_apply_op_batch(state_b, ops, interpret=True)
    assert_states_match(state_b, state_a, n_keys)


def test_counter_accumulation_and_overwrite():
    """Counters add across batches; a later set overwrites an earlier one."""
    n_docs, n_keys = 4, 8
    key = np.zeros((n_docs, 2), dtype=np.int32)
    packed = np.tile(np.array([[1 << ACTOR_BITS, 2 << ACTOR_BITS]],
                              dtype=np.int32), (n_docs, 1))
    value = np.tile(np.array([[5, 7]], dtype=np.int32), (n_docs, 1))
    is_set = np.tile(np.array([[True, False]]), (n_docs, 1))
    ops = OpBatch(key, packed, value, is_set, ~is_set,
                  np.ones((n_docs, 2), dtype=bool))
    state = FleetState.empty(n_docs, n_keys)
    state, _ = pallas_apply_op_batch(state, ops, interpret=True)
    assert np.asarray(state.values)[0, 0] == 5
    assert np.asarray(state.counters)[0, 0] == 7
    # Second round: overwrite with a later opId
    packed2 = np.full((n_docs, 1), 9 << ACTOR_BITS, dtype=np.int32)
    ops2 = OpBatch(np.zeros((n_docs, 1), np.int32), packed2,
                   np.full((n_docs, 1), 42, np.int32),
                   np.ones((n_docs, 1), bool), np.zeros((n_docs, 1), bool),
                   np.ones((n_docs, 1), bool))
    state, _ = pallas_apply_op_batch(state, ops2, interpret=True)
    assert np.asarray(state.values)[0, 0] == 42
    assert np.asarray(state.winners)[0, 0] == 9 << ACTOR_BITS
    # The overwritten counter's accumulator resets with its op
    assert np.asarray(state.counters)[0, 0] == 0


def test_counter_reset_parity_with_jnp():
    """Winner-change counter reset must match between both kernels,
    including the keep-base case (re-delivered standing winner)."""
    n_docs, n_keys = 4, 8
    base = FleetState.empty(n_docs, n_keys)
    mk = lambda key, packed, value, is_set: OpBatch(
        np.full((n_docs, 1), key, np.int32),
        np.full((n_docs, 1), packed, np.int32),
        np.full((n_docs, 1), value, np.int32),
        np.full((n_docs, 1), is_set, bool),
        np.full((n_docs, 1), not is_set, bool),
        np.ones((n_docs, 1), bool))
    rounds = [
        mk(0, 1 << ACTOR_BITS, 10, True),    # counter base
        mk(0, 2 << ACTOR_BITS, -4, False),   # negative inc
        mk(0, 1 << ACTOR_BITS, 10, True),    # duplicate delivery: keep base
        mk(0, 9 << ACTOR_BITS, 100, True),   # overwrite: reset
        mk(0, 11 << ACTOR_BITS, 2, False),   # inc on the new winner
    ]
    a = b = base
    for ops in rounds:
        a, _ = apply_op_batch(a, ops)
        b, _ = pallas_apply_op_batch(b, ops, interpret=True)
        assert_states_match(b, a, n_keys)
    assert np.asarray(a.counters)[0, 0] == 2
    assert np.asarray(a.values)[0, 0] == 100


class TestMosaicAOT:
    """Round-4 VERDICT item 2: prove Mosaic ACCEPTS both kernel variants
    without TPU hardware, by AOT-lowering against a v5e topology
    (jax.experimental.topologies + libtpu's PJRT topology description).
    Interpret-mode runs exercise none of what actually fails on TPU
    (lowering rejections, unsupported primitives, block-shape rules);
    this compiles the real Mosaic pipeline on the CPU-only CI box."""

    # On images without a working libtpu the PJRT topology client burns
    # ~7 minutes of connection retries in SETUP before the compile fails
    # anyway (433s of tier-1's 870s budget, measured round 21 on a
    # 1-core box).  A deadline-bounded child probe decides cheaply
    # whether this environment can produce the topology at all; an
    # environment that can't inside the deadline was never going to
    # AOT-compile either, so the family skips instead of eating the
    # suite's timeout.  Working-toolchain boxes pass the probe in
    # seconds and run the real compile unchanged.
    PROBE_DEADLINE_S = 120

    @pytest.fixture(scope='class')
    def v5e_topology(self):
        import os
        import subprocess
        import sys
        os.environ.setdefault('TPU_ACCELERATOR_TYPE', 'v5litepod-8')
        os.environ.setdefault('TPU_WORKER_HOSTNAMES', 'localhost')
        probe = ("from jax.experimental import topologies; "
                 "topologies.get_topology_desc('v5e:2x2', 'tpu')")
        try:
            proc = subprocess.run(
                [sys.executable, '-c', probe], env=dict(os.environ),
                timeout=self.PROBE_DEADLINE_S, capture_output=True)
        except subprocess.TimeoutExpired:
            pytest.skip('AOT TPU topology probe exceeded '
                        f'{self.PROBE_DEADLINE_S}s deadline — no working '
                        'libtpu in this environment')
        if proc.returncode != 0:
            tail = proc.stderr.decode('utf-8', 'replace').strip()
            pytest.skip('AOT TPU topology unavailable: '
                        f'{tail.splitlines()[-1] if tail else proc.returncode}')
        try:
            from jax.experimental import topologies
            return topologies.get_topology_desc('v5e:2x2', 'tpu')
        except Exception as exc:   # no libtpu in this environment
            pytest.skip(f'AOT TPU topology unavailable: {exc}')

    @pytest.mark.parametrize('variant', ['dense', 'loop'])
    def test_mosaic_compiles_variant(self, v5e_topology, variant):
        import jax.numpy as jnp
        import jax.tree_util as tu
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        n_docs, n_keys, p = 256, 256, 256   # multi-tile on every grid axis
        state = FleetState(*(jnp.zeros((n_docs, n_keys), jnp.int32)
                             for _ in range(3)))
        ops = OpBatch(*(jnp.zeros((n_docs, p), jnp.int32) for _ in range(3)),
                      *(jnp.zeros((n_docs, p), bool) for _ in range(3)))
        sh = NamedSharding(
            Mesh(np.array(v5e_topology.devices[:1]).reshape(1), ('d',)), P())
        fn = jax.jit(lambda s, o: pallas_apply_op_batch(s, o,
                                                        variant=variant))
        absargs = tu.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
            (state, ops))
        compiled = fn.lower(*absargs).compile()
        # A compiled executable with a memory analysis is the proof; the
        # kernel's state tiles live in VMEM scratch (temp reports 0 for
        # aliased in/out buffers)
        assert compiled.memory_analysis() is not None
