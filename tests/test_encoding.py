"""Codec conformance tests, ported from reference test/encoding_test.js.

Exact-byte assertions guarantee wire compatibility of the LEB128/RLE/
delta/boolean column codecs.
"""

import pytest

from automerge_tpu.encoding import (
    Encoder, Decoder, RLEEncoder, RLEDecoder, DeltaEncoder, DeltaDecoder,
    BooleanEncoder, BooleanDecoder,
)

MAX_SAFE = 2 ** 53 - 1
MIN_SAFE = -(2 ** 53 - 1)


def check_encoded(encoder, expected):
    assert encoder.buffer == bytes(expected)


def enc(method, value):
    e = Encoder()
    getattr(e, method)(value)
    return e


class TestLeb128_32bit:
    CASES_UINT = [
        (0, [0]), (1, [1]), (0x42, [0x42]), (0x7f, [0x7f]), (0x80, [0x80, 0x01]),
        (0xff, [0xff, 0x01]), (0x1234, [0xb4, 0x24]), (0x3fff, [0xff, 0x7f]),
        (0x4000, [0x80, 0x80, 0x01]), (0x5678, [0xf8, 0xac, 0x01]),
        (0xfffff, [0xff, 0xff, 0x3f]), (0x1fffff, [0xff, 0xff, 0x7f]),
        (0x200000, [0x80, 0x80, 0x80, 0x01]), (0xfffffff, [0xff, 0xff, 0xff, 0x7f]),
        (0x10000000, [0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x7fffffff, [0xff, 0xff, 0xff, 0xff, 0x07]),
        (0x87654321, [0xa1, 0x86, 0x95, 0xbb, 0x08]),
        (0xffffffff, [0xff, 0xff, 0xff, 0xff, 0x0f]),
    ]
    CASES_INT = [
        (0, [0]), (1, [1]), (-1, [0x7f]), (0x3f, [0x3f]), (0x40, [0xc0, 0x00]),
        (-0x3f, [0x41]), (-0x40, [0x40]), (-0x41, [0xbf, 0x7f]),
        (0x1fff, [0xff, 0x3f]), (0x2000, [0x80, 0xc0, 0x00]), (-0x2000, [0x80, 0x40]),
        (-0x2001, [0xff, 0xbf, 0x7f]), (0xfffff, [0xff, 0xff, 0x3f]),
        (0x100000, [0x80, 0x80, 0xc0, 0x00]), (-0x100000, [0x80, 0x80, 0x40]),
        (-0x100001, [0xff, 0xff, 0xbf, 0x7f]), (0x7ffffff, [0xff, 0xff, 0xff, 0x3f]),
        (0x8000000, [0x80, 0x80, 0x80, 0xc0, 0x00]), (-0x8000000, [0x80, 0x80, 0x80, 0x40]),
        (-0x8000001, [0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x76543210, [0x90, 0xe4, 0xd0, 0xb2, 0x07]),
        (-0x76543210, [0xf0, 0x9b, 0xaf, 0xcd, 0x78]),
        (0x7fffffff, [0xff, 0xff, 0xff, 0xff, 0x07]),
        (-0x80000000, [0x80, 0x80, 0x80, 0x80, 0x78]),
    ]

    def test_encode_unsigned(self):
        for value, expected in self.CASES_UINT:
            check_encoded(enc('append_uint32', value), expected)

    def test_round_trip_unsigned(self):
        for value, _ in self.CASES_UINT:
            d = Decoder(enc('append_uint32', value).buffer)
            assert d.read_uint32() == value
            assert d.done

    def test_encode_signed(self):
        for value, expected in self.CASES_INT:
            check_encoded(enc('append_int32', value), expected)

    def test_round_trip_signed(self):
        for value, _ in self.CASES_INT:
            d = Decoder(enc('append_int32', value).buffer)
            assert d.read_int32() == value
            assert d.done

    def test_encode_out_of_range(self):
        for bad in (0x100000000, MAX_SAFE, -1, -0x80000000):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_uint32(bad)
        for bad in (0x80000000, MAX_SAFE, -0x80000001):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_int32(bad)
        for bad in (float('-inf'), float('nan'), 3.14159):
            with pytest.raises(ValueError, match='not an integer'):
                Encoder().append_uint32(bad)
            with pytest.raises(ValueError, match='not an integer'):
                Encoder().append_int32(bad)

    def test_decode_out_of_range(self):
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80, 0x80, 0x80, 0x80, 0x80, 0x00])).read_uint32()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80, 0x80, 0x80, 0x80, 0x80, 0x00])).read_int32()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80, 0x80, 0x80, 0x80, 0x10])).read_uint32()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80, 0x80, 0x80, 0x80, 0x08])).read_int32()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0xff, 0xff, 0xff, 0xff, 0x77])).read_int32()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_uint32()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_int32()


class TestLeb128_53bit:
    CASES_UINT = [
        (0, [0]), (0x7f, [0x7f]), (0x80, [0x80, 0x01]), (0x3fff, [0xff, 0x7f]),
        (0x4000, [0x80, 0x80, 0x01]), (0x1fffff, [0xff, 0xff, 0x7f]),
        (0x200000, [0x80, 0x80, 0x80, 0x01]), (0xfffffff, [0xff, 0xff, 0xff, 0x7f]),
        (0x10000000, [0x80, 0x80, 0x80, 0x80, 0x01]),
        (0xffffffff, [0xff, 0xff, 0xff, 0xff, 0x0f]),
        (0x100000000, [0x80, 0x80, 0x80, 0x80, 0x10]),
        (0x7ffffffff, [0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x800000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x3ffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x40000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x2000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x123456789abcde, [0xde, 0xf9, 0xea, 0xc4, 0xe7, 0x8a, 0x8d, 0x09]),
        (MAX_SAFE, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x0f]),
    ]
    CASES_INT = [
        (0, [0]), (1, [1]), (-1, [0x7f]), (0x3f, [0x3f]), (-0x40, [0x40]),
        (0x40, [0xc0, 0x00]), (-0x41, [0xbf, 0x7f]), (0x1fff, [0xff, 0x3f]),
        (-0x2000, [0x80, 0x40]), (0x2000, [0x80, 0xc0, 0x00]),
        (-0x2001, [0xff, 0xbf, 0x7f]), (0xfffff, [0xff, 0xff, 0x3f]),
        (-0x100000, [0x80, 0x80, 0x40]), (0x100000, [0x80, 0x80, 0xc0, 0x00]),
        (-0x100001, [0xff, 0xff, 0xbf, 0x7f]), (0x7ffffff, [0xff, 0xff, 0xff, 0x3f]),
        (-0x8000000, [0x80, 0x80, 0x80, 0x40]), (0x8000000, [0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x8000001, [0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x7fffffff, [0xff, 0xff, 0xff, 0xff, 0x07]),
        (0x80000000, [0x80, 0x80, 0x80, 0x80, 0x08]),
        (-0x80000000, [0x80, 0x80, 0x80, 0x80, 0x78]),
        (-0x80000001, [0xff, 0xff, 0xff, 0xff, 0x77]),
        (0x3ffffffff, [0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x400000000, [0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x400000000, [0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x400000001, [0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x1ffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x20000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x20000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x20000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0xffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x1000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x1000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x1000000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x123456789abcde, [0xde, 0xf9, 0xea, 0xc4, 0xe7, 0x8a, 0x8d, 0x09]),
        (MAX_SAFE, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x0f]),
        (MIN_SAFE, [0x81, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x70]),
    ]

    def test_encode_unsigned(self):
        for value, expected in self.CASES_UINT:
            check_encoded(enc('append_uint53', value), expected)

    def test_round_trip_unsigned(self):
        for value, _ in self.CASES_UINT:
            d = Decoder(enc('append_uint53', value).buffer)
            assert d.read_uint53() == value
            assert d.done

    def test_encode_signed(self):
        for value, expected in self.CASES_INT:
            check_encoded(enc('append_int53', value), expected)

    def test_round_trip_signed(self):
        extra = []
        for mag in (0x123, 0x1234, 0x12345, 0x123456, 0x1234567, 0x12345678,
                    0x123456789, 0x123456789a, 0x123456789ab, 0x123456789abc,
                    0x123456789abcd, 0x123456789abcde):
            extra.extend([(mag, None), (-mag, None)])
        for value, _ in self.CASES_INT + extra:
            d = Decoder(enc('append_int53', value).buffer)
            assert d.read_int53() == value
            assert d.done

    def test_encode_out_of_range(self):
        for bad in (MAX_SAFE + 1, -1, -0x80000000, MIN_SAFE):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_uint53(bad)
        for bad in (MAX_SAFE + 1, MIN_SAFE - 1):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_int53(bad)
        for bad in (float('-inf'), float('nan'), 3.14159):
            with pytest.raises(ValueError, match='not an integer'):
                Encoder().append_uint53(bad)
            with pytest.raises(ValueError, match='not an integer'):
                Encoder().append_int53(bad)

    def test_decode_out_of_range(self):
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 7 + [0x10])).read_uint53()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 7 + [0x10])).read_int53()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 7 + [0x70])).read_int53()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0xff] * 7 + [0x6f])).read_int53()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_uint53()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_int53()


class TestLeb128_64bit:
    # (value, expected bytes); values written as (high32, low32) pairs in the
    # reference are combined here since Python ints are arbitrary precision
    CASES_UINT = [
        (0, [0]), (0x7f, [0x7f]), (0x80, [0x80, 0x01]), (0x3fff, [0xff, 0x7f]),
        (0xffffffff, [0xff, 0xff, 0xff, 0xff, 0x0f]),
        (0x100000000, [0x80, 0x80, 0x80, 0x80, 0x10]),
        (0x7ffffffff, [0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x800000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x3ffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x40000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0x1ffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x2000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0xffffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f]),
        (0x100000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x01]),
        (0xffffffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x01]),
    ]
    CASES_INT = [
        (0, [0]), (1, [1]), (-1, [0x7f]), (0x3f, [0x3f]), (-0x40, [0x40]),
        (0x40, [0xc0, 0x00]), (-0x41, [0xbf, 0x7f]),
        (0x7fffffff, [0xff, 0xff, 0xff, 0xff, 0x07]),
        (0x80000000, [0x80, 0x80, 0x80, 0x80, 0x08]),
        (0xffffffff, [0xff, 0xff, 0xff, 0xff, 0x0f]),
        (-0x80000000, [0x80, 0x80, 0x80, 0x80, 0x78]),
        (-0x100000000 + 0x7fffffff, [0xff, 0xff, 0xff, 0xff, 0x77]),
        (-0xffffffff, [0x81, 0x80, 0x80, 0x80, 0x70]),
        (-0x100000000, [0x80, 0x80, 0x80, 0x80, 0x70]),
        (-0x100000001, [0xff, 0xff, 0xff, 0xff, 0x6f]),
        (0x3ffffffff, [0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x400000000, [0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x400000000, [0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x400000001, [0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x1ffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x20000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x20000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x20000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0xffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x1000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x1000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x1000000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x7fffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x80000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x80000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x80000000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x3fffffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x3f]),
        (-0x4000000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x40]),
        (0x4000000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0xc0, 0x00]),
        (-0x4000000000000001, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xbf, 0x7f]),
        (0x7fffffffffffffff, [0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x00]),
        (-0x8000000000000000, [0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x80, 0x7f]),
    ]

    def test_encode_unsigned(self):
        for value, expected in self.CASES_UINT:
            check_encoded(enc('append_uint64', value), expected)

    def test_round_trip_unsigned(self):
        for value, _ in self.CASES_UINT:
            d = Decoder(enc('append_uint64', value).buffer)
            assert d.read_uint64() == value
            assert d.done

    def test_encode_signed(self):
        for value, expected in self.CASES_INT:
            check_encoded(enc('append_int64', value), expected)

    def test_round_trip_signed(self):
        for value, _ in self.CASES_INT:
            d = Decoder(enc('append_int64', value).buffer)
            assert d.read_int64() == value
            assert d.done

    def test_encode_out_of_range(self):
        for bad in (2 ** 64, -1):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_uint64(bad)
        for bad in (2 ** 63, -(2 ** 63) - 1):
            with pytest.raises(ValueError, match='out of range'):
                Encoder().append_int64(bad)

    def test_decode_out_of_range(self):
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 10 + [0x00])).read_uint64()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 10 + [0x00])).read_int64()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0xff] * 9 + [0x02])).read_uint64()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0xff] * 9 + [0x01])).read_int64()
        with pytest.raises(ValueError, match='out of range'):
            Decoder(bytes([0x80] * 9 + [0x7e])).read_int64()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_uint64()
        with pytest.raises(ValueError, match='incomplete number'):
            Decoder(bytes([0x80, 0x80])).read_int64()


class TestStringsAndHex:
    def test_encode_strings(self):
        check_encoded(Encoder().append_prefixed_string(''), [0])
        check_encoded(Encoder().append_prefixed_string('a'), [1, 0x61])
        check_encoded(Encoder().append_prefixed_string('Oh là là'),
                      [10, 79, 104, 32, 108, 195, 160, 32, 108, 195, 160])
        check_encoded(Encoder().append_prefixed_string('\U0001f604'),
                      [4, 0xf0, 0x9f, 0x98, 0x84])

    def test_round_trip_strings(self):
        for s in ('', 'a', 'Oh là là', '\U0001f604'):
            assert Decoder(Encoder().append_prefixed_string(s).buffer) \
                .read_prefixed_string() == s

    def test_multiple_strings(self):
        e = Encoder()
        for s in ('one', 'two', 'three'):
            e.append_prefixed_string(s)
        d = Decoder(e.buffer)
        assert [d.read_prefixed_string() for _ in range(3)] == ['one', 'two', 'three']

    def test_encode_hex(self):
        check_encoded(Encoder().append_hex_string(''), [0])
        check_encoded(Encoder().append_hex_string('00'), [1, 0])
        check_encoded(Encoder().append_hex_string('0123'), [2, 1, 0x23])
        check_encoded(Encoder().append_hex_string('fedcba9876543210'),
                      [8, 0xfe, 0xdc, 0xba, 0x98, 0x76, 0x54, 0x32, 0x10])

    def test_round_trip_hex(self):
        for s in ('', '00', '0123', 'fedcba9876543210'):
            assert Decoder(Encoder().append_hex_string(s).buffer).read_hex_string() == s

    def test_malformed_hex(self):
        with pytest.raises(TypeError, match='value is not a string'):
            Encoder().append_hex_string(0x1234)
        for bad in ('abcd-ef', '0', 'ABCD', 'zz'):
            with pytest.raises(ValueError, match='value is not hexadecimal'):
                Encoder().append_hex_string(bad)


def encode_rle(type, values):
    e = RLEEncoder(type)
    for v in values:
        e.append_value(v)
    return e.buffer


def decode_rle(type, buffer):
    if isinstance(buffer, list):
        buffer = bytes(buffer)
    d = RLEDecoder(type, buffer)
    values = []
    while not d.done:
        values.append(d.read_value())
    return values


class TestRLE:
    def test_encode_without_nulls(self):
        assert encode_rle('uint', []) == b''
        assert encode_rle('uint', [1, 2, 3]) == bytes([0x7d, 1, 2, 3])
        assert encode_rle('uint', [0, 1, 2, 2, 3]) == bytes([0x7e, 0, 1, 2, 2, 0x7f, 3])
        assert encode_rle('uint', [1, 1, 1, 1, 1, 1]) == bytes([6, 1])
        assert encode_rle('uint', [1, 1, 1, 4, 4, 4]) == bytes([3, 1, 3, 4])
        assert encode_rle('uint', [0xff]) == bytes([0x7f, 0xff, 0x01])
        assert encode_rle('int', [-0x40]) == bytes([0x7f, 0x40])

    def test_encode_with_nulls(self):
        assert encode_rle('uint', [None, 1]) == bytes([0, 1, 0x7f, 1])
        assert encode_rle('uint', [1, None]) == bytes([0x7f, 1, 0, 1])
        assert encode_rle('uint', [1, 1, 1, None]) == bytes([3, 1, 0, 1])
        assert encode_rle('uint', [None, None, None, 3, 4, 5, None]) == \
            bytes([0, 3, 0x7d, 3, 4, 5, 0, 1])
        assert encode_rle('uint', [None, None, None, 9, 9, 9]) == bytes([0, 3, 3, 9])
        assert encode_rle('uint', [1, 1, 1, 1, 1, None, None, None, 1]) == \
            bytes([5, 1, 0, 3, 0x7f, 1])

    def test_round_trip_without_nulls(self):
        for seq in ([], [1, 2, 3], [0, 1, 2, 2, 3], [1, 1, 1, 1, 1, 1],
                    [1, 1, 1, 4, 4, 4], [0xff]):
            assert decode_rle('uint', encode_rle('uint', seq)) == seq
        assert decode_rle('int', encode_rle('int', [-0x40])) == [-0x40]

    def test_round_trip_with_nulls(self):
        for seq in ([None, 1], [1, None],
                    [1, 1, 1, None], [None, None, None, 3, 4, 5, None],
                    [None, None, None, 9, 9, 9], [1, 1, 1, 1, 1, None, None, None, 1]):
            assert decode_rle('uint', encode_rle('uint', seq)) == seq

    def test_string_values(self):
        assert encode_rle('utf8', ['a']) == bytes([0x7f, 1, 0x61])
        assert encode_rle('utf8', ['a', 'b', 'c', 'd']) == \
            bytes([0x7c, 1, 0x61, 1, 0x62, 1, 0x63, 1, 0x64])
        assert encode_rle('utf8', ['a', 'a', 'a', 'a']) == bytes([4, 1, 0x61])
        assert encode_rle('utf8', ['a', 'a', None, None, 'a', 'a']) == \
            bytes([2, 1, 0x61, 0, 2, 2, 1, 0x61])
        assert encode_rle('utf8', [None, None, None, None, 'abc']) == \
            bytes([0, 4, 0x7f, 3, 0x61, 0x62, 0x63])

    def test_round_trip_string_values(self):
        for seq in (['a'], ['a', 'b', 'c', 'd'], ['a', 'a', 'a', 'a'],
                    ['a', 'a', None, None, 'a', 'a'], [None, None, None, None, 'abc']):
            assert decode_rle('utf8', encode_rle('utf8', seq)) == seq

    def test_repetition_counts(self):
        cases = [
            ([(3, 0)], []),
            ([(3, 10)], [10, 3]),
            ([(3, 10), (3, 10)], [20, 3]),
            ([(3, 10), (4, 10)], [10, 3, 10, 4]),
            ([(3, 10), (None, 10)], [10, 3, 0, 10]),
            ([(1, 1), (1, 2)], [3, 1]),
            ([(1, 1), (2, 3)], [0x7f, 1, 3, 2]),
            ([(1, 1), (2, 1), (3, 3)], [0x7e, 1, 2, 3, 3]),
            ([(None, 1), (3, 3)], [0, 1, 3, 3]),
            ([(None, 1), (None, 3), (1, 1)], [0, 4, 0x7f, 1]),
        ]
        for appends, expected in cases:
            e = RLEEncoder('uint')
            for value, reps in appends:
                e.append_value(value, reps)
            check_encoded(e, expected)

    def test_all_nulls_empty_buffer(self):
        assert encode_rle('uint', []) == b''
        assert encode_rle('uint', [None]) == b''
        assert encode_rle('uint', [None] * 4) == b''

    def test_canonical_form_enforced(self):
        with pytest.raises(ValueError, match='Repetition count of 1 is not allowed'):
            decode_rle('int', [1, 1])
        with pytest.raises(ValueError, match='Successive repetitions with the same value'):
            decode_rle('int', [2, 1, 2, 1])
        with pytest.raises(ValueError, match='Successive null runs are not allowed'):
            decode_rle('int', [0, 1, 0, 2])
        with pytest.raises(ValueError, match='Zero-length null runs are not allowed'):
            decode_rle('int', [0, 0])
        with pytest.raises(ValueError, match='Successive literals are not allowed'):
            decode_rle('int', [0x7f, 1, 0x7f, 2])
        with pytest.raises(ValueError, match='Repetition of values is not allowed'):
            decode_rle('int', [0x7d, 1, 2, 2])
        with pytest.raises(ValueError, match='Repetition of values is not allowed'):
            decode_rle('int', [2, 0, 0x7e, 0, 1])
        with pytest.raises(ValueError, match='Successive repetitions with the same value'):
            decode_rle('int', [0x7e, 1, 2, 2, 2])

    def test_skip_strings(self):
        example = [None, None, None, 'a', 'a', 'a', 'b', 'c', 'd', 'e']
        encoded = encode_rle('utf8', example)
        for skip in range(len(example)):
            d = RLEDecoder('utf8', encoded)
            d.skip_values(skip)
            values = []
            while not d.done:
                values.append(d.read_value())
            assert values == example[skip:], f'skipping {skip} values failed'

    def test_skip_integers(self):
        example = [None, None, None, 1, 1, 1, 2, 3, 4, 5]
        encoded = encode_rle('uint', example)
        for skip in range(len(example)):
            d = RLEDecoder('uint', encoded)
            d.skip_values(skip)
            values = []
            while not d.done:
                values.append(d.read_value())
            assert values == example[skip:], f'skipping {skip} values failed'


def do_copy_rle(input1, input2, skip=None, count=None, **kw):
    if isinstance(input1, list):
        encoder1 = RLEEncoder('uint')
        for v in input1:
            encoder1.append_value(v)
    else:
        encoder1 = input1
    encoder2 = RLEEncoder('uint')
    for v in input2:
        encoder2.append_value(v)
    decoder2 = RLEDecoder('uint', encoder2.buffer)
    if skip:
        decoder2.skip_values(skip)
    encoder1.copy_from(decoder2, count=count, **kw)
    return encoder1


class TestRLECopyFrom:
    def test_copy_sequence(self):
        cases = [
            (([], [0, 1, 2]), [0x7d, 0, 1, 2]),
            (([0, 1, 2], []), [0x7d, 0, 1, 2]),
            (([0, 1, 2], [3, 4, 5, 6]), [0x79, 0, 1, 2, 3, 4, 5, 6]),
            (([0, 1], [2, 3, 4, 4, 4]), [0x7c, 0, 1, 2, 3, 3, 4]),
            (([0, 1, 2], [3, 4, 4, 4]), [0x7c, 0, 1, 2, 3, 3, 4]),
            (([0, 1, 2], [3, 3, 3, 4, 4, 4]), [0x7d, 0, 1, 2, 3, 3, 3, 4]),
            (([0, 1, 2], [None, None, 4, 4, 4]), [0x7d, 0, 1, 2, 0, 2, 3, 4]),
            (([0, 1, 2], [3, 4, 4, None, None]), [0x7c, 0, 1, 2, 3, 2, 4, 0, 2]),
            (([0, 1, 2], [3, 4, 4, 5, 6, 6]), [0x7c, 0, 1, 2, 3, 2, 4, 0x7f, 5, 2, 6]),
            (([0, 1, 2], [2, 2, 3, 3, 4, 5, 6]), [0x7e, 0, 1, 3, 2, 2, 3, 0x7d, 4, 5, 6]),
            (([0, 0, 0], [0, 0, 0]), [6, 0]),
            (([0, 0, 0], [0, 1, 1]), [4, 0, 2, 1]),
            (([0, 0, 0], [1, 2, 2]), [3, 0, 0x7f, 1, 2, 2]),
            (([0, 0, 0], [1, 2, 3]), [3, 0, 0x7d, 1, 2, 3]),
            (([0, 0, 0], [None, None, 2, 2]), [3, 0, 0, 2, 2, 2]),
            (([0, 0, 0], [None, 0, 0, 0]), [3, 0, 0, 1, 3, 0]),
            (([0, 0, None], [None, 0, 0]), [2, 0, 0, 2, 2, 0]),
            (([0, 0, None], [0, 0, 0]), [2, 0, 0, 1, 3, 0]),
            (([0, 0, None], [1, 2, 3]), [2, 0, 0, 1, 0x7d, 1, 2, 3]),
        ]
        for (in1, in2), expected in cases:
            check_encoded(do_copy_rle(in1, in2), expected)

    def test_copy_multiple(self):
        check_encoded(do_copy_rle(do_copy_rle([0, 0, 1], [1, 2]), [2, 3]),
                      [2, 0, 2, 1, 2, 2, 0x7f, 3])
        check_encoded(do_copy_rle(do_copy_rle([0], [0, 0, 1, 1, 2]), [2, 3, 3, 4]),
                      [3, 0, 2, 1, 2, 2, 2, 3, 0x7f, 4])
        check_encoded(do_copy_rle(do_copy_rle([0, 1, 2], [3, 4]), [5, 6]),
                      [0x79, 0, 1, 2, 3, 4, 5, 6])
        check_encoded(do_copy_rle(do_copy_rle([0, 0, 0], [0, 0, 1, 1]), [1, 1]),
                      [5, 0, 4, 1])
        check_encoded(do_copy_rle(do_copy_rle([0, None], [None, 1, None]), [None, 2]),
                      [0x7f, 0, 0, 2, 0x7f, 1, 0, 2, 0x7f, 2])

    def test_copy_subsequence(self):
        cases = [
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=0, count=0), [0x7d, 0, 1, 2]),
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=0, count=1), [0x7c, 0, 1, 2, 3]),
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=0, count=2), [0x7b, 0, 1, 2, 3, 4]),
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=0, count=4), [0x79, 0, 1, 2, 3, 4, 5, 6]),
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=1, count=1), [0x7c, 0, 1, 2, 4]),
            (([0, 1, 2], [3, 4, 5, 6]), dict(skip=1, count=2), [0x7b, 0, 1, 2, 4, 5]),
            (([0, 1, 2], [3, 3, 3, 3]), dict(skip=0, count=2), [0x7d, 0, 1, 2, 2, 3]),
            (([0, 0, 0], [0, 0, 0, 0]), dict(skip=0, count=2), [5, 0]),
            (([0, 0], [0, 0, 1, 1, 1]), dict(skip=0, count=4), [4, 0, 2, 1]),
            (([0, 0], [0, 0, 1, 1, 2, 2]), dict(skip=1, count=4), [3, 0, 2, 1, 0x7f, 2]),
            (([0, 0], [1, 1, 2, 3, 4, 5]), dict(skip=0, count=3), [2, 0, 2, 1, 0x7f, 2]),
            (([None], [None, 1, 1, None]), dict(skip=0, count=2), [0, 2, 0x7f, 1]),
            (([None], [None, 1, 1, None]), dict(skip=1, count=3), [0, 1, 2, 1, 0, 1]),
            (([], [None, None, None, 0, 0]), dict(skip=0, count=5), [0, 3, 2, 0]),
        ]
        for (in1, in2), opts, expected in cases:
            check_encoded(do_copy_rle(in1, in2, **opts), expected)

    def test_insertion_into_sequence(self):
        d1 = RLEDecoder('uint', encode_rle('uint', [0, 1, 2, 3, 4, 5, 6]))
        d2 = RLEDecoder('uint', encode_rle('uint', [3, 3, 3]))
        e = RLEEncoder('uint')
        e.copy_from(d1, count=4)
        e.copy_from(d2)
        e.copy_from(d1)
        check_encoded(e, [0x7d, 0, 1, 2, 4, 3, 0x7d, 4, 5, 6])

    def test_insertion_into_repetition_run(self):
        d1 = RLEDecoder('uint', encode_rle('uint', [1, 2, 3, 3, 4]))
        d2 = RLEDecoder('uint', encode_rle('uint', [5]))
        e = RLEEncoder('uint')
        e.copy_from(d1, count=3)
        e.copy_from(d2)
        e.copy_from(d1)
        check_encoded(e, [0x7a, 1, 2, 3, 5, 3, 4])

    def test_copy_starting_with_nulls(self):
        d = RLEDecoder('uint', bytes([0, 2, 0x7f, 0]))  # null, null, 0
        RLEEncoder('uint').copy_from(d, count=1)
        assert d.read_value() is None
        assert d.read_value() == 0
        d.reset()
        RLEEncoder('uint').copy_from(d, count=2)
        assert d.read_value() == 0

    def test_sum_of_copied_values(self):
        e2 = RLEEncoder('uint')
        for v in (1, 2, 3, 10, 10, 10):
            e2.append_value(v)
        assert RLEEncoder('uint').copy_from(
            RLEDecoder('uint', e2.buffer), sum_values=True) == (6, 36)
        assert RLEEncoder('uint').copy_from(
            RLEDecoder('uint', e2.buffer), sum_values=True, sum_shift=2) == (6, 6)

    def test_too_few_values(self):
        for in1, in2, count in ([[0, 1, 2], [], 1], [[0, 1, 2], [3], 2],
                                [[0, 1, 2], [3, 4, 5, 6], 5], [[0, 1, 2], [3, 3, 3], 4],
                                [[0, 1, 2], [3, 3, 4, 4, 5, 5], 7]):
            with pytest.raises(ValueError, match=f'cannot copy {count} values'):
                do_copy_rle(in1, in2, count=count)
        with pytest.raises(ValueError, match='incomplete literal'):
            RLEEncoder('uint').copy_from(RLEDecoder('uint', bytes([0x7e, 1])))
        with pytest.raises(ValueError, match='Repetition of values'):
            RLEEncoder('uint').copy_from(RLEDecoder('uint', bytes([2, 1, 0x7f, 1])))

    def test_decoder_type_check(self):
        with pytest.raises(TypeError, match='incompatible type of decoder'):
            RLEEncoder('uint').copy_from(Decoder(b''))
        with pytest.raises(TypeError, match='incompatible type of decoder'):
            RLEEncoder('uint').copy_from(RLEDecoder('int', b''))


def encode_delta(values):
    e = DeltaEncoder()
    for v in values:
        e.append_value(v)
    return e.buffer


def decode_delta(buffer):
    d = DeltaDecoder(buffer)
    values = []
    while not d.done:
        values.append(d.read_value())
    return values


def do_copy_delta(input1, input2, skip=None, count=None):
    if isinstance(input1, list):
        encoder1 = DeltaEncoder()
        for v in input1:
            encoder1.append_value(v)
    else:
        encoder1 = input1
    encoder2 = DeltaEncoder()
    for v in input2:
        encoder2.append_value(v)
    decoder2 = DeltaDecoder(encoder2.buffer)
    if skip:
        decoder2.skip_values(skip)
    encoder1.copy_from(decoder2, count=count)
    return encoder1


class TestDelta:
    def test_encode(self):
        assert encode_delta([]) == b''
        assert encode_delta([18, 2, 9, 15, 16, 19, 25]) == \
            bytes([0x79, 18, 0x70, 7, 6, 1, 3, 6])
        assert encode_delta([1, 2, 3, 4, 5, 6, 7, 8]) == bytes([8, 1])
        assert encode_delta([10, 11, 12, 13, 14, 15]) == bytes([0x7f, 10, 5, 1])
        assert encode_delta([10, 11, 12, 13, 0, 1, 2, 3]) == \
            bytes([0x7f, 10, 3, 1, 0x7f, 0x73, 3, 1])
        assert encode_delta([0, 1, 2, 3, None, None, None, 4, 5, 6]) == \
            bytes([0x7f, 0, 3, 1, 0, 3, 3, 1])
        assert encode_delta([-64, -60, -56, -52, -48, -44, -40, -36]) == \
            bytes([0x7f, 0x40, 7, 4])

    def test_round_trip(self):
        for seq in ([], [18, 2, 9, 15, 16, 19, 25], [1, 2, 3, 4, 5, 6, 7, 8],
                    [10, 11, 12, 13, 14, 15], [10, 11, 12, 13, 0, 1, 2, 3],
                    [0, 1, 2, 3, None, None, None, 4, 5, 6],
                    [-64, -60, -56, -52, -48, -44, -40, -36]):
            assert decode_delta(encode_delta(seq)) == seq

    def test_repetition_counts(self):
        e = DeltaEncoder(); e.append_value(3, 0); check_encoded(e, [])
        e = DeltaEncoder(); e.append_value(3, 10); check_encoded(e, [0x7f, 3, 9, 0])
        e = DeltaEncoder(); e.append_value(1, 3); e.append_value(1, 3)
        check_encoded(e, [0x7f, 1, 5, 0])

    def test_skip(self):
        example = [None, None, None, 10, 11, 12, 13, 14, 15, 16, 1, 2, 3,
                   40, 11, 13, 21, 103]
        encoded = encode_delta(example)
        for skip in range(len(example)):
            d = DeltaDecoder(encoded)
            d.skip_values(skip)
            values = []
            while not d.done:
                values.append(d.read_value())
            assert values == example[skip:], f'skipping {skip} values failed'

    def test_copy_sequence(self):
        cases = [
            (([], [0, 0, 0]), [3, 0]),
            (([0, 0, 0], []), [3, 0]),
            (([0, 0, 0], [0, 0, 0]), [6, 0]),
            (([1, 2, 3], [4, 5, 6]), [6, 1]),
            (([1, 2, 3], [4, 10, 20]), [4, 1, 0x7e, 6, 10]),
            (([1, 2, 3], [1, 2, 3, 4]), [3, 1, 0x7f, 0x7e, 3, 1]),
            (([0, 1, 3], [6, 10, 15]), [0x7a, 0, 1, 2, 3, 4, 5]),
            (([0, 1, 3], [5, 9, 14]), [0x7e, 0, 1, 2, 2, 0x7e, 4, 5]),
            (([1, 2, 4], [5, 6, 8, 9, 10, 12]),
             [2, 1, 0x7f, 2, 2, 1, 0x7f, 2, 2, 1, 0x7f, 2]),
            (([4, 4, 4], [4, 4, 4, 5, 6, 7]), [0x7f, 4, 5, 0, 3, 1]),
            (([0, 1, 4], [9, 6, 2, 5, 3]), [0x78, 0, 1, 3, 5, 0x7d, 0x7c, 3, 0x7e]),
            (([1, 2, 3], [None, 4, 5, 6]), [3, 1, 0, 1, 3, 1]),
            (([1, 2, 3], [None, 6, 6, 6]), [3, 1, 0, 1, 0x7f, 3, 2, 0]),
            (([1, 2, 3], [None, None, 4, 5, 7, 9]), [3, 1, 0, 2, 2, 1, 2, 2]),
            (([1, 2, None], [3, 4, 5]), [2, 1, 0, 1, 3, 1]),
            (([1, 2, None], [6, 6, 6]), [2, 1, 0, 1, 0x7f, 4, 2, 0]),
            (([1, 2, None], [None, 3, 4]), [2, 1, 0, 2, 2, 1]),
            (([1, 2, None], [None, 6, 6]), [2, 1, 0, 2, 0x7e, 4, 0]),
        ]
        for (in1, in2), expected in cases:
            check_encoded(do_copy_delta(in1, in2), expected)

    def test_copy_subsequence(self):
        check_encoded(do_copy_delta([1, 2, 3], [4, 5, 6, 7], count=2), [5, 1])
        check_encoded(do_copy_delta([1, 2, 3], [None, None, 4], count=1), [3, 1, 0, 1])
        check_encoded(do_copy_delta([1, 2, 3], [None, None, 4], count=2), [3, 1, 0, 2])

    def test_copy_non_ascending(self):
        d = DeltaDecoder(bytes([2, 1, 0x7e, 2, 0x7f]))  # 1, 2, 4, 3
        e = DeltaEncoder()
        e.copy_from(d, count=4)
        e.append_value(5)
        check_encoded(e, [2, 1, 0x7d, 2, 0x7f, 2])  # 1, 2, 4, 3, 5

    def test_pause_and_resume(self):
        num_values = 13  # 1, 3, 4, 2, null, 3, 4, 5, null, null, 4, 2, -1
        data = bytes([0x7c, 1, 2, 1, 0x7e, 0, 1, 3, 1, 0, 2, 0x7d, 0x7f, 0x7e, 0x7d])
        d = DeltaDecoder(data)
        for i in range(num_values + 1):
            e = DeltaEncoder()
            e.copy_from(d, count=i)
            e.copy_from(d, count=num_values - i)
            check_encoded(e, data)
            d.reset()

    def test_copy_then_append(self):
        e1 = do_copy_delta([], [1, 2, 3])
        e1.append_value(4)
        check_encoded(e1, [4, 1])

        e2 = do_copy_delta([5], [6, None, None, None, 7, 8])
        e2.append_value(9)
        check_encoded(e2, [0x7e, 5, 1, 0, 3, 3, 1])

        e3 = do_copy_delta([1], [2])
        e3.append_value(3)
        check_encoded(e3, [3, 1])

    def test_too_few_values(self):
        with pytest.raises(ValueError, match='cannot copy 1 values'):
            do_copy_delta([0, 1, 2], [], count=1)
        with pytest.raises(ValueError, match='cannot copy 1 values'):
            do_copy_delta([0, 1, 2], [None, 3], count=3)
        with pytest.raises(ValueError, match='cannot copy 3 values'):
            DeltaEncoder().copy_from(DeltaDecoder(bytes([0, 2])), count=3)

    def test_argument_checks(self):
        with pytest.raises(TypeError, match='incompatible type of decoder'):
            DeltaEncoder().copy_from(Decoder(b''))
        with pytest.raises(ValueError, match='unsupported options'):
            DeltaEncoder().copy_from(DeltaDecoder(b''), sum_values=True)


def encode_bools(values):
    e = BooleanEncoder()
    for v in values:
        e.append_value(v)
    return e.buffer


def decode_bools(buffer):
    if isinstance(buffer, list):
        buffer = bytes(buffer)
    d = BooleanDecoder(buffer)
    values = []
    while not d.done:
        values.append(d.read_value())
    return values


def do_copy_bools(input1, input2, skip=None, count=None):
    if isinstance(input1, list):
        encoder1 = BooleanEncoder()
        for v in input1:
            encoder1.append_value(v)
    else:
        encoder1 = input1
    encoder2 = BooleanEncoder()
    for v in input2:
        encoder2.append_value(v)
    decoder2 = BooleanDecoder(encoder2.buffer)
    if skip:
        decoder2.skip_values(skip)
    encoder1.copy_from(decoder2, count=count)
    return encoder1


class TestBoolean:
    def test_encode(self):
        assert encode_bools([]) == b''
        assert encode_bools([False]) == bytes([1])
        assert encode_bools([True]) == bytes([0, 1])
        assert encode_bools([False, False, False, True, True]) == bytes([3, 2])
        assert encode_bools([True, True, True, False, False]) == bytes([0, 3, 2])
        assert encode_bools([True, False, True, False, True, True, False]) == \
            bytes([0, 1, 1, 1, 1, 2, 1])

    def test_round_trip(self):
        for seq in ([], [False], [True], [False, False, False, True, True],
                    [True, True, True, False, False],
                    [True, False, True, False, True, True, False]):
            assert decode_bools(encode_bools(seq)) == seq

    def test_non_boolean_rejected(self):
        for bad in (42, None, 'false'):
            with pytest.raises(ValueError, match='Unsupported value'):
                encode_bools([bad])

    def test_repetition_counts(self):
        e = BooleanEncoder(); e.append_value(False, 0); check_encoded(e, [])
        e = BooleanEncoder(); e.append_value(False, 2); e.append_value(False, 2)
        check_encoded(e, [4])
        e = BooleanEncoder(); e.append_value(True, 2); e.append_value(False, 2)
        check_encoded(e, [0, 2, 2])

    def test_skip(self):
        example = [False, False, False, True, True, True, False, True, False, True]
        encoded = encode_bools(example)
        for skip in range(len(example)):
            d = BooleanDecoder(encoded)
            d.skip_values(skip)
            values = []
            while not d.done:
                values.append(d.read_value())
            assert values == example[skip:], f'skipping {skip} values failed'

    def test_canonical_form(self):
        with pytest.raises(ValueError, match='Zero-length runs are not allowed'):
            decode_bools([1, 0])
        with pytest.raises(ValueError, match='Zero-length runs are not allowed'):
            decode_bools([1, 1, 0])
        d = BooleanDecoder(bytes([2, 0, 1]))
        d.skip_values(1)
        with pytest.raises(ValueError, match='Zero-length runs are not allowed'):
            d.skip_values(2)

    def test_copy_sequence(self):
        check_encoded(do_copy_bools([False, False, True], []), [2, 1])
        check_encoded(do_copy_bools([], [False, False, True, True]), [2, 2])
        check_encoded(do_copy_bools([False, False], [False, False, True, True]), [4, 2])
        check_encoded(do_copy_bools([True, True], [False, False, True, True]), [0, 2, 2, 2])
        check_encoded(do_copy_bools([True, True], [True, True]), [0, 4])

    def test_copy_subsequence(self):
        check_encoded(do_copy_bools([False], [False, False, False, True], count=2), [3])
        check_encoded(do_copy_bools([False], [True, True, True, True], count=3), [1, 3])
        check_encoded(do_copy_bools([False], [False, True, True, True], skip=1), [1, 3])
        check_encoded(do_copy_bools([False], [False, True, True, True], skip=2), [1, 2])

    def test_too_few_values(self):
        with pytest.raises(ValueError, match='cannot copy 1 values'):
            do_copy_bools([False], [], count=1)
        with pytest.raises(ValueError, match='cannot copy 3 values'):
            do_copy_bools([False], [True, False], count=3)

    def test_argument_checks(self):
        with pytest.raises(TypeError, match='incompatible type of decoder'):
            BooleanEncoder().copy_from(Decoder(b''))
        with pytest.raises(ValueError, match='Zero-length runs'):
            BooleanEncoder().copy_from(BooleanDecoder(bytes([2, 0])))
