"""Columnar turbo commit (ISSUE-12 "melt the serial floor"): the
struct-of-arrays doc state + lazily-folded log segments must be
byte-identical to the per-doc commit loop they replace — including over
parked docs (delta-tail append, parked-prefix log indexing, revive
through `changes`) — and the fast path must run with ZERO per-doc
commit-loop iterations (the regression guard that keeps the serial
floor from creeping back).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu.columnar import decode_change, encode_change  # noqa: E402
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet.backend import (                        # noqa: E402
    DocFleet, init_docs, apply_changes_docs, park_docs)
from automerge_tpu import native                                 # noqa: E402

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='columnar commit needs the native '
                                       'codec (turbo path)')


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _chain(actor, n, start_seq=1, deps=(), key='k', base=0):
    """A linear chain of n changes for one actor, returning (buffers,
    heads) continuing from `deps`."""
    out, heads = [], list(deps)
    for i in range(n):
        buf = _change(actor, start_seq + i, start_seq + i, heads, key,
                      base + i)
        heads = [decode_change(buf)['hash']]
        out.append(buf)
    return out, heads


def _apply_rounds(fleet, handles, rounds, base_seq=1):
    for r in range(rounds):
        per_doc = [[_change(f'{d:04x}' * 4, base_seq + r, base_seq + r,
                            fleet_backend.get_heads(handles[d]),
                            f'k{r}', d * 10 + r)]
                   for d in range(len(handles))]
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
    return handles


class TestParkedColumnarCommit:
    """The delta+main write path through the columnar commit: parked
    docs' accepted buffers append to the delta tail with parked-prefix
    bases, byte-identical to the pre-refactor per-doc loop (whose
    output equals a from-scratch replay — the form we pin against)."""

    def test_parked_live_mixed_batch_byte_identical(self):
        n = 6
        fleet = DocFleet(doc_capacity=n, key_capacity=8)
        handles = _apply_rounds(fleet, init_docs(n, fleet), 2)
        # park half in-fleet (device state + causal state stay live)
        parked_idx = [0, 2, 4]
        assert park_docs([handles[i] for i in parked_idx]) == 3
        for i in parked_idx:
            assert handles[i]['state']._impl._doc_pending is not None
        # one mixed batch over every doc: parked docs take the delta
        # tail, live docs the plain columnar append — SAME fused call
        per_doc = [[_change(f'{d:04x}' * 4, 3, 3,
                            fleet_backend.get_heads(handles[d]),
                            'kx', 100 + d)] for d in range(n)]
        tails = {d: list(per_doc[d]) for d in range(n)}
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        # parked docs: chunk still parked, tail holds ONLY the delta
        for i in parked_idx:
            impl = handles[i]['state']._impl
            assert impl._doc_pending is not None
            assert list(impl._changes) == tails[i]
            assert impl._parked_n == 2
        # byte-identity: every doc's full history (revive-through-
        # `changes` for parked ones) must equal a from-scratch replay's
        for d in range(n):
            state = handles[d]['state']
            log = [bytes(b) for b in state.changes]   # materializes parked
            ref_fleet = DocFleet(doc_capacity=1, key_capacity=8)
            ref = init_docs(1, ref_fleet)
            ref, _ = apply_changes_docs(ref, [log], mirror=False)
            assert bytes(state.save()) == bytes(ref[0]['state'].save())
            assert fleet_backend.get_heads(handles[d]) == \
                fleet_backend.get_heads(ref[0])
            assert state._impl.clock == ref[0]['state']._impl.clock
            assert state._impl.max_op == ref[0]['state']._impl.max_op

    def test_parked_prefix_log_indexing_through_graph(self):
        """Deferred-graph records written by the columnar commit carry
        parked-prefix-aware bases: hash-graph queries over a parked doc
        with a delta tail must resolve every change (prefix AND tail) at
        its true log index."""
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        handles = _apply_rounds(fleet, init_docs(1, fleet), 3)
        all_hashes = [decode_change(bytes(b))['hash']
                      for b in handles[0]['state'].changes]
        assert park_docs(handles) == 1
        # two more columnar commits onto the parked doc (multi-batch
        # pending segments fold in commit order)
        for r in (3, 4):
            per_doc = [[_change('0000' * 4, r + 1, r + 1,
                                fleet_backend.get_heads(handles[0]),
                                f'k{r}', r)]]
            handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        state = handles[0]['state']
        tail_hashes = [decode_change(bytes(b))['hash']
                       for b in state._impl._changes]
        assert len(tail_hashes) == 2
        # graph query: every change retrievable by its hash, in order
        for i, h in enumerate(all_hashes + tail_hashes):
            buf = state.get_change_by_hash(h)
            assert buf is not None
            assert decode_change(bytes(buf))['hash'] == h
            assert bytes(state.changes[i]) == bytes(buf)

    def test_fold_limit_and_slot_recycling(self):
        """Past _SEAM_FOLD_LIMIT outstanding seam records the fleet
        folds everything; freed slots' pending segments die with the
        doc (a recycled slot must never inherit them)."""
        from automerge_tpu.fleet.backend import _SEAM_FOLD_LIMIT
        fleet = DocFleet(doc_capacity=4, key_capacity=8)
        handles = init_docs(2, fleet)
        heads = [[], []]
        for r in range(_SEAM_FOLD_LIMIT + 4):
            per_doc = []
            for d in range(2):
                buf = _change(f'{d:04x}' * 4, r + 1, r + 1, heads[d],
                              'k', r)
                heads[d] = [decode_change(buf)['hash']]
                per_doc.append([buf])
            handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        assert len(fleet._pend_seams) <= _SEAM_FOLD_LIMIT + 1
        assert len(handles[0]['state'].changes) == _SEAM_FOLD_LIMIT + 4
        # free doc 1 with un-folded segments pending, then recycle its slot
        handles2 = init_docs(1, fleet)
        slot_before = handles[1]['state']._impl.slot
        fleet_backend.free_docs([handles[1]])
        fresh = init_docs(1, fleet)
        assert fresh[0]['state']._impl.slot == slot_before  # recycled
        assert fresh[0]['state'].changes == []
        assert fleet_backend.get_heads(fresh[0]) == []
        chain, _ = _chain('ee' * 16, 2)
        fresh, _ = apply_changes_docs(fresh, [chain], mirror=False)
        assert [bytes(b) for b in fresh[0]['state'].changes] == \
            [bytes(b) for b in chain]
        del handles2


class TestCommitRegressionGuard:
    """The commit-phase guard (ISSUE-12 satellite): fast-path docs make
    ZERO per-doc commit-loop iterations, and the columnar commit keeps
    the O(1)-dispatch contract — the floor cannot silently creep back."""

    def test_fast_path_zero_fallback_iterations(self):
        n = 64
        fleet = DocFleet(doc_capacity=n, key_capacity=8)
        handles = init_docs(n, fleet)
        per_doc = [_chain(f'{d:04x}' * 4, 3)[0] for d in range(n)]
        handles, _ = apply_changes_docs(handles, per_doc, mirror=False)
        assert fleet.metrics.turbo_calls == 1
        assert fleet.metrics.fallbacks == 0
        assert fleet.metrics.turbo_commit_fallback_docs == 0
        # second batch (docs now hold state: gate reads the columnar
        # heads/clock) — still zero per-doc iterations
        per_doc2 = []
        for d in range(n):
            c, _ = _chain(f'{d:04x}' * 4, 2, start_seq=4,
                          deps=fleet_backend.get_heads(handles[d]), base=50)
            per_doc2.append(c)
        handles, _ = apply_changes_docs(handles, per_doc2, mirror=False)
        assert fleet.metrics.turbo_commit_fallback_docs == 0

    def test_slow_docs_are_counted(self):
        """Out-of-order delivery routes through the general gate — those
        docs DO take the per-doc tail loop and must be counted (the
        counter is the guard's tripwire, so it must actually move)."""
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        handles = init_docs(2, fleet)
        chain, _ = _chain('aa' * 16, 3)
        fast, _ = _chain('bb' * 16, 3)
        # doc 0: reversed order (causally premature head first)
        handles, _ = apply_changes_docs(
            handles, [[chain[1], chain[0], chain[2]], fast], mirror=False)
        assert fleet.metrics.turbo_commit_fallback_docs == 1
        assert [bytes(b) for b in handles[0]['state'].changes] == \
            [bytes(b) for b in chain]

    def test_seam_commit_dispatches_flat(self):
        """One device dispatch per turbo batch, independent of doc
        count — the seam_commit bench section's dispatch pin, as a
        tier-1 test."""
        for n in (8, 64):
            fleet = DocFleet(doc_capacity=n, key_capacity=8)
            handles = init_docs(n, fleet)
            d0 = fleet.metrics.dispatches
            for r in range(3):
                per_doc = []
                for d in range(n):
                    c, _ = _chain(f'{d:04x}' * 4, 1, start_seq=r + 1,
                                  deps=fleet_backend.get_heads(handles[d]),
                                  base=r)
                    per_doc.append(c)
                handles, _ = apply_changes_docs(handles, per_doc,
                                                mirror=False)
            assert fleet.metrics.dispatches - d0 == 3


class TestColumnarDocState:
    """The _DocCols property views must stay coherent through every
    writer — multi-head frontiers, lane-overflowing clocks, and the
    exact/slow paths that assign whole attributes."""

    def test_clock_lane_overflow_matches_reference(self):
        """> CLOCK_LANES actors on one doc: the commit degrades that
        doc's clock to dict mode (counted fallback) and every later
        read/gate still sees the exact reference clock."""
        from automerge_tpu.fleet.backend import _DocCols
        n_actors = _DocCols.CLOCK_LANES + 2
        actors = [f'{i:02x}' * 16 for i in range(n_actors)]
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        handles = init_docs(1, fleet)
        heads = []
        bufs = []
        for i, actor in enumerate(actors):
            buf = _change(actor, 1, i + 1, heads, f'k{i}', i)
            heads = [decode_change(buf)['hash']]
            bufs.append(buf)
        handles, _ = apply_changes_docs(handles, [bufs], mirror=False)
        assert handles[0]['state']._impl.clock == \
            {actor: 1 for actor in actors}
        assert fleet.metrics.turbo_commit_fallback_docs >= 1
        # follow-up chain by one actor still gates + commits correctly
        nxt = _change(actors[0], 2, n_actors + 1, heads, 'kz', 99)
        handles, _ = apply_changes_docs(handles, [[nxt]], mirror=False)
        clock = handles[0]['state']._impl.clock
        assert clock[actors[0]] == 2

    def test_multihead_frontier_attr_mode_gate(self):
        """Two concurrent branches -> a 2-head frontier (attr-mode
        columns); a change dep'ing on BOTH heads takes the host
        first-change compare (doc_hostcheck) and commits columnar,
        collapsing the frontier to one head."""
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        handles = init_docs(1, fleet)
        a1 = _change('aa' * 16, 1, 1, [], 'ka', 1)
        b1 = _change('bb' * 16, 1, 1, [], 'kb', 2)
        handles, _ = apply_changes_docs(handles, [[a1, b1]], mirror=False)
        heads = fleet_backend.get_heads(handles[0])
        assert len(heads) == 2 and heads == sorted(heads)
        merge = _change('aa' * 16, 2, 3, heads, 'kc', 3)
        handles, _ = apply_changes_docs(handles, [[merge]], mirror=False)
        assert fleet_backend.get_heads(handles[0]) == \
            [decode_change(merge)['hash']]
        impl = handles[0]['state']._impl
        assert fleet.doc_cols.head_n[impl.slot] == 1

    def test_exact_path_assignments_round_trip(self):
        """Whole-attribute writes (the exact/slow paths' pattern) land
        in the columns and read back exactly."""
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        impl = init_docs(1, fleet)[0]['state']._impl
        h = 'ab' * 32
        impl.heads = [h]
        assert impl.heads == [h]
        assert fleet.doc_cols.head_n[impl.slot] == 1
        assert fleet.doc_cols.head32[impl.slot].tobytes().hex() == h
        impl.heads = []
        assert impl.heads == []
        multi = sorted(['ab' * 32, 'cd' * 32])
        impl.heads = multi
        assert impl.heads == multi
        assert fleet.doc_cols.head_n[impl.slot] == -1
        impl.clock = {'aa' * 16: 3}
        assert impl.clock == {'aa' * 16: 3}
        big = {f'{i:02x}' * 16: i + 1 for i in range(9)}
        impl.clock = big
        assert impl.clock == big
        impl.max_op = 17
        assert impl.max_op == 17
        impl.stale = True
        assert impl.stale is True
        impl.binary_doc = b'xyz'
        assert impl.binary_doc == b'xyz'

    def test_shrinking_clock_assignment_clears_stale_lanes(self):
        """A SHRINKING whole-dict clock assignment (restore_all's
        rollback shape) must clear the tail lanes — a stale lane would
        hand the gate a phantom seq base and fast-commit a change the
        causal gate should queue."""
        A, B = 'aa' * 16, 'bb' * 16
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        handles = init_docs(1, fleet)
        impl = handles[0]['state']._impl
        impl.clock = {A: 1, B: 1}
        impl.clock = {A: 1}              # rollback-shaped shrink
        assert (fleet.doc_cols.ck_actor[impl.slot, 1:] == -1).all()
        assert impl.clock == {A: 1}
        # behavioral pin: B seq=2 arriving now is NOT causally ready
        # (B:1 was rolled back) — it must queue, never fast-commit
        a1 = _change(A, 1, 1, [], 'k', 1)
        impl.heads = [decode_change(a1)['hash']]
        impl._changes = [a1]
        b2 = _change(B, 2, 2, impl.heads, 'k', 2)
        handles, _ = apply_changes_docs(handles, [[b2]], mirror=False)
        assert len(handles[0]['state'].queue) == 1
        assert len(handles[0]['state'].changes) == 1

    def test_freed_engine_is_severed_from_columns(self):
        """A raw engine reference leaked across free must fail LOUDLY
        on use (slot severed), never alias the slot's next tenant."""
        fleet = DocFleet(doc_capacity=2, key_capacity=8)
        handles = init_docs(1, fleet)
        impl = handles[0]['state']._impl
        fleet_backend.free_docs(handles)
        assert impl.slot == 'freed'
        with pytest.raises((TypeError, IndexError)):
            impl.heads
        with pytest.raises((TypeError, IndexError)):
            impl.max_op = 5


class TestNoIncKernel:
    def test_noinc_kernel_matches_general(self):
        """The set-only merge kernel must produce exactly the general
        kernel's state on inc-free batches over a counter-free grid."""
        import jax
        from automerge_tpu.fleet.tensor_doc import FleetState, OpBatch
        from automerge_tpu.fleet.apply import (
            apply_op_batch, _apply_op_batch_noinc_impl)
        rng = np.random.default_rng(3)
        n_docs, n_keys, P = 16, 8, 4
        state = FleetState.empty(n_docs, n_keys)
        for _ in range(3):
            ops = OpBatch(
                rng.integers(0, n_keys, (n_docs, P)).astype(np.int32),
                rng.integers(1, 1 << 16, (n_docs, P)).astype(np.int32),
                rng.integers(1, 1 << 16, (n_docs, P)).astype(np.int32),
                np.ones((n_docs, P), bool), np.zeros((n_docs, P), bool),
                rng.random((n_docs, P)) < 0.8)
            ref, _ = apply_op_batch(state, ops)
            got, _ = jax.jit(_apply_op_batch_noinc_impl)(state, ops)
            np.testing.assert_array_equal(np.asarray(ref.winners),
                                          np.asarray(got.winners))
            np.testing.assert_array_equal(np.asarray(ref.values),
                                          np.asarray(got.values))
            np.testing.assert_array_equal(np.asarray(ref.counters),
                                          np.asarray(got.counters))
            state = ref

    def test_counters_pin_general_kernel(self):
        """The first inc lane pins the fleet to the general kernel —
        and a later set overwriting the counter resets its accumulator
        (the exact semantics the no-inc shortcut must never skip)."""
        fleet = DocFleet(doc_capacity=1, key_capacity=8)
        handles = init_docs(1, fleet)
        assert not fleet._counters_touched
        heads = []
        c1 = encode_change({
            'actor': 'aa' * 16, 'seq': 1, 'startOp': 1, 'time': 0,
            'message': '', 'deps': [],
            'ops': [{'action': 'set', 'obj': '_root', 'key': 'n',
                     'value': 5, 'datatype': 'counter', 'pred': []}]})
        heads = [decode_change(c1)['hash']]
        c2 = encode_change({
            'actor': 'aa' * 16, 'seq': 2, 'startOp': 2, 'time': 0,
            'message': '', 'deps': heads,
            'ops': [{'action': 'inc', 'obj': '_root', 'key': 'n',
                     'value': 3, 'pred': ['1@' + 'aa' * 16]}]})
        heads = [decode_change(c2)['hash']]
        handles, _ = apply_changes_docs(handles, [[c1, c2]], mirror=False)
        assert fleet._counters_touched
        assert handles[0]['state'].materialize() == {'n': 8}
        c3 = _change('aa' * 16, 3, 3, heads, 'n', 42)
        handles, _ = apply_changes_docs(handles, [[c3]], mirror=False)
        assert handles[0]['state'].materialize() == {'n': 42}
