"""Fleet-scale sync fabric (ISSUE-16): per-peer sentHashes as peer-spaces
in the shared frontier table, fused generate/receive dispatches across
every live link, and the satellites that ride the same plane.

The load-bearing contracts pinned here:

- Fused multi-peer rounds are BYTE-IDENTICAL to the classic per-peer
  loop — across host backends, lww fleet docs, and exact-device fleet
  docs, including a mid-round disconnect/reconnect (released peer-space,
  fresh space id, full resend) and a promoted host doc riding a mixed
  batch.
- Dispatch counts per round are FLAT in the link count: 16 links and
  1024 links cost the same number of hashindex + Bloom kernel launches.
- Disconnect/reset release their peer-space everywhere the sync states
  die (service close/release/reset, cluster pair reset) — space ids are
  never reused, so a reconnecting peer can never inherit its
  predecessor's sent set.
- The batched SYNC path feeds doc recency into the ClockDemote ring
  (sync-hot docs are not demotion fodder), and `max_chain` escalation
  routes through the CostModel ledger with flight-recorded verdict
  flips.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from automerge_tpu import backend as Backend                     # noqa: E402
from automerge_tpu import native                                 # noqa: E402
from automerge_tpu.backend import init_sync_state                # noqa: E402
from automerge_tpu.backend.sync import (                         # noqa: E402
    generate_sync_message, receive_sync_message)
from automerge_tpu.columnar import (                             # noqa: E402
    decode_change_meta, encode_change)
from automerge_tpu.fleet import backend as fleet_backend         # noqa: E402
from automerge_tpu.fleet import bloom as fleet_bloom             # noqa: E402
from automerge_tpu.fleet import hashindex                        # noqa: E402
from automerge_tpu.fleet.backend import (                        # noqa: E402
    DocFleet, apply_changes_docs, init_docs)
from automerge_tpu.fleet.hashindex import (                      # noqa: E402
    PeerSentSet, release_sync_state)
from automerge_tpu.fleet.sync_driver import (                    # noqa: E402
    generate_sync_messages_docs, receive_sync_messages_docs)

needs_native = pytest.mark.skipif(
    not native.available(), reason='fleet modes ride the turbo path')


def _change(actor, seq, start_op, deps, key, val):
    return encode_change({
        'actor': actor, 'seq': seq, 'startOp': start_op, 'time': 0,
        'message': '', 'deps': list(deps),
        'ops': [{'action': 'set', 'obj': '_root', 'key': key,
                 'value': val, 'datatype': 'int', 'pred': []}]})


def _doc_change_rows(n, per_doc=2):
    """Per-doc linear change chains as raw bytes — both universes of a
    differential run are built from the SAME bytes."""
    rows = []
    for i in range(n):
        deps, row = [], []
        for s in range(1, per_doc + 1):
            buf = _change(f'{i:02x}' * 16, s, s, deps, f'd{i}', s)
            deps = [decode_change_meta(buf, True)['hash']]
            row.append(buf)
        rows.append(row)
    return rows


def _peer_change_rows(n, k):
    """One private root change per (doc, peer) link — traffic flows both
    directions of every link."""
    return [[[_change(f'{0xa0 + i:02x}{j:02x}' * 8, 1, 1, [],
                      f'p{i}_{j}', 100 * i + j)]
             for j in range(k)] for i in range(n)]


def _host_doc(change_rows):
    b = Backend.init()
    b, _ = Backend.apply_changes(b, list(change_rows))
    return b


def _build_universe(mode, doc_rows, peer_rows, fused):
    """One complete sync universe: server docs (host backends or fleet
    handles), per-link sync states, and host peer replicas."""
    if mode == 'host':
        docs = [_host_doc(row) for row in doc_rows]
    else:
        fleet = DocFleet(exact_device=(mode == 'exact'))
        docs = init_docs(len(doc_rows), fleet)
        docs, _ = apply_changes_docs(docs, doc_rows, mirror=False)
        if fused:
            fleet.frontier_index(device_min=1)   # force the device table
    n, k = len(doc_rows), len(peer_rows[0])
    states = [[init_sync_state() for _ in range(k)] for _ in range(n)]
    peers = [[_host_doc(peer_rows[i][j]) for j in range(k)]
             for i in range(n)]
    peer_states = [[init_sync_state() for _ in range(k)]
                   for _ in range(n)]
    return docs, states, peers, peer_states


def _drive_rounds(docs, states, peers, peer_states, fused, rounds,
                  on_round=None):
    """Drive `rounds` full sync rounds over every (doc, peer) link;
    fused=True batches the server side exactly like the exchange fabric
    (one generate dispatch set per round, receive in transpose waves
    over distinct dst docs); fused=False is the classic per-peer loop.
    Returns the byte transcript of every server and peer message."""
    n, k = len(docs), len(peers[0])
    transcript = []
    for r in range(rounds):
        if on_round is not None:
            on_round(r, states, peers, peer_states)
        # --- server generate (the fabric's fused half) ---
        if fused:
            flat_docs = [docs[i] for i in range(n) for _ in range(k)]
            flat_states = [states[i][j]
                           for i in range(n) for j in range(k)]
            new_states, flat_msgs = generate_sync_messages_docs(
                flat_docs, flat_states)
            out = [[None] * k for _ in range(n)]
            for idx in range(n * k):
                i, j = divmod(idx, k)
                states[i][j] = new_states[idx]
                out[i][j] = flat_msgs[idx]
        else:
            out = [[None] * k for _ in range(n)]
            for i in range(n):
                for j in range(k):
                    states[i][j], out[i][j] = generate_sync_message(
                        docs[i], states[i][j])
        transcript.append([[None if m is None else bytes(m)
                            for m in row] for row in out])
        # --- peers receive + reply (classic host loop, both universes) ---
        replies = [[None] * k for _ in range(n)]
        for i in range(n):
            for j in range(k):
                if out[i][j] is not None:
                    peers[i][j], peer_states[i][j], _ = \
                        receive_sync_message(peers[i][j],
                                             peer_states[i][j], out[i][j])
                peer_states[i][j], replies[i][j] = generate_sync_message(
                    peers[i][j], peer_states[i][j])
        transcript.append([[None if m is None else bytes(m)
                            for m in row] for row in replies])
        # --- server receive ---
        if fused:
            queues = {i: [(j, replies[i][j]) for j in range(k)
                          if replies[i][j] is not None]
                      for i in range(n)}
            queues = {i: q for i, q in queues.items() if q}
            while queues:
                wave = [(i, q.pop(0)) for i, q in queues.items()]
                new_docs, new_states, _p = receive_sync_messages_docs(
                    [docs[i] for i, _ in wave],
                    [states[i][j] for i, (j, _m) in wave],
                    [m for _i, (_j, m) in wave])
                for (i, (j, _m)), doc, state in zip(wave, new_docs,
                                                    new_states):
                    docs[i] = doc
                    states[i][j] = state
                queues = {i: q for i, q in queues.items() if q}
        else:
            for i in range(n):
                for j in range(k):
                    if replies[i][j] is not None:
                        docs[i], states[i][j], _ = receive_sync_message(
                            docs[i], states[i][j], replies[i][j])
    return transcript


def _heads(doc):
    if isinstance(doc, dict) and 'heads' in doc:
        return sorted(doc['heads'])
    return sorted(Backend.get_heads(doc))


class TestFusedByteIdentity:
    """Tentpole contract: the fused fabric is byte-identical on the wire
    to the classic per-peer protocol loop, in every engine mode."""

    @pytest.mark.parametrize('mode', ['host', 'lww', 'exact'])
    def test_multi_peer_rounds_with_mid_round_disconnect(self, mode):
        if mode != 'host' and not native.available():
            pytest.skip('fleet modes ride the turbo path')
        n, k, rounds = 3, 3, 6
        doc_rows = _doc_change_rows(n)
        peer_rows = _peer_change_rows(n, k)
        released = {}

        def disconnect(r, states, peers, peer_states):
            # round 3: link (0, 1) drops mid-conversation and the peer
            # comes back having LOST its replica — both ends handshake
            # from fresh states and the server must resend everything
            # through a brand-new peer-space
            if r != 3:
                return
            old = states[0][1].get('sentHashes')
            if isinstance(old, PeerSentSet):
                released['ps'] = old
            release_sync_state(states[0][1])
            states[0][1] = init_sync_state()
            peers[0][1] = Backend.init()
            peer_states[0][1] = init_sync_state()

        fused_u = _build_universe(mode, doc_rows, peer_rows, fused=True)
        classic_u = _build_universe(mode, doc_rows, peer_rows, fused=False)
        t_fused = _drive_rounds(*fused_u, fused=True, rounds=rounds,
                                on_round=disconnect)
        t_classic = _drive_rounds(*classic_u, fused=False, rounds=rounds,
                                  on_round=disconnect)
        assert t_fused == t_classic     # every message, every round
        docs_f, states_f, peers_f, _ = fused_u
        docs_c, _, peers_c, _ = classic_u
        for i in range(n):
            assert _heads(docs_f[i]) == _heads(docs_c[i])
            for j in range(k):
                assert _heads(peers_f[i][j]) == _heads(peers_c[i][j])
                assert _heads(peers_f[i][j]) == _heads(docs_f[i])
        if mode == 'host':
            return
        # every member link that sent changes promoted to a peer-space,
        # and the dropped link's old space died with the disconnect —
        # its reconnect re-promoted into a FRESH (higher) space id
        sent = [states_f[i][j]['sentHashes']
                for i in range(n) for j in range(k)]
        assert all(isinstance(s, PeerSentSet) for s in sent)
        assert len({s.sid for s in sent}) == n * k   # one space per link
        old = released['ps']
        assert not old.alive
        assert not old.table._live[old.sid]
        assert states_f[0][1]['sentHashes'].sid > old.sid
        # converged fleet twins save byte-identically
        for df, dc in zip(docs_f, docs_c):
            assert bytes(df['state'].save()) == bytes(dc['state'].save())

    @needs_native
    def test_promoted_host_doc_rides_mixed_batch(self):
        """One doc promoted OFF the fleet (CTR_LIMIT-overflow op) rides
        the same fused multi-peer round as its fleet neighbours —
        byte-identical to the classic loop, fleet links still promote
        their sentHashes, the straggler keeps a plain set."""
        from automerge_tpu.fleet.tensor_doc import CTR_LIMIT
        n, k = 3, 2
        doc_rows = _doc_change_rows(n)
        peer_rows = _peer_change_rows(n, k)
        universes = []
        for fused in (True, False):
            docs, states, peers, peer_states = _build_universe(
                'lww', doc_rows, peer_rows, fused)
            big = encode_change({
                'actor': 'dd' * 16, 'seq': 1, 'startOp': CTR_LIMIT + 10,
                'time': 0, 'message': '', 'deps': list(docs[0]['heads']),
                'ops': [{'action': 'makeText', 'obj': '_root',
                         'key': 'deep', 'pred': []}]})
            docs, _ = apply_changes_docs(
                docs, [[big]] + [[] for _ in docs[1:]], mirror=False)
            assert not docs[0]['state'].is_fleet
            assert all(d['state'].is_fleet for d in docs[1:])
            universes.append((docs, states, peers, peer_states))
        t_fused = _drive_rounds(*universes[0], fused=True, rounds=5)
        t_classic = _drive_rounds(*universes[1], fused=False, rounds=5)
        assert t_fused == t_classic
        docs_f, states_f, _peers, _ps = universes[0]
        for j in range(k):
            assert isinstance(states_f[0][j]['sentHashes'], set)
            assert isinstance(states_f[1][j]['sentHashes'], PeerSentSet)


@needs_native
class TestDispatchPins:
    def test_generate_round_dispatches_flat_16_vs_1024_links(self):
        """The fabric's O(1)-dispatch property: a steady-state generate
        round over N links costs the SAME number of hashindex + Bloom
        kernel launches at 16 links as at 1024."""
        deltas = {}
        for n_links in (16, 1024):
            fleet = DocFleet()
            handles = init_docs(1, fleet)
            rows = _doc_change_rows(1, per_doc=3)
            handles, _ = apply_changes_docs(handles, rows, mirror=False)
            fleet.frontier_index(device_min=1)
            # every link's peer solicits a full resend (empty bloom):
            # the cold round sends changes on all links, staging and
            # promoting each link's sentHashes to a peer-space
            states = []
            for _ in range(n_links):
                s = init_sync_state()
                s['theirHeads'] = []
                s['theirHave'] = [{'lastSync': [], 'bloom': b''}]
                s['theirNeed'] = []
                states.append(s)
            flat = [handles[0]] * n_links
            states, msgs = generate_sync_messages_docs(flat, states)
            assert all(m is not None for m in msgs)
            assert all(isinstance(s['sentHashes'], PeerSentSet)
                       for s in states)
            # round 2 (steady state): the sent filter rides the FUSED
            # peer-space probe across all links at once
            h0 = hashindex.dispatch_count()
            b0 = fleet_bloom.dispatch_count()
            states, msgs = generate_sync_messages_docs(flat, states)
            deltas[n_links] = (hashindex.dispatch_count() - h0,
                               fleet_bloom.dispatch_count() - b0)
            assert all(m is not None for m in msgs)
        assert deltas[16] == deltas[1024], \
            f'dispatches scale with links: {deltas}'
        assert sum(deltas[16]) <= 8     # a round is a handful, not O(links)

    def test_probe_window_env_and_setter(self):
        from automerge_tpu.fleet.hashindex import (probe_window,
                                                   set_probe_window)
        base = probe_window()
        prev = set_probe_window(8)
        try:
            assert prev == base
            assert probe_window() == 8
            # clamped to the legal range
            set_probe_window(10 ** 9)
            assert probe_window() == 1024
            # correctness is window-independent
            for width in (1, 8, 64):
                set_probe_window(width)
                ix = hashindex.HashIndex(capacity=8, device_min=1)
                sid = ix.new_space()
                import hashlib
                keys = [hashlib.sha256(bytes([i])).hexdigest()
                        for i in range(12)]
                ix.insert(sid, keys[:9])
                got = ix.probe(sid, keys).tolist()
                assert got == [True] * 9 + [False] * 3
        finally:
            set_probe_window(base)


@needs_native
class TestReleaseWiring:
    """Every path that drops a sync state hands its peer-space back."""

    def _serve_until_promoted(self, svc, session, client, state,
                              max_rounds=8):
        for _ in range(max_rounds):
            state, msg = generate_sync_message(client, state)
            t = svc.submit(session, 'sync', msg)
            svc.pump()
            assert t.status == 'ok'
            if t.result is not None:
                client, state, _ = receive_sync_message(
                    client, state, t.result)
            if isinstance(session.sync_state.get('sentHashes'),
                          PeerSentSet):
                return client, state
        pytest.fail('session sentHashes never promoted to a peer-space')

    def _service(self):
        from automerge_tpu.service import DocService
        fleet = DocFleet(doc_capacity=8, key_capacity=64)
        svc = DocService(fleet=fleet, tenant_rate=10_000.0,
                         tenant_burst=1000.0)
        fleet.frontier_index(device_min=1)
        return svc, fleet

    def test_service_reset_and_close_release_peer_spaces(self):
        svc, fleet = self._service()
        table = fleet.frontier_index().table
        session = svc.open_session('t0')
        t = svc.submit(session, 'apply',
                       [_change('aa' * 16, 1, 1, [], 'k', 7)])
        svc.pump()
        assert t.status == 'ok'
        client, state = self._serve_until_promoted(
            svc, session, Backend.init(), init_sync_state())
        old = session.sync_state['sentHashes']
        old_sid = old.sid
        # client reconnect with reset=True: fresh handshake, the old
        # link's space handed back NOW (not at GC)
        state = init_sync_state()
        state, msg = generate_sync_message(client, state)
        t = svc.submit(session, 'sync', msg, reset=True)
        svc.pump()
        assert t.status == 'ok'
        assert not old.alive and not table._live[old_sid]
        assert not isinstance(session.sync_state.get('sentHashes'),
                              PeerSentSet) or \
            session.sync_state['sentHashes'].sid > old_sid
        # new server-side content so the reconnected link sends again
        # (lazy promotion: a quiet link never re-promotes) — then
        # close_session releases whatever the session holds
        t = svc.submit(session, 'apply',
                       [_change('aa' * 16, 2, 2,
                                list(session.handle['heads']), 'k', 8)])
        svc.pump()
        assert t.status == 'ok'
        client2, state2 = self._serve_until_promoted(
            svc, session, client, state)
        ps2 = session.sync_state['sentHashes']
        svc.close_session(session)
        assert not ps2.alive and not table._live[ps2.sid]

    def test_cluster_pair_reset_releases_both_spaces(self):
        from automerge_tpu.shard.cluster import _Tenant
        ix = hashindex.HashIndex(capacity=16, device_min=1)
        a = PeerSentSet(ix)
        b = PeerSentSet(ix)
        a.add('ab' * 32)
        a.flush()

        class _Rec:
            pass

        rec = _Rec()
        rec.state_home = dict(init_sync_state(), sentHashes=a)
        rec.state_rep = dict(init_sync_state(), sentHashes=b)
        _Tenant._reset_pair(rec)
        assert not a.alive and not b.alive
        assert not ix._live[a.sid] and not ix._live[b.sid]
        assert isinstance(rec.state_home['sentHashes'], set)
        assert isinstance(rec.state_rep['sentHashes'], set)
        assert rec.inbox_home == [] and rec.inbox_rep == []

    def test_sync_serve_touches_demote_ring(self):
        """Satellite: the batched SYNC path stamps access recency, so a
        read-mostly doc answering handshakes is not demotion fodder."""
        from automerge_tpu.service import DocService

        class _FakeDemote:
            def __init__(self):
                self.registered, self.touched = [], []

            def register(self, handles):
                self.registered.extend(handles)

            def touch(self, handles):
                self.touched.extend(handles)

        class _FakeTiering:
            demote = None

            def tick(self, **kw):
                pass

        tiering = _FakeTiering()
        tiering.demote = _FakeDemote()
        svc = DocService(fleet=DocFleet(doc_capacity=8, key_capacity=64),
                         tiering=tiering, tenant_rate=10_000.0,
                         tenant_burst=1000.0)
        session = svc.open_session('t0')
        t = svc.submit(session, 'apply',
                       [_change('ab' * 16, 1, 1, [], 'k', 1)])
        svc.pump()
        assert t.status == 'ok'
        state, msg = generate_sync_message(Backend.init(),
                                           init_sync_state())
        t = svc.submit(session, 'sync', msg)
        svc.pump()
        assert t.status == 'ok'
        assert session.handle in tiering.demote.registered
        assert session.handle in tiering.demote.touched


class _StubDurable:
    def __init__(self, segments, tail_bytes, base):
        self._debt = {'segments': segments, 'bytes': tail_bytes}
        self._base = base

    def chain_debt(self):
        return dict(self._debt)

    def base_bytes(self):
        return self._base


class TestChainEscalationLedger:
    """Satellite: `max_chain` escalation routes through the CostModel —
    stitch debt (tail bytes + per-segment overhead) vs full-rewrite
    cost, pressure-scaled, verdict flips flight-recorded."""

    def _model(self):
        from automerge_tpu.fleet.tiering import CostModel
        return CostModel()

    def test_empty_chain_never_fires(self):
        m = self._model()
        assert m.chain_escalate_due(_StubDurable(0, 0, 1 << 20)) is False

    def test_stitch_debt_dominating_rewrite_fires(self):
        m = self._model()
        # tail ~= base: benefit 2x bytes + per-segment overhead beats
        # the (base + tail) rewrite
        dur = _StubDurable(4, 1 << 20, 1 << 20)
        assert m.chain_escalate_due(dur) is True

    def test_huge_base_defers_escalation(self):
        m = self._model()
        # one tiny segment over a huge base: rewriting everything to
        # retire 1KB of stitch debt never pays
        dur = _StubDurable(1, 1 << 10, 100 << 20)
        assert m.chain_escalate_due(dur) is False

    def test_many_tiny_segments_fire_on_stitch_overhead(self):
        m = self._model()
        # bytes alone would not justify it; the per-segment open/
        # validate overhead does
        dur = _StubDurable(32, 16 << 10, 64 << 10)
        assert m.chain_escalate_due(dur) is True

    def test_pressure_defers_and_flight_records_the_flip(self):
        from automerge_tpu.observability import recorder
        m = self._model()
        dur = _StubDurable(4, 1 << 20, 1 << 20)
        assert m.chain_escalate_due(dur, stage=0) is True
        recorder.clear_events()
        # stage 2: the write-cost bar rises ~8x; same debt now defers,
        # and the verdict FLIP lands in the flight ring
        assert m.chain_escalate_due(dur, stage=2) is False
        evs = [e for e in recorder.recent_events()
               if e['kind'] == 'tiering' and e.get('action') == 'chain']
        assert evs and evs[-1]['verdict'] == 'defer'
        assert evs[-1]['stage'] == 2

    def test_compact_escalates_early_when_ledger_says_so(self, tmp_path):
        """Integration: a DurableFleet whose attached model deems the
        chain's stitch debt due checkpoints EARLY (chain collapses to a
        fresh base) while max_chain stays the hard backstop."""
        from automerge_tpu.fleet.durability import DurableFleet
        path = str(tmp_path / 'dur')
        mgr = DurableFleet(path, max_chain=8)

        def grow(handles, round_no):
            per_doc = [[_change(f'{i:02x}' * 16, round_no, round_no,
                                fleet_backend.get_heads(h),
                                'k', round_no)]
                       for i, h in enumerate(handles)]
            out, _patches, errors = mgr.apply_changes(handles, per_doc)
            assert not any(errors)
            return out

        handles = mgr.init_docs(2)
        handles = grow(handles, 1)
        assert mgr.maybe_compact(force=True)        # cuts the base
        handles = grow(handles, 2)
        assert mgr.maybe_compact(force=True)        # first segment
        assert len(mgr.chain) == 2

        class _Always:
            def chain_escalate_due(self, durable, stage=0):
                return True

        mgr.cost_model = _Always()
        handles = grow(handles, 3)
        assert mgr.maybe_compact(force=True)
        assert len(mgr.chain) == 1      # escalated well before max_chain

        class _Never:
            def chain_escalate_due(self, durable, stage=0):
                return False

        mgr.cost_model = _Never()
        for r in range(4, 7):
            handles = grow(handles, r)
            mgr.maybe_compact(force=True)
        assert len(mgr.chain) == 4      # ledger says wait: chain grows
        mgr.close()
