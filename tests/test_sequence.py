"""Device sequence-engine tests: RGA ordering unit cases mirroring reference
test/new_backend_test.js:725-880 (same-position and head concurrent inserts),
plus differential fuzzing against the full host engine (public API with
multi-actor Text editing and merge) — the wasm.js-style cross-implementation
harness, with the host OpSet as the oracle."""

import random

import numpy as np
import pytest

import automerge_tpu as A
from automerge_tpu.columnar import decode_change
from automerge_tpu.fleet.sequence import (
    DEL, INSERT, PAD, SET, SeqEncoder, SeqOpBatch, SeqState,
    apply_seq_batch, linearize, materialize, visible_text)

A1, A2, A3 = '01234567', '89abcdef', 'fedcba98'


def run_ops(per_doc_ops, actors, capacity=64):
    enc = SeqEncoder(actors)
    batch = enc.batch(per_doc_ops)
    state = SeqState.empty(len(per_doc_ops), capacity)
    state, applied = apply_seq_batch(state, batch)
    return state


def ins(ref, op_id, ch):
    return {'kind': 'insert', 'ref': ref, 'id': op_id, 'value': ord(ch)}


class TestRGAOrdering:
    def test_typewriter(self):
        ops = [ins('_head', f'2@{A1}', 'h'), ins(f'2@{A1}', f'3@{A1}', 'i')]
        state = run_ops([ops], [A1])
        assert visible_text(state) == ['hi']

    def test_same_position_concurrent(self):
        """Concurrent siblings at the same insertion point order descending
        by opId (ref new.js:145-163); asserted against the host oracle
        rather than hand-derived."""
        ops = [ins('_head', f'2@{A1}', 'a'),
               ins(f'2@{A1}', f'3@{A1}', 'c'),
               ins(f'2@{A1}', f'3@{A2}', 'b')]
        state = run_ops([ops], [A1, A2])
        # Host oracle on identical ops
        assert visible_text(state) == [host_text(ops, [A1, A2])]

    def test_head_concurrent(self):
        ops = [ins('_head', f'2@{A1}', 'd'),
               ins('_head', f'3@{A1}', 'c'),
               ins('_head', f'3@{A2}', 'a'),
               ins(f'3@{A2}', f'4@{A2}', 'b')]
        state = run_ops([ops], [A1, A2])
        assert visible_text(state) == [host_text(ops, [A1, A2])]

    def test_delete(self):
        ops = [ins('_head', f'2@{A1}', 'h'),
               ins(f'2@{A1}', f'3@{A1}', 'x'),
               ins(f'3@{A1}', f'4@{A1}', 'i'),
               {'kind': 'del', 'target': f'3@{A1}', 'id': f'5@{A1}'}]
        state = run_ops([ops], [A1])
        assert visible_text(state) == ['hi']

    def test_set_updates_value(self):
        ops = [ins('_head', f'2@{A1}', 'a'),
               ins(f'2@{A1}', f'3@{A1}', 'b'),
               {'kind': 'set', 'target': f'3@{A1}', 'id': f'4@{A1}',
                'value': ord('B')}]
        state = run_ops([ops], [A1])
        assert visible_text(state) == ['aB']

    def test_insert_after_deleted_elem(self):
        ops = [ins('_head', f'2@{A1}', 'a'),
               {'kind': 'del', 'target': f'2@{A1}', 'id': f'3@{A1}'},
               ins(f'2@{A1}', f'4@{A1}', 'b')]
        state = run_ops([ops], [A1])
        assert visible_text(state) == ['b']

    def test_multiple_docs_independent(self):
        doc0 = [ins('_head', f'2@{A1}', 'x')]
        doc1 = [ins('_head', f'2@{A1}', 'a'), ins(f'2@{A1}', f'3@{A1}', 'b'),
                ins(f'3@{A1}', f'4@{A1}', 'c')]
        doc2 = []
        state = run_ops([doc0, doc1, doc2], [A1])
        assert visible_text(state) == ['x', 'abc', '']

    def test_incremental_batches(self):
        """State carries correctly across separate apply_seq_batch calls."""
        enc = SeqEncoder([A1, A2])
        state = SeqState.empty(1, 64)
        b1 = enc.batch([[ins('_head', f'2@{A1}', 'a'),
                         ins(f'2@{A1}', f'3@{A1}', 'c')]])
        state, _ = apply_seq_batch(state, b1)
        b2 = enc.batch([[ins(f'2@{A1}', f'3@{A2}', 'b')]])
        state, _ = apply_seq_batch(state, b2)
        ops = [ins('_head', f'2@{A1}', 'a'), ins(f'2@{A1}', f'3@{A1}', 'c'),
               ins(f'2@{A1}', f'3@{A2}', 'b')]
        assert visible_text(state) == [host_text(ops, [A1, A2])]

    def test_capacity_overflow_drops_and_reports(self):
        """Inserts past capacity are dropped (not silently corrupting), and
        the applied-count stat exposes the overflow."""
        ops = [ins('_head' if i == 0 else f'{i + 1}@{A1}', f'{i + 2}@{A1}',
                   chr(ord('a') + i)) for i in range(6)]
        enc = SeqEncoder([A1])
        state = SeqState.empty(1, 4)
        state, applied = apply_seq_batch(state, enc.batch([ops]))
        assert int(applied) == 4  # two inserts dropped
        assert visible_text(state) == ['abcd']

    def test_unknown_target_is_dropped(self):
        """Ops referencing an elemId absent from the doc (e.g. one dropped by
        overflow) are dropped and reported, not resolved to slot 0."""
        ops = [ins('_head', f'2@{A1}', 'a'),
               {'kind': 'del', 'target': f'99@{A1}', 'id': f'3@{A1}'},
               ins(f'98@{A1}', f'4@{A1}', 'z')]
        enc = SeqEncoder([A1])
        state = SeqState.empty(1, 8)
        state, applied = apply_seq_batch(state, enc.batch([ops]))
        assert int(applied) == 1
        assert visible_text(state) == ['a']

    def test_concurrent_sets_keep_both_values(self):
        """Two actors concurrently overwrite the same element: both ops stay
        in the element's visible register (multi-value conflict), the
        Lamport winner renders, and the row is NOT inexact (ref
        new.js:1204-1217 succ visibility rule)."""
        from automerge_tpu.fleet.sequence import element_conflicts
        ops = [ins('_head', f'2@{A1}', 'a'),
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A1}',
                'value': ord('X'), 'pred': [f'2@{A1}']},
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A2}',
                'value': ord('Y'), 'pred': [f'2@{A1}']}]
        state = run_ops([ops], [A1, A2])
        assert not bool(np.asarray(state.inexact)[0])
        # winner: same counter 3, higher actor hex (A2='89abcdef' > A1)
        assert visible_text(state) == ['Y']
        enc = SeqEncoder([A1, A2])
        conf = element_conflicts(state, 0)
        assert conf == {enc.pack(f'2@{A1}'): {
            enc.pack(f'3@{A1}'): ord('X'), enc.pack(f'3@{A2}'): ord('Y')}}

    def test_concurrent_set_vs_del_resurrects(self):
        """A set racing a delete of the same element: the delete kills only
        its pred, the concurrent set survives — element stays visible with
        the set's value, exactly (ref test/new_backend_test.js:1660), and
        the row is NOT inexact."""
        for del_last in (False, True):
            edits = [
                {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A1}',
                 'value': ord('Z'), 'pred': [f'2@{A1}']},
                {'kind': 'del', 'target': f'2@{A1}', 'id': f'3@{A2}',
                 'pred': [f'2@{A1}']}]
            if del_last:
                edits.reverse()
            ops = [ins('_head', f'2@{A1}', 'a')] + edits
            state = run_ops([ops], [A1, A2])
            assert not bool(np.asarray(state.inexact)[0])
            assert visible_text(state) == ['Z']

    def test_conflict_then_overwrite_multi_pred(self):
        """Resolving a two-op conflict preds BOTH visible ops: the new set
        kills both lanes and becomes the sole visible value."""
        ops = [ins('_head', f'2@{A1}', 'a'),
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A1}',
                'value': ord('X'), 'pred': [f'2@{A1}']},
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A2}',
                'value': ord('Y'), 'pred': [f'2@{A1}']},
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'4@{A1}',
                'value': ord('R'), 'pred': [f'3@{A1}', f'3@{A2}']}]
        state = run_ops([ops], [A1, A2])
        from automerge_tpu.fleet.sequence import element_conflicts
        assert not bool(np.asarray(state.inexact)[0])
        assert visible_text(state) == ['R']
        assert element_conflicts(state, 0) == {}

    def test_concurrent_dels_both_kill(self):
        """Two concurrent deletes of one element: idempotent, element gone,
        row exact."""
        ops = [ins('_head', f'2@{A1}', 'a'), ins(f'2@{A1}', f'3@{A1}', 'b'),
               {'kind': 'del', 'target': f'2@{A1}', 'id': f'4@{A1}',
                'pred': [f'2@{A1}']},
               {'kind': 'del', 'target': f'2@{A1}', 'id': f'4@{A2}',
                'pred': [f'2@{A1}']}]
        state = run_ops([ops], [A1, A2])
        assert not bool(np.asarray(state.inexact)[0])
        assert visible_text(state) == ['b']

    def test_self_overwrite_without_pred_flags_inexact(self):
        """An actor overwriting an element without pred'ing its own visible
        op (only constructible by hand-built changes) leaves the exact
        shape: flagged, reads route to the mirror."""
        ops = [ins('_head', f'2@{A1}', 'a'),
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'3@{A1}',
                'value': ord('X'), 'pred': [f'2@{A1}']},
               {'kind': 'set', 'target': f'2@{A1}', 'id': f'4@{A1}',
                'value': ord('Y'), 'pred': []}]
        state = run_ops([ops], [A1])
        assert bool(np.asarray(state.inexact)[0])

    def test_linearize_positions(self):
        from automerge_tpu.fleet.sequence import SLOT0
        ops = [ins('_head', f'2@{A1}', 'a'), ins(f'2@{A1}', f'3@{A1}', 'b')]
        state = run_ops([ops], [A1])
        pos, n = linearize(state)
        pos, n = np.asarray(pos), np.asarray(n)
        assert n[0] == 2
        # pos is indexed by node id; slots allocate from SLOT0 in op order
        assert pos[0, SLOT0] == 0 and pos[0, SLOT0 + 1] == 1


def host_text(seq_ops, actors, key='text'):
    """Oracle: run the same elemId-level ops through the host OpSet engine,
    one single-op change per op (deps = current heads, so any stream order
    that respects per-elem causality is a valid causal order)."""
    from automerge_tpu.backend.op_set import OpSet
    from automerge_tpu.columnar import encode_change
    backend = OpSet()
    make = {'actor': actors[0], 'seq': 1, 'startOp': 1, 'time': 0, 'deps': [],
            'ops': [{'action': 'makeText', 'obj': '_root', 'key': key,
                     'insert': False, 'pred': []}]}
    obj = f'1@{actors[0]}'
    backend.apply_changes([encode_change(make)])
    seqs = {a: (2 if a == actors[0] else 1) for a in actors}
    for op in seq_ops:
        ctr_s, _, actor = op['id'].partition('@')
        if op['kind'] == 'insert':
            o = {'action': 'set', 'obj': obj, 'elemId': op['ref'],
                 'insert': True, 'value': chr(op['value']), 'pred': []}
        elif op['kind'] == 'set':
            o = {'action': 'set', 'obj': obj, 'elemId': op['target'],
                 'insert': False, 'value': chr(op['value']),
                 'pred': [op['target']]}
        else:
            o = {'action': 'del', 'obj': obj, 'elemId': op['target'],
                 'insert': False, 'pred': [op['target']]}
        change = {'actor': actor, 'seq': seqs[actor], 'startOp': int(ctr_s),
                  'time': 0, 'deps': list(backend.heads), 'ops': [o]}
        seqs[actor] += 1
        backend.apply_changes([encode_change(change)])
    return patch_text(backend.get_patch(), key)


def patch_text(patch, key='text'):
    """Fold a whole-document patch's text edits into a string."""
    props = patch['diffs'].get('props', {})
    if key not in props or not props[key]:
        return ''
    obj_patch = next(iter(props[key].values()))
    chars = []
    for edit in obj_patch.get('edits', []):
        if edit['action'] == 'insert':
            chars.insert(edit['index'], edit['value']['value'])
        elif edit['action'] == 'multi-insert':
            for i, v in enumerate(edit['values']):
                chars.insert(edit['index'] + i, v)
        elif edit['action'] == 'update':
            chars[edit['index']] = edit['value']['value']
        elif edit['action'] == 'remove':
            del chars[edit['index']:edit['index'] + edit['count']]
    return ''.join(str(c) for c in chars)


class TestDifferentialFuzz:
    """Multi-actor Text editing through the public API as oracle; the same
    ops (recovered from the merged doc's change log) through the device
    sequence engine (wasm.js-pattern differential harness)."""

    def _device_ops_from_doc(self, doc):
        """Decode the merged doc's changes back to elemId-level seq ops."""
        changes = A.get_all_changes(doc)
        text_obj = None
        seq_ops = []
        actors = set()
        for buf in changes:
            change = decode_change(buf)
            actors.add(change['actor'])
            for idx, op in enumerate(change['ops']):
                if op['action'] == 'makeText' and op.get('obj') == '_root':
                    # the single text object in these fuzz docs
                    text_obj = f"{change['startOp'] + idx}@{change['actor']}"
                    continue
                if text_obj is None or op.get('obj') != text_obj:
                    continue
                op_id = f"{change['startOp'] + idx}@{change['actor']}"
                if op['action'] == 'set' and op.get('insert'):
                    seq_ops.append({'kind': 'insert', 'ref': op['elemId'],
                                    'id': op_id, 'value': ord(op['value'])})
                elif op['action'] == 'set':
                    seq_ops.append({'kind': 'set', 'target': op['elemId'],
                                    'id': op_id, 'value': ord(op['value']),
                                    'pred': op.get('pred')})
                elif op['action'] == 'del':
                    seq_ops.append({'kind': 'del', 'target': op['elemId'],
                                    'id': op_id, 'pred': op.get('pred')})
        return seq_ops, actors

    @pytest.mark.parametrize('seed', [0, 1, 2])
    def test_random_trace_matches_public_api(self, seed):
        rng = random.Random(seed)
        actors = [A1, A2, A3]
        base = A.from_({'text': A.Text()}, actors[0])
        docs = [base] + [A.merge(A.init(a), base) for a in actors[1:]]
        alphabet = 'abcdefghijklmnopqrstuvwxyz'

        for round_ in range(6):
            for i in range(len(docs)):
                for _ in range(rng.randrange(0, 4)):
                    def edit(d, rng=rng):
                        t = d['text']
                        roll = rng.random()
                        if len(t) and roll < 0.3:
                            t.delete_at(rng.randrange(len(t)))
                        elif len(t) and roll < 0.5:
                            # overwrites: merged replicas produce the
                            # concurrent set-vs-set / set-vs-del shapes the
                            # element registers must resolve exactly
                            t.set(rng.randrange(len(t)),
                                  rng.choice(alphabet).upper())
                        else:
                            t.insert_at(rng.randrange(len(t) + 1),
                                        rng.choice(alphabet))
                    docs[i] = A.change(docs[i], edit)
            # random pairwise merge
            i, j = rng.sample(range(len(docs)), 2)
            docs[i] = A.merge(docs[i], docs[j])

        final = docs[0]
        for d in docs[1:]:
            final = A.merge(final, d)
        expected = str(final['text'])

        seq_ops, seen_actors = self._device_ops_from_doc(final)
        enc = SeqEncoder(seen_actors)
        batch = enc.batch([seq_ops])
        state = SeqState.empty(1, max(64, len(seq_ops) + 1))
        state, _ = apply_seq_batch(state, batch)
        assert visible_text(state) == [expected]
        # every shape in this trace (incl. concurrent overwrites/deletes)
        # must resolve exactly on device — no mirror fallback
        assert not bool(np.asarray(state.inexact)[0])


class TestLongDocSharding:
    """Slot-axis sharding for very long documents (sequence/context
    parallelism): sharded apply + materialize must equal the single-device
    path bit-for-bit."""

    def _build_long_doc(self, length, seed=0):
        import numpy as np
        from automerge_tpu.fleet.sequence import (
            INSERT, SET, DEL, SeqOpBatch, SeqState, apply_seq_batch)
        from automerge_tpu.fleet.tensor_doc import ACTOR_BITS
        rng = np.random.default_rng(seed)
        kind = np.full((1, length), INSERT, dtype=np.int32)
        value = rng.integers(97, 123, (1, length), dtype=np.int32)
        actor = rng.integers(0, 3, (1, length), dtype=np.int32)
        ctr = 2 + np.arange(length, dtype=np.int32)
        packed = ((ctr[None, :] << ACTOR_BITS) | actor).astype(np.int32)
        ref = np.zeros((1, length), dtype=np.int32)
        for i in range(1, length):
            j = int(rng.integers(0, i))
            ref[0, i] = packed[0, j]
        batch = SeqOpBatch(kind, ref, packed, value)
        state = SeqState.empty(1, length + 61)  # odd capacity: uneven shards
        state, applied = apply_seq_batch(state, batch)
        assert int(applied) == length
        return state, packed

    def test_sharded_matches_local(self):
        import jax
        import numpy as np
        from automerge_tpu.fleet.sequence import (
            DEL, SET, SeqOpBatch, apply_seq_batch, materialize, visible_text)
        from automerge_tpu.fleet.sharding import (
            fleet_mesh, shard_long_seq, sharded_long_seq_apply,
            sharded_long_seq_materialize)
        state, packed = self._build_long_doc(500)
        mesh = fleet_mesh(jax.devices()[:8], keys_axis=2)
        sharded = shard_long_seq(state, mesh)

        # More edits through the sharded apply vs the local apply
        extra = SeqOpBatch(
            np.array([[SET, DEL]], dtype=np.int32),
            np.array([[int(packed[0, 10]), int(packed[0, 20])]],
                     dtype=np.int32),
            np.array([[(600 << 8) | 0, (601 << 8) | 1]], dtype=np.int32),
            np.array([[90, 0]], dtype=np.int32))
        local, _ = apply_seq_batch(state, extra)
        sharded, _ = sharded_long_seq_apply(mesh)(sharded, extra)

        lv, _lc, lvis, ln = jax.device_get(materialize(local))
        sv, _sc, svis, sn = jax.device_get(
            sharded_long_seq_materialize(mesh)(sharded))
        # The sharded state may be tail-padded to a device-count multiple;
        # padded slots are unallocated, so the real prefix must match exactly
        np.testing.assert_array_equal(lv, sv[:, :lv.shape[1]])
        np.testing.assert_array_equal(lvis, svis[:, :lvis.shape[1]])
        assert not svis[:, lvis.shape[1]:].any()
        assert visible_text(local) == visible_text(sharded)


class TestCounterSumOverflow:
    """Round-4 advisor finding: the INC kernel's (sum << 2) bit-packed
    counter lane must flag the row inexact when the ACCUMULATED sum leaves
    the +/-2^29 envelope — each delta passes the ingest guards, but two
    +2^28 incs would wrap the packed int32 silently, diverging live-applied
    replicas from bulk-loaded ones (loader.py's counter_over rule)."""

    def _inc_trace(self, deltas):
        ops = [ins('_head', f'2@{A1}', 'a')]
        for i, d in enumerate(deltas):
            ops.append({'kind': 'inc', 'ref': f'2@{A1}', 'id': f'{3 + i}@{A1}',
                        'value': d, 'pred': [f'2@{A1}']})
        return run_ops([ops], [A1], capacity=8)

    def test_in_envelope_sum_stays_exact(self):
        state = self._inc_trace([(1 << 28), (1 << 28) - 1])
        assert not bool(np.asarray(state.inexact)[0])
        # accumulated value reads back exactly
        from automerge_tpu.fleet.sequence import element_visibility
        _, _, _, cnt = element_visibility(state)
        sums = np.asarray(cnt) >> 2
        assert (1 << 29) - 1 in sums[0]

    def test_overflowing_sum_flags_inexact(self):
        state = self._inc_trace([(1 << 28), (1 << 28)])
        assert bool(np.asarray(state.inexact)[0])

    def test_negative_overflow_flags_inexact(self):
        state = self._inc_trace([-(1 << 28), -(1 << 28)])
        assert bool(np.asarray(state.inexact)[0])
