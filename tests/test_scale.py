"""Fleet-scale smoke: hundreds of documents through the batched public
surface in one process — capacity growth, actor-table renumbering, turbo
ingest, the batched sync driver, bulk load, and whole-fleet readback all
interact at a size the per-feature suites (doc_capacity 2-8) never reach.
Shapes stay small enough for the CI budget; BENCH-scale runs live in
bench.py."""

import numpy as np
import pytest

from automerge_tpu import native
from automerge_tpu import backend as host_backend
from automerge_tpu.backend import init_sync_state
from automerge_tpu.columnar import encode_change, decode_change_meta
from automerge_tpu.fleet import backend as fleet_backend
from automerge_tpu.fleet.backend import DocFleet, materialize_docs
from automerge_tpu.fleet.loader import load_docs
from automerge_tpu.fleet.sync_driver import generate_sync_messages_docs

N_DOCS = 512


@pytest.mark.skipif(not native.available(),
                    reason='native codec unavailable')
def test_fleet_of_512_docs_end_to_end():
    rng = np.random.default_rng(11)
    # Actors arrive in descending hex order so later batches force live
    # actor-table renumbering over grown device state
    actors = [f'{0xf0 - d // 64:02x}' * 16 for d in range(N_DOCS)]

    # Start small: capacity must grow doc axis (4 -> 512) and key axis
    fleet = DocFleet(doc_capacity=4, key_capacity=4)
    handles = fleet_backend.init_docs(N_DOCS, fleet)

    def chain(d, n_changes, start_seq=1, heads=(), start_op=1):
        out, hs = [], list(heads)
        for c in range(n_changes):
            buf = encode_change({
                'actor': actors[d], 'seq': start_seq + c,
                'startOp': start_op + c, 'time': 0, 'message': '',
                'deps': hs,
                'ops': [{'action': 'set', 'obj': '_root',
                         'key': f'k{int(rng.integers(0, 24))}',
                         'value': int(rng.integers(0, 1 << 20)),
                         'datatype': 'int', 'pred': []}]})
            hs = [decode_change_meta(buf, True)['hash']]
            out.append(buf)
        return out, hs

    # Wave 1: turbo across the whole fleet
    per_doc, heads = [], []
    for d in range(N_DOCS):
        chg, hs = chain(d, 4)
        per_doc.append(chg)
        heads.append(hs)
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc,
                                                  mirror=False)
    assert fleet.metrics.turbo_calls == 1
    assert fleet.metrics.fallbacks == 0

    # Wave 2: more changes per doc (exercises grown state + deferred graph)
    per_doc2 = []
    for d in range(N_DOCS):
        chg, _ = chain(d, 3, start_seq=5, heads=heads[d], start_op=5)
        per_doc2.append(chg)
    handles, _ = fleet_backend.apply_changes_docs(handles, per_doc2,
                                                  mirror=False)
    assert all(h['state'].is_fleet for h in handles)
    assert fleet.metrics.promotions == 0

    # Whole-fleet readback in one transfer; spot-check against the host
    mats = materialize_docs(handles)
    assert len(mats) == N_DOCS
    for d in (0, N_DOCS // 2, N_DOCS - 1):
        hb = host_backend.init()
        hb, _ = host_backend.apply_changes(hb, per_doc[d] + per_doc2[d])
        host_view = {k: v['value'] for k, v in
                     host_backend.get_patch(hb)['diffs']['props'].items()
                     for v in [max(v.values(),
                                   key=lambda x: x.get('value', 0))]}
        assert set(mats[d]) == set(
            host_backend.get_patch(hb)['diffs']['props'])
        assert bytes(fleet_backend.save(handles[d])) == \
            bytes(host_backend.save(hb))

    # Batched sync generate round over the whole fleet
    states = [init_sync_state() for _ in handles]
    _, messages = generate_sync_messages_docs(handles, states)
    assert sum(m is not None for m in messages) == N_DOCS

    # Bulk-load every save into a fresh fleet; reads must match
    saves = [bytes(fleet_backend.save(h)) for h in handles]
    fresh = DocFleet(doc_capacity=8, key_capacity=8)
    loaded = load_docs(saves, fresh)
    assert fresh.metrics.docs_bulk_loaded == N_DOCS
    assert materialize_docs(loaded) == mats
