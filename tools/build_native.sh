#!/bin/sh
# Build the native codec (automerge_tpu/native/codec.cpp) into the cached
# shared object the ctypes wrapper loads. The wrapper normally compiles on
# demand (automerge_tpu/native/__init__.py:_build) — this script is the
# same recipe for CI images, cross-builds, and for recovering from a
# stale-.so NativeAbiMismatch failure at import.
#
# Flags that matter:
#   -pthread   the codec runs a persistent worker pool (NativePool); a
#              build without it deadlocks or crashes on first parallel
#              parse instead of failing cleanly
#   -I<python> optional: CPython headers enable the zero-copy list entry
#              (am_ingest_changes_list); the codec builds without them
#
# Sanitizer plane:
#   --sanitize=address,undefined   builds a SEPARATE artifact
#       _codec_<cache_tag>_san.<sanitizers>.so at -O1 -g with the given
#       -fsanitize= list. The normal .so is untouched; point the loader
#       at the sanitized build explicitly with
#       AUTOMERGE_TPU_NATIVE_SO=<path> (plus LD_PRELOAD of libasan when
#       ASan is in the list — the host python is not ASan-linked).
#       tools/native_sanitize_replay.py replays the fuzz corpus under it.
#
# The binary carries an ABI stamp (am_abi_version, checked against
# native.__init__._ABI_VERSION at import): a stale .so fails LOUDLY
# instead of silently running an old single-threaded codec. After editing
# codec.cpp's C surface, bump BOTH stamps. The sanitized build compiles
# from the same source, so it carries the same stamp — the loader's ABI
# check applies to it unchanged.
set -eu

here="$(cd "$(dirname "$0")/.." && pwd)"
src="$here/automerge_tpu/native/codec.cpp"
python_bin="${PYTHON:-python3}"

sanitize=""
for arg in "$@"; do
    case "$arg" in
        --sanitize=*) sanitize="${arg#--sanitize=}" ;;
        --sanitize) sanitize="address,undefined" ;;
        *) echo "usage: $0 [--sanitize[=address,undefined]]" >&2; exit 2 ;;
    esac
done

cache_tag="$("$python_bin" -c 'import sys; print(sys.implementation.cache_tag)')"

inc="$("$python_bin" -c 'import sysconfig; print(sysconfig.get_paths().get("include") or "")')"
inc_flag=""
if [ -n "$inc" ] && [ -e "$inc/Python.h" ]; then
    inc_flag="-I$inc"
fi

if [ -n "$sanitize" ]; then
    # separate artifact name so the sanitized build can never shadow the
    # fast .so the on-demand loader picks up
    suffix="$(printf '%s' "$sanitize" | tr ',' '-')"
    out="$here/automerge_tpu/native/_codec_${cache_tag}_san.${suffix}.so"
    rm -f "$out"   # glibc dlopen dedups by inode: never rebuild in place
    # shellcheck disable=SC2086
    g++ -O1 -g -fno-omit-frame-pointer "-fsanitize=$sanitize" \
        -shared -fPIC -std=c++17 -pthread $inc_flag "$src" -lz -o "$out"
    echo "built sanitized codec: $out"
    echo "replay the fuzz corpus under it with:"
    echo "  $python_bin $here/tools/native_sanitize_replay.py --so $out"
    exit 0
fi

out="$here/automerge_tpu/native/_codec_${cache_tag}.so"

# shellcheck disable=SC2086  # inc_flag is intentionally word-split
g++ -O3 -shared -fPIC -std=c++17 -pthread $inc_flag "$src" -lz -o "$out"

"$python_bin" - <<EOF
import sys
sys.path.insert(0, "$here")
from automerge_tpu import native
assert native.available(), 'built but failed to load'
assert native._abi_of(native._load()) == native._ABI_VERSION, 'ABI stamp skew'
print('built', "$out", 'ABI', native._ABI_VERSION,
      'threads', native.native_threads())
EOF
